#!/usr/bin/env python3
"""Nightly chaos sweep: run the deterministic chaos suite across N seeds.

Each seed re-pins every fault schedule and retry-jitter walk in the suite
(``tests/test_chaos.py`` reads ``ASYNC_CHAOS_SEED``), so a sweep covers N
*distinct* deterministic fault interleavings -- any seed that fails is a
one-command repro:

    ASYNC_CHAOS_SEED=<seed> pytest -m chaos tests/test_chaos.py

Usage:
    bin/chaos_sweep.py                  # 5 seeds, chaos suite only
    bin/chaos_sweep.py -n 20 --base-seed 100
    bin/chaos_sweep.py --soak           # also the kill -9 soak tests
    bin/chaos_sweep.py -k saga          # filter tests per pytest -k

Prints a per-seed pass/fail table; exits non-zero iff any seed failed.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def slo_sanity(seed: int) -> str:
    """Per-seed SLO-engine sanity (ISSUE 7): drive the conf rule set
    through a seeded outage -- healthy window, violation long enough to
    burn into firing, then recovery -- and assert no rule stays wedged
    firing after the series recovers.  Deterministic per seed (the noise
    walk is seeded); returns "" on pass, the failure reason otherwise."""
    import random

    sys.path.insert(0, REPO)
    from asyncframework_tpu.metrics.slo import (
        FIRING,
        OK,
        SLOEngine,
        parse_rules,
    )
    from asyncframework_tpu.metrics.timeseries import TimeSeriesStore
    from asyncframework_tpu.utils.clock import ManualClock

    rng = random.Random(seed)
    clk = ManualClock()
    store = TimeSeriesStore(capacity=512, clock=clk)
    rules = parse_rules(
        "lag: p95(serving.freshness_lag_ms) < 2000 over 15s for 2s"
    )
    eng = SLOEngine(rules, store=store, now_fn=lambda: clk.now_ms() / 1e3)

    def tick(value: float, n: int) -> None:
        for _ in range(n):
            clk.advance(1000)
            store.record("serving.freshness_lag_ms",
                         value * (1.0 + rng.uniform(-0.05, 0.05)))
            eng.evaluate()

    tick(100.0, 20)     # healthy
    state0 = eng.evaluate()["lag"]["state"]
    if state0 != OK:
        return f"healthy window evaluated {state0!r}, want ok"
    tick(10_000.0, 20)  # outage: violated >> burn duration
    state1 = eng.evaluate()["lag"]["state"]
    if state1 != FIRING:
        return f"sustained violation evaluated {state1!r}, want firing"
    tick(100.0, 30)     # recovery: the whole 15 s window drains
    view = eng.evaluate()["lag"]
    if view["state"] != OK:
        return (f"rule wedged {view['state']!r} after recovery "
                f"(value={view['value']})")
    if not view["fired"] or not view["recovered"]:
        return f"transition counts wrong: {view}"
    return ""


def lockorder_sanity(seed: int) -> str:
    """Per-seed lock-order-detector arming check (ISSUE 10): drive two
    threads through a seeded reversed acquisition (A->B in one, B->A in
    the other) and assert the detector reports exactly that cycle --
    proving the machinery every suite in this seed leans on (the
    test_chaos teardown assert_no_cycles gate) is actually live.
    Deterministic per seed (the interleaving is join-serialized; the
    seed only varies lock names).  Returns "" on pass."""
    import threading

    sys.path.insert(0, REPO)
    from asyncframework_tpu.net import lockwatch

    a, b = f"sweep.a{seed}", f"sweep.b{seed}"
    lockwatch.reset_totals()
    # snapshot after the fold: a real cycle some earlier run left in
    # this process survives the restore below
    prior_history = lockwatch.cycle_history()
    lockwatch.enable(True)
    try:
        la, lb = lockwatch.WatchedLock(a), lockwatch.WatchedLock(b)

        def fwd():
            with la:
                with lb:
                    pass

        def rev():
            with lb:
                with la:
                    pass

        for fn, name in ((fwd, "sweep-fwd"), (rev, "sweep-rev")):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            t.join(timeout=10.0)
        cycles = lockwatch.lock_order_cycles()
        if len(cycles) != 1 or a not in cycles[0] or b not in cycles[0]:
            return (f"reversed acquisition yielded cycles={cycles!r}, "
                    f"want exactly one through {a}/{b}")
        try:
            lockwatch.assert_no_cycles()
            return "assert_no_cycles did not raise on a known cycle"
        except AssertionError:
            pass
        return ""
    finally:
        lockwatch.enable(False)
        lockwatch.reset_totals()
        # this sanity check creates its cycle deliberately -- restore
        # the prior history (dropping only OUR cycle) so any REAL cycle
        # recorded earlier still reaches a session-wide gate
        lockwatch.set_cycle_history(prior_history)


def controller_sanity(seed: int) -> str:
    """Per-seed adaptive-controller sanity (ISSUE 15): drive an
    AsyncController on a ManualClock through a seeded straggler phase, a
    steady phase, and an adversarial oscillating signal, and assert (1)
    the cohort drops under straggler spread but NEVER below its declared
    floor, (2) on the steady cluster the knob-change rate falls below
    the ``controller_converged`` SLO threshold within its burn window,
    and (3) the oscillation guard trips (and freezes the knob) on the
    flapping signal.  Deterministic per seed; returns "" on pass."""
    import random

    sys.path.insert(0, REPO)
    from asyncframework_tpu.conf import AsyncConf, set_global_conf
    from asyncframework_tpu.metrics.slo import OK, SLOEngine, parse_rules
    from asyncframework_tpu.metrics.timeseries import TimeSeriesStore
    from asyncframework_tpu.parallel import controller as ctrl_mod
    from asyncframework_tpu.utils.clock import ManualClock
    from tests.test_controller import FakePS

    rng = random.Random(seed)
    set_global_conf(AsyncConf())
    ctrl_mod.reset_control_totals()
    clk = ManualClock()
    ps = FakePS(num_workers=8, bucket_ratio=1.0)
    ctl = ctrl_mod.AsyncController(ps, conf=AsyncConf(),
                                   now_fn=lambda: clk.now_ms() / 1e3)
    try:
        store = TimeSeriesStore(capacity=512, clock=clk)
        eng = SLOEngine(parse_rules(
            "controller_converged: rate(control.changes) < 0.5 "
            "over 20s for 5s"), store=store,
            now_fn=lambda: clk.now_ms() / 1e3)

        def run(n, stats_fn):
            for _ in range(n):
                clk.advance(1000)
                ps.wstats = stats_fn()
                ctl.tick()
                store.record("control.changes",
                             float(ctrl_mod.control_totals()["changes"]))
                eng.evaluate()

        def steady():
            return {str(w): {"accepted": 50, "interval_ms":
                             10.0 * (1 + rng.uniform(-0.05, 0.05))}
                    for w in range(8)}

        def straggler():
            st = steady()
            st["3"]["interval_ms"] = 200.0  # one DELAYed worker
            return st

        run(10, straggler)
        b_low = ctl.status()["knobs"]["b"]["value"]
        if not (1 <= b_low < 8):
            return f"straggler phase left b={b_low}, want < conf 8"
        floor = ctl._bounds["async.bucket.ratio"][0] * 8
        if b_low < max(1, floor):
            return f"b={b_low} actuated below declared floor {floor}"
        run(30, steady)
        view = eng.evaluate()["controller_converged"]
        if view["state"] != OK:
            return (f"controller_converged={view['state']!r} on a "
                    f"steady cluster (value={view['value']})")
        # adversarial flapping: alternate straggler on/off every tick
        # faster than the cooldown can settle -- the guard must trip
        flip = [False]

        def flapping():
            flip[0] = not flip[0]
            return straggler() if flip[0] else steady()

        before = ctrl_mod.control_totals()["osc_trips"]
        # cooldown is 2s; tick every 3s so changes are admitted and the
        # reversals accumulate
        for _ in range(20):
            clk.advance(3000)
            ps.wstats = flapping()
            ctl.tick()
        if ctrl_mod.control_totals()["osc_trips"] <= before:
            return "flapping signal never tripped the oscillation guard"
        return ""
    finally:
        ctrl_mod.reset_control_totals()
        set_global_conf(None)


def run_seed(seed: int, args) -> dict:
    env = dict(os.environ)
    env["ASYNC_CHAOS_SEED"] = str(seed)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if getattr(args, "net_profile", "none") != "none":
        # net-profile preset (net/faults.py wan_profile_schedule): suites
        # that OPT IN (tests/test_fencing.py today) merge the profile's
        # delay/jitter/loss events into their schedules via
        # profile_schedule_from_env + merge_schedules.  Byte-identical-
        # replay suites (test_chaos.py, test_dataplane.py paths) stay on
        # their exact schedules by design -- a merged profile would
        # break the very determinism they assert.
        env["ASYNC_CHAOS_NET_PROFILE"] = args.net_profile
    # debug lock watchdog on for every sweep seed: any socket send/recv
    # under the PS model lock fails the seed loudly (the lock-free PULL
    # serving claim is re-checked on every fault interleaving)
    env.setdefault("ASYNCTPU_ASYNC_DEBUG_LOCKWATCH", "1")
    marker = "chaos or soak" if args.soak else "chaos"
    # the serving scenario rides every sweep seed: seeded SUBSCRIBE/
    # PREDICT fault schedules (torn-model and failover invariants) are
    # part of the chaos surface now that a read path exists
    # telemetry-plane chaos rides every seed too: /metrics + /api/status
    # availability/validity under the fault schedule (tests/test_telemetry)
    # shard-group chaos rides every seed: kill -9 one PS shard of 3 mid-run
    # (real OS processes), recovery from the durable checkpoint, exactly-
    # once across the restart (tests/test_shardgroup.py, seeded kill timing)
    # partition/fencing chaos rides every seed too: partition (not kill) a
    # shard past lease expiry, epoch-fenced relaunch, stale-epoch pushes
    # REJECT_FENCED, run completes (tests/test_fencing.py, seeded timing)
    # relay-tree chaos rides every seed: seeded SIGKILL of an interior
    # relay node mid-distribution -- children re-home to the root within
    # the suspicion window, CRC + fence assert no torn/stale-epoch model
    # ever serves (tests/test_relaycast.py, seeded kill timing)
    # hot-standby replication chaos rides every seed: SIGKILL (seeded
    # timing) and PARTITION of a shard primary with a warm standby --
    # promotion instead of restart, zombie stream appends REJECT_FENCED,
    # exactly-once across the failover (tests/test_replication.py)
    # flight-recorder harvest rides every seed too: a worker child is
    # SIGKILLed mid-run (seeded timing) and the collector must harvest
    # a dump whose last events straddle the kill and whose push ledger
    # matches the PS-side accepted_by_wid view (tests/test_observer.py)
    # adaptive-controller chaos rides every seed: the wan/DELAY
    # acceptance (controller-on run with an injected straggler converges
    # without hand-tuning, decisions recorded, exactly-once + fencing
    # hold across a mid-run promotion) plus the decision-logic units
    # (tests/test_controller.py)
    # continuous-profiling crash path rides every seed: a profiling-
    # enabled worker child is SIGKILLed mid-run (seeded timing) and its
    # harvested flight dump must carry a non-empty profile snapshot
    # with the wire zones attributed (tests/test_profiler.py)
    # native data plane rides every seed: bit-identity of the native
    # wire codecs, the SHM_OPEN upgrade round-trip, and the shm-ring
    # kill -9 rider -- a SIGKILLed ring peer must degrade the survivor
    # with ConnectionError inside the liveness window, never a hang
    # (tests/test_wire_native.py)
    cmd = [
        sys.executable, "-m", "pytest", "tests/test_chaos.py",
        "tests/test_net_retry.py", "tests/test_serving.py",
        "tests/test_telemetry.py", "tests/test_shardgroup.py",
        "tests/test_fencing.py", "tests/test_relaycast.py",
        "tests/test_replication.py", "tests/test_observer.py",
        "tests/test_controller.py", "tests/test_profiler.py",
        "tests/test_wire_native.py",
        "-q", "-m",
        f"({marker}) or serve or telemetry or shard or fence or relay"
        f" or repl or observer or ctrl or prof or native",
        "-p", "no:cacheprovider",
    ]
    if args.soak:
        cmd.insert(cmd.index("-q"), "tests/test_deploy_soak.py")
        cmd.insert(cmd.index("-q"), "tests/test_ps_dcn.py")
    if args.keyword:
        cmd += ["-k", args.keyword]
    t0 = time.monotonic()
    proc = subprocess.run(
        cmd, cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        timeout=args.timeout,
    )
    elapsed = time.monotonic() - t0
    tail = proc.stdout.strip().splitlines()
    summary = tail[-1] if tail else ""
    ok = proc.returncode == 0
    # SLO-engine sanity each seed: no rule may stay wedged firing after
    # recovery completes (deterministic, seeded; one-line repro below)
    slo_err = slo_sanity(seed)
    if slo_err:
        ok = False
        summary = f"SLO sanity: {slo_err} | {summary}"
    # lock-order detector armed + self-checked each seed: the chaos
    # suites' teardown gate (lockwatch.assert_no_cycles) fails any seed
    # whose interleaving produced a real acquisition-order cycle; this
    # proves the detector itself catches a known reversed acquisition
    lock_err = lockorder_sanity(seed)
    if lock_err:
        ok = False
        summary = f"lock-order sanity: {lock_err} | {summary}"
    # adaptive-controller sanity each seed: the cohort never actuates
    # below its declared floor, the controller_converged SLO passes on a
    # steady cluster, and the oscillation guard trips on a flapping
    # signal (deterministic, seeded)
    ctrl_err = controller_sanity(seed)
    if ctrl_err:
        ok = False
        summary = f"controller sanity: {ctrl_err} | {summary}"
    return {
        "seed": seed,
        "ok": ok,
        "elapsed_s": elapsed,
        "summary": summary,
        "output": proc.stdout,
    }


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Run the chaos suite across N seeds; per-seed table."
    )
    ap.add_argument("-n", "--seeds", type=int, default=5,
                    help="number of seeds to sweep (default 5)")
    ap.add_argument("--base-seed", type=int, default=7,
                    help="first seed (default 7, the suite's default)")
    ap.add_argument("--soak", action="store_true",
                    help="include the slow kill -9 soak tests")
    ap.add_argument("-k", dest="keyword", default=None,
                    help="pytest -k expression forwarded to each run")
    ap.add_argument("--net-profile", choices=["none", "wan"],
                    default="none",
                    help="overlay a net profile on the schedules of "
                         "suites that opt in (the fencing/partition "
                         "suite today; exact-replay suites keep their "
                         "pinned schedules): 'wan' = 15ms+jitter per op "
                         "plus seeded reply drops / mid-frame cuts "
                         "(net/faults.py wan_profile_schedule)")
    ap.add_argument("--timeout", type=float, default=1800.0,
                    help="per-seed timeout in seconds (default 1800)")
    ap.add_argument("--show-failures", action="store_true",
                    help="dump full pytest output of failing seeds")
    args = ap.parse_args()

    results = []
    for i in range(args.seeds):
        seed = args.base_seed + i
        print(f"[chaos-sweep] seed {seed} ...", flush=True)
        try:
            results.append(run_seed(seed, args))
        except subprocess.TimeoutExpired:
            results.append({
                "seed": seed, "ok": False, "elapsed_s": args.timeout,
                "summary": "TIMEOUT", "output": "",
            })

    width = max(len(r["summary"]) for r in results) if results else 0
    print()
    print(f"{'seed':>6}  {'result':6}  {'time':>8}  summary")
    print("-" * (26 + width))
    for r in results:
        status = "PASS" if r["ok"] else "FAIL"
        print(f"{r['seed']:>6}  {status:6}  {r['elapsed_s']:7.1f}s  "
              f"{r['summary']}")
    failed = [r for r in results if not r["ok"]]
    print("-" * (26 + width))
    print(f"[chaos-sweep] {len(results) - len(failed)}/{len(results)} "
          f"seeds passed")
    if failed:
        print("repro: ASYNC_CHAOS_SEED=<seed> pytest -m chaos "
              "tests/test_chaos.py")
        if args.show_failures:
            for r in failed:
                print(f"\n===== seed {r['seed']} output =====\n{r['output']}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
