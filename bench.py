#!/usr/bin/env python
"""Benchmark: ASGD wall-clock to target objective on an epsilon-shaped problem.

Metric of record (BASELINE.md): wall-clock to target loss, asynchronous SGD.
The reference repo publishes recipes but no absolute numbers (its figures live
in the IPDPS 2020 paper, arXiv:1907.08526).  BASELINE_S is derived from the
reference's own recipe (derivation recorded in BASELINE.md section "Derived
baseline"): the epsilon ASGD recipe runs 320k gradient updates to reach its
target band (README.md:64); Spark's driver-mediated per-task path (launch RPC
+ result serde + scheduling) has a widely measured floor of ~5 ms/task, and 8
workers pipeline it, giving >= 320000 x 5ms / 8 = 200 s as a lower bound for
the 8-worker cluster.  BASELINE_S = 120 s is kept BELOW that derived bound
(i.e. generous to the reference) and fixed so rounds are comparable.

Workload: epsilon-shaped planted least squares (400k x 2000 dense f32,
generated directly in device HBM -- this container's host<->device link is a
high-latency tunnel, and shipping 3.2 GB through it would benchmark the
tunnel, not the framework).  Target: reduce the mean objective to 0.1% of
its initial value (~2,500-4,000 accepted updates at the tuned step size) --
deep enough that steady-state update throughput, not the dispatch ramp,
decides wall-clock, yet a decade above the planted noise floor (~1e-4 of
initial, measured), so the target is always reachable.

The run exercises the REAL framework hot path: executor threads, result
queue, tau filter, partial barrier, versioned model handles, on-device updates
-- 8 logical workers on however many chips are attached (1 in this harness).

Output: ONE json line {"metric", "value", "unit", "vs_baseline"};
vs_baseline > 1 means faster than the reference estimate.
"""

import faulthandler
import json
import os
import sys
import threading
import time
import traceback

import numpy as np

sys.path.insert(0, ".")

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.ops import steps
from asyncframework_tpu.solvers import ASGD, SolverConfig

# BENCH_N/BENCH_D env overrides let the full flow be validated on a small
# CPU problem; the driver's TPU run uses the defaults
N = int(os.environ.get("BENCH_N", 400_000))
D = int(os.environ.get("BENCH_D", 2_000))
NUM_WORKERS = 8
BASELINE_S = 120.0  # below the 200 s recipe-derived lower bound; BASELINE.md
SPARK_TASK_FLOOR_S = 0.005  # per-gradient driver-mediated floor (BASELINE.md)
TARGET_FRACTION = 0.001
BACKEND_INIT_BUDGET_S = 360.0  # total retry budget for flaky TPU backend init
RUN_TIMEOUT_S = 240.0          # solver-internal deadline
WATCHDOG_S = 600.0             # hard kill: a dead device link can block a
                               # device op forever (threads stuck in C code)


def arm_watchdog() -> None:
    """Emit a parseable failure line and hard-exit if the process wedges
    (e.g. the host<->TPU tunnel dies mid-run and block_until_ready never
    returns -- observed in round 2).  ``os._exit`` on purpose: stuck C calls
    do not honor normal interpreter shutdown."""
    faulthandler.dump_traceback_later(WATCHDOG_S - 30, file=sys.stderr)

    def fire():
        emit(0.0, "s (WATCHDOG: process wedged past "
             f"{WATCHDOG_S:.0f}s; see stderr traceback)", 0.0)
        sys.stdout.flush()
        os._exit(0)

    t = threading.Timer(WATCHDOG_S, fire)
    t.daemon = True
    t.start()


def emit(value: float, unit: str, vs_baseline: float) -> None:
    print(json.dumps({
        "metric": "asgd_epsilon_time_to_target",
        "value": value,
        "unit": unit,
        "vs_baseline": vs_baseline,
    }))


def init_devices():
    """jax.devices() with retry/backoff: one flaky TPU backend init must not
    erase the round's perf evidence (BENCH_r01 died exactly this way).

    BENCH_PLATFORM=cpu forces the CPU backend through the config API (env
    vars alone cannot: the image's sitecustomize latches the TPU plugin
    first) -- used with BENCH_N/BENCH_D to validate the whole flow off-TPU.
    """
    import jax

    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    deadline = time.monotonic() + BACKEND_INIT_BUDGET_S
    delay = 5.0
    attempt = 0
    while True:
        attempt += 1
        try:
            devices = jax.devices()
            print(f"# backend up on attempt {attempt}: "
                  f"{[d.platform for d in devices]}", file=sys.stderr)
            return devices
        except Exception as e:  # backend init raises RuntimeError/JaxRuntimeError
            remaining = deadline - time.monotonic()
            print(f"# backend init attempt {attempt} failed: {e!r}; "
                  f"{remaining:.0f}s budget left", file=sys.stderr)
            if remaining <= 0:
                raise
            # jax caches the failed-backend error; clear it so the next
            # attempt actually re-initializes the plugin
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                try:
                    jax.clear_backends()
                except Exception:
                    pass
            time.sleep(min(delay, max(remaining, 0)))
            delay = min(delay * 2, 60.0)


def main() -> None:
    devices = init_devices()
    import jax
    t0 = time.monotonic()
    ds = ShardedDataset.generate_on_device(
        N, D, NUM_WORKERS, devices=devices, seed=7, noise=0.01
    )
    for w in range(NUM_WORKERS):
        ds.shard(w).y.block_until_ready()
    gen_s = time.monotonic() - t0
    print(f"# data: {N}x{D} generated on device in {gen_s:.1f}s", file=sys.stderr)

    # gamma is tuned to the problem's conditioning: rows are N(0, I/d), so
    # the covariance is I/d and per-update contraction is ~gamma/d -- the
    # measured updates-to-1%-target is ~300 at gamma=100 (gamma=6 cannot
    # reach the target in any feasible budget).  Each side of a
    # wall-clock-to-target comparison runs its own best recipe, as in the
    # paper's figures.
    cfg = SolverConfig(
        num_workers=NUM_WORKERS,
        num_iterations=5_000,
        gamma=100.0,
        taw=2**31 - 1,
        batch_rate=0.1,
        bucket_ratio=0.7,
        printer_freq=25,
        coeff=0.0,
        seed=42,
        calibration_iters=100,
        run_timeout_s=RUN_TIMEOUT_S,
    )
    solver = ASGD(ds, None, cfg, devices=devices)

    # warm the XLA compile caches outside the timed region (the reference's
    # first blocking iteration plays the same role for Spark's caches)
    shard = ds.shard(0)
    key = jax.random.PRNGKey(0)
    g, _ = solver._step(shard.X, shard.y, jax.device_put(
        np.zeros(D, np.float32), devices[0]), key)
    solver._apply(
        jax.device_put(np.zeros(D, np.float32), devices[0]),
        jax.device_put(g, devices[0]),
        jax.device_put(np.float32(0), devices[0]),
    )
    print("# compile warm-up done", file=sys.stderr)

    # dispatch round-trip diagnostic: on a tunneled/remote device the
    # per-dispatch RTT, not the framework, bounds updates/sec -- record it
    # so the headline number can be read in context
    probe = jax.device_put(np.zeros(8, np.float32), devices[0])
    t0 = time.monotonic()
    for _ in range(20):
        probe = (probe + 1.0).block_until_ready()
    rtt_ms = (time.monotonic() - t0) / 20 * 1e3
    print(f"# device dispatch round-trip ~{rtt_ms:.2f} ms "
          f"(bounds updates/sec at ~{8 / max(rtt_ms, 1e-3) * 1e3:.0f}/s)",
          file=sys.stderr)

    res = solver.run()

    # wall-clock to target from the evaluated trajectory
    initial = res.trajectory[0][1]
    target = initial * TARGET_FRACTION
    t_hit = None
    k_hit = None
    for i, (t_ms, obj) in enumerate(res.trajectory):
        if obj <= target:
            t_hit = t_ms / 1e3
            # snapshot i covers ~i * printer_freq accepted updates
            k_hit = max(i * cfg.printer_freq, 1)
            break
    print(
        f"# accepted={res.accepted} dropped={res.dropped} rounds={res.rounds} "
        f"updates/s={res.updates_per_sec:.0f} max_staleness={res.max_staleness} "
        f"elapsed={res.elapsed_s:.1f}s obj {initial:.4f}->{res.trajectory[-1][1]:.6f} "
        f"target={target:.6f} t_hit={t_hit}",
        file=sys.stderr,
    )
    if t_hit is None:
        # did not reach target: report elapsed as value with penalty ratio
        emit(round(res.elapsed_s, 2), "s (TARGET NOT REACHED)", 0.0)
        return
    # EQUAL-RECIPE baseline: the reference running this same recipe (same
    # update count) pays at least SPARK_TASK_FLOOR_S per gradient across 8
    # pipelined workers (BASELINE.md "Derived baseline") -- comparing
    # against the fixed 320k-iteration recipe would credit step-size tuning
    # to the framework.  Also floor the baseline at the recipe-independent
    # BASELINE_S when OUR update count exceeds the reference recipe's.
    # per-gradient cost for the reference at THIS recipe = scheduling floor
    # + gradient compute: 2 * par_recs * d flops on a 2-core executor at an
    # optimistic 6 GFLOP/s (BASELINE.md "Derived baseline")
    par_recs = cfg.batch_rate * N / NUM_WORKERS
    spark_compute_s = 2.0 * par_recs * D / 6e9
    per_grad_s = SPARK_TASK_FLOOR_S + spark_compute_s
    equal_recipe_baseline = k_hit * per_grad_s / NUM_WORKERS
    baseline = min(max(equal_recipe_baseline, 1e-3), BASELINE_S)
    print(
        f"# k_hit={k_hit} spark_per_grad={per_grad_s * 1e3:.1f}ms "
        f"equal-recipe baseline={equal_recipe_baseline:.3f}s",
        file=sys.stderr,
    )
    emit(round(t_hit, 2), "s", round(baseline / t_hit, 2))


if __name__ == "__main__":
    arm_watchdog()
    try:
        main()
    except Exception as e:
        # Persistent failure: still produce ONE parseable JSON line so the
        # round records a diagnosable result instead of a bare traceback.
        traceback.print_exc(file=sys.stderr)
        emit(0.0, f"s (FAILED: {type(e).__name__}: {str(e)[:200]})", 0.0)
        sys.exit(0)
