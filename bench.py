#!/usr/bin/env python
"""Benchmark: ASGD wall-clock to target objective on the reference's three
dataset shapes -- epsilon (400k x 2000 dense f32), mnist8m (8.1M x 784 dense
bf16), rcv1 (~700k x 47,236 sparse) -- with fresh-process medians.

Metric of record (BASELINE.md): wall-clock to target loss, asynchronous SGD.
The reference repo publishes recipes but no absolute numbers (its figures
live in the IPDPS 2020 paper, arXiv:1907.08526); the per-config baseline is
derived from the reference's own recipe (BASELINE.md "Derived baseline"):
Spark's driver-mediated per-task path has a ~5 ms floor, plus gradient
compute at an optimistic 6 GFLOP/s for the recipe's 2-core executor, across
8 pipelined workers; capped by the recipe-length bound with the same
generosity ratio that put the round-1 epsilon cap at 120 s (below the 200 s
derived lower bound).

Measurement discipline (BASELINE.md round 2): the tunneled backend's first
device->host readback permanently degrades per-dispatch latency for the rest
of the process, and run-to-run variance exceeded the effects measured.  So
EVERY measurement runs in a fresh subprocess (`bench.py --config NAME`), the
parent reports per-config MEDIANS of >= BENCH_REPEATS runs, and the timed
region is readback-free.

Workloads are planted problems generated directly in device HBM (this
container's host<->device link is a high-latency tunnel; shipping 3-13 GB
through it would benchmark the tunnel).  All three share E[x x^T] = I/d
conditioning so the gamma = 0.05*d step-size rule transfers; targets are
0.1% of the initial objective -- deep enough that steady-state update
throughput decides wall-clock, a decade above each problem's noise floor.

Every run exercises the REAL framework hot path: executor threads, result
queue, tau filter, partial barrier, versioned model handles, on-device
updates.  The bf16 config stores shards in bfloat16 with f32 accumulation
(the MXU-native mixed-precision path); the sparse config runs the
padded-ELL gather/scatter kernels.

Output: ONE json line {"metric", "value", "unit", "vs_baseline", "configs",
"gflops", "mfu"}.  value = epsilon median time-to-target; vs_baseline = the
MINIMUM of the three per-config median ratios (the conservative claim: every
dataset beats its reference estimate by at least this factor); gflops/mfu =
achieved compute rate of the flop-heaviest config (mnist8m).

If the TPU backend is unavailable (probe subprocesses fail/hang), the
payload carries `skipped` per config AND a labeled `fallback` block: the
same engine hot path on the host CPU backend at reduced scale, marked
not-TPU.  The fallback never stands in for the metric of record -- it exists
so a dead tunnel round still produces a non-null liveness artifact
(VERDICT r4 #1).  Disable with BENCH_FALLBACK=0.
"""

import faulthandler
import json
import os
import statistics
import subprocess
import sys
import threading
import time
import traceback
from typing import Tuple

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)) or ".")

NUM_WORKERS = 8
SPARK_TASK_FLOOR_S = 0.005   # per-gradient driver-mediated floor (BASELINE.md)
SPARK_GFLOPS = 6e9           # optimistic 2-core executor gradient compute rate
CAP_GENEROSITY = 0.6         # epsilon: 320k * 5ms / 8 * 0.6 = 120 s (round-1 cap)
TARGET_FRACTION = 0.001
BACKEND_INIT_BUDGET_S = 90.0
RUN_TIMEOUT_S = 240.0
CHILD_WATCHDOG_S = 420.0     # child hard-kill (dead device link wedges C code)
CHILD_TIMEOUT_S = 480.0      # parent's per-child subprocess timeout
PROBE_TIMEOUT_S = 75.0       # cheap backend-liveness probe (first init 20-45s)
PROBE_ATTEMPTS = 2
# hard bound on the WHOLE probe (all attempts + child reaping): the probe
# exists to detect a dead TPU tunnel, so the probe itself must be
# un-wedgeable -- subprocess timeouts alone are not enough (a killed child
# whose grandchild still holds the pipe can block the post-kill reap
# forever; reaping is pushed to a daemon thread and this deadline caps
# everything else)
PROBE_BUDGET_S = float(os.environ.get("BENCH_PROBE_BUDGET_S",
                                      2 * PROBE_TIMEOUT_S + 15))
TOTAL_BUDGET_S = float(os.environ.get("BENCH_TOTAL_BUDGET_S", 2400.0))
REPEATS = int(os.environ.get("BENCH_REPEATS", 3))
# per-arm watchdog: total wall one config may burn across its repeats
# (r03-r05 lesson: one wedging TPU config must not eat the whole budget
# and leave the other arms dark)
ARM_BUDGET_S = float(os.environ.get("BENCH_ARM_BUDGET_S", 900.0))

# Each config mirrors one reference dataset's shape and recipe
# (README.md:44-74; BASELINE.md).  gamma follows the 0.05*d conditioning
# rule validated in round 2 (rows ~ N(0, I/d) -> contraction ~ lr/d).
CONFIGS = {
    "epsilon": dict(
        n=400_000, d=2_000, dtype="float32", sparse=False, nnz=None,
        gamma=100.0, batch_rate=0.1, iters=5_000,
        ref_iters=320_000, ref_dims=2_000,   # README.md:64 ASGD epsilon row
    ),
    "mnist8m": dict(
        n=8_100_000, d=784, dtype="bfloat16", sparse=False, nnz=None,
        gamma=39.2, batch_rate=0.1, iters=5_000,
        ref_iters=300_000, ref_dims=784,     # README.md:64 ASGD mnist8m row
    ),
    "rcv1": dict(
        n=697_641, d=47_236, dtype="float32", sparse=True, nnz=75,
        # iters capped lower than the dense configs: target is reached by
        # ~k=300 and each sparse task costs real device milliseconds even
        # compacted -- a 5k budget would pay for nothing but drain time
        gamma=2361.8, batch_rate=0.05, iters=1_200, printer_freq=50,
        ref_iters=100_000, ref_dims=75,      # README.md:64 ASGD rcv1 row;
        # reference compute scales with nnz, not d, on sparse vectors
    ),
}

# BENCH_SCALE=small shrinks every config for off-TPU flow validation
if os.environ.get("BENCH_SCALE") == "small":
    for _name, _c in CONFIGS.items():
        _c.update(
            n=20_000, d=128, gamma=0.05 * 128, iters=600,
            nnz=(8 if _c["sparse"] else None),
        )

# BENCH_SCALE=fallback: moderate shapes for the labeled CPU fallback pass --
# big enough that engine rates mean something, small enough to finish on a
# host CPU backend inside the child budget.  These numbers are NEVER the
# metric of record; they exist so a dead TPU tunnel still yields a labeled
# partial artifact instead of three nulls (VERDICT r4 #1).
if os.environ.get("BENCH_SCALE") == "fallback":
    _FB = {
        "epsilon": dict(n=60_000, d=1_024, gamma=0.05 * 1_024, iters=1_500),
        "mnist8m": dict(n=200_000, d=784, gamma=0.05 * 784, iters=1_500),
        "rcv1": dict(n=60_000, d=8_192, gamma=0.05 * 8_192, nnz=32,
                     iters=600, printer_freq=25),
    }
    for _name, _c in CONFIGS.items():
        _c.update(_FB[_name])


def _guarded(fn, what: str):
    """Local copy of utils/threads.guarded (the thread exception policy):
    the probe/reaper paths must not import the package -- a wedged jax
    init is exactly what they guard against."""
    def _run(*a, **k):
        try:
            fn(*a, **k)
        except Exception:  # noqa: BLE001 - report, never die silently
            print(f"bench: unhandled exception in thread {what!r}",
                  file=sys.stderr, flush=True)
            traceback.print_exc()
    return _run


def emit(payload: dict) -> None:
    print(json.dumps(payload))
    sys.stdout.flush()


def telemetry_block(trajectory, updates_per_sec) -> dict:
    """Per-config statistical-efficiency record (ISSUE 7): the convergence
    curve summarized as loss at 25/50/100% of the run's wallclock plus its
    trailing-half slope, and the conf SLO rule set's static verdicts --
    BENCH_*.json captures how well the run CONVERGED, not just how fast it
    pushed updates."""
    from asyncframework_tpu.metrics import slo
    from asyncframework_tpu.metrics.timeseries import (
        loss_at_fractions,
        loss_slope,
    )

    out: dict = {}
    try:
        traj = [(t, l) for (t, l) in (trajectory or [])]
        out["loss_at"] = loss_at_fractions(traj)
        slope = loss_slope(traj)
        out["slope_per_s"] = (round(slope, 8) if slope is not None
                              else None)
        out["samples"] = len(traj)
        out["slo"] = slo.bench_verdicts(updates_per_sec, traj)
    except Exception as e:  # evidence-only: never fail the run on it
        out["error"] = f"{type(e).__name__}: {str(e)[:120]}"
    return out


class ArmObserver:
    """Per-arm cluster-observer harness for the DCN bench: a bare
    telemetry server (role ``ps`` -- the in-process PS registers its
    ``ps`` series source and ``ps_workers`` section there) scraped by a
    real ClusterObserver over HTTP while the arm runs, so every
    BENCH_*.json dcn arm carries the fleet series + derived signals the
    observer would have seen (ISSUE 14).  Never-dark: any failure
    becomes an ``{"error": ...}`` block, not a hole."""

    SERIES_KEEP = ("ps.accepted", "ps.queue_depth", "ps.max_staleness",
                   "observer.push_rate", "observer.merge_queue_depth",
                   "observer.straggler_score")

    def __init__(self):
        self.err = None
        self.srv = self.obs = None
        self._scrapes0 = 0
        try:
            from asyncframework_tpu.metrics.live import LiveUIServer
            from asyncframework_tpu.metrics.observer import (
                ClusterObserver,
                RoleTarget,
                observer_totals,
            )

            # process-global counter: delta it so each arm reports its
            # OWN scrape count, not the run's cumulative one
            self._scrapes0 = observer_totals().get("scrapes", 0)
            self.srv = LiveUIServer(None, port=0, role="ps").start()
            self.obs = ClusterObserver(
                targets=[RoleTarget(
                    "ps", "ps", f"http://127.0.0.1:{self.srv.port}")],
                interval_s=0.25, history_dir="", persist_s=0.0,
            ).start()
        except Exception as e:  # noqa: BLE001 - never-dark per arm
            self.err = f"{type(e).__name__}: {str(e)[:120]}"

    def finish(self) -> dict:
        if self.err is not None or self.obs is None:
            if self.srv is not None:
                self.srv.stop()
            return {"error": self.err or "observer harness unavailable"}
        try:
            self.obs.scrape_once()  # final fold before teardown
            snap = self.obs.fleet_snapshot()
            series = {}
            for role in self.obs.history.roles():
                per = self.obs.history.series_of(role)
                for key in self.SERIES_KEEP:
                    pts = per.get(key)
                    if pts:
                        series[f"{role}:{key}"] = {
                            "points": len(pts),
                            "first": pts[0][1], "last": pts[-1][1],
                        }
            return {
                "derived": snap.get("derived"),
                "stragglers": snap.get("stragglers"),
                "roles_up": (snap.get("derived") or {}).get("roles_up"),
                "scrapes": ((snap.get("totals") or {}).get("scrapes", 0)
                            - self._scrapes0),
                "series": series,
            }
        except Exception as e:  # noqa: BLE001 - never-dark per arm
            return {"error": f"{type(e).__name__}: {str(e)[:120]}"}
        finally:
            try:
                self.obs.stop()
                self.srv.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


#: stated tolerance for the profile-vs-trace consistency cross-check:
#: exact wire-zone milliseconds must not exceed this factor times the
#: traced stage total (p50 x count).  Loose by design -- zones count
#: BOTH sides of the loopback wire while traces are client-side, and
#: p50 x count underestimates a skewed stage -- but it catches the
#: failure class that matters: a zone accumulator whose clock math is
#: off by orders of magnitude.
PROFILE_TRACE_TOLERANCE = 3.0


def profile_block(prof_mod, stages: dict) -> dict:
    """Per-arm ``profile`` block (never-dark): zone shares + exact zone
    ms, samples collected, compile count/time, and the consistency
    cross-check of exact zone nanoseconds against the PR 3 trace-stage
    p50s (tolerance stated above)."""
    try:
        snap = prof_mod.last_snapshot()
        if not snap:
            return {"error": "ProfileUnavailable: profiler not installed"}
        zones = snap.get("zones") or {}
        zone_ms = {z: round(float(d.get("ns", 0)) / 1e6, 3)
                   for z, d in zones.items()}
        comp = snap.get("compile") or {}
        disp = snap.get("dispatch") or {}
        block = {
            "samples": snap.get("samples", 0),
            "zone_share": {z: round(float(d.get("share", 0.0)), 4)
                           for z, d in zones.items() if d.get("samples")},
            "zone_ms": zone_ms,
            "compile_count": comp.get("count", 0),
            "compile_ms": round(float(comp.get("ns", 0)) / 1e6, 1),
            "dispatch_count": disp.get("count", 0),
            "dispatch_ms": round(float(disp.get("ns", 0)) / 1e6, 1),
        }
        wire_ms = sum(v for z, v in zone_ms.items()
                      if z.startswith("wire."))
        traced_ms = sum(
            float(d.get("p50", 0.0)) * int(d.get("count", 0))
            for d in (stages or {}).values())
        tol = PROFILE_TRACE_TOLERANCE
        if traced_ms <= 0:
            block["trace_xcheck"] = {
                "ok": None, "tolerance": tol,
                "detail": "no trace stages to check against"}
        else:
            ok = wire_ms <= tol * traced_ms
            block["trace_xcheck"] = {
                "ok": ok, "tolerance": tol,
                "wire_zone_ms": round(wire_ms, 1),
                "trace_total_ms": round(traced_ms, 1),
                "detail": (f"exact wire-zone ms within {tol}x traced "
                           f"p50*count" if ok else
                           f"wire zones {wire_ms:.0f}ms exceed {tol}x "
                           f"traced {traced_ms:.0f}ms")}
        return block
    except Exception as e:  # noqa: BLE001 - never-dark discipline
        return {"error": f"{type(e).__name__}: {str(e)[:200]}"}


# --------------------------------------------------------------------- child
def arm_watchdog(config_name: str) -> None:
    """Emit a parseable failure line and hard-exit if the process wedges
    (a dead host<->TPU tunnel can block a device op forever in C code, where
    normal interpreter shutdown never runs)."""
    faulthandler.dump_traceback_later(CHILD_WATCHDOG_S - 30, file=sys.stderr)

    def fire():
        emit({"config": config_name, "ok": False,
              "note": f"WATCHDOG: wedged past {CHILD_WATCHDOG_S:.0f}s"})
        os._exit(0)

    t = threading.Timer(CHILD_WATCHDOG_S, fire)
    t.daemon = True
    t.start()


def init_devices():
    """jax.devices() with retry/backoff: one flaky TPU backend init must not
    erase a sample.  BENCH_PLATFORM=cpu forces the CPU backend through the
    config API (env vars alone cannot: the image's sitecustomize latches the
    TPU plugin first)."""
    import jax

    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)

    deadline = time.monotonic() + BACKEND_INIT_BUDGET_S
    delay = 5.0
    attempt = 0
    while True:
        attempt += 1
        try:
            devices = jax.devices()
            print(f"# backend up on attempt {attempt}: "
                  f"{[d.platform for d in devices]}", file=sys.stderr)
            return devices
        except Exception as e:
            remaining = deadline - time.monotonic()
            print(f"# backend init attempt {attempt} failed: {e!r}; "
                  f"{remaining:.0f}s budget left", file=sys.stderr)
            if remaining <= 0:
                raise
            try:
                jax.extend.backend.clear_backends()
            except Exception:
                try:
                    jax.clear_backends()
                except Exception:
                    pass
            time.sleep(min(delay, max(remaining, 0)))
            delay = min(delay * 2, 60.0)


def build_dataset(cfg: dict, devices):
    from asyncframework_tpu.data.sharded import ShardedDataset
    from asyncframework_tpu.data.sparse import SparseShardedDataset

    if cfg["sparse"]:
        return SparseShardedDataset.generate_on_device(
            cfg["n"], cfg["d"], cfg["nnz"], NUM_WORKERS,
            devices=devices, seed=7, noise=0.01,
        )
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if cfg["dtype"] == "bfloat16" else jnp.float32
    return ShardedDataset.generate_on_device(
        cfg["n"], cfg["d"], NUM_WORKERS, devices=devices, seed=7,
        noise=0.01, dtype=dtype,
    )


def spark_equal_recipe_baseline(cfg: dict, k_hit: int) -> float:
    """Reference cost to produce k_hit accepted gradients on this recipe
    (scheduling floor + compute, 8 pipelined workers), capped by the
    recipe-length bound at round-1's generosity ratio."""
    par_recs = cfg["batch_rate"] * cfg["n"] / NUM_WORKERS
    per_grad_s = SPARK_TASK_FLOOR_S + 2.0 * par_recs * cfg["ref_dims"] / SPARK_GFLOPS
    equal = k_hit * per_grad_s / NUM_WORKERS
    cap = cfg["ref_iters"] * SPARK_TASK_FLOOR_S / NUM_WORKERS * CAP_GENEROSITY
    return min(max(equal, 1e-3), cap)


def run_child(config_name: str) -> None:
    """One fresh-process measurement; prints one JSON line."""
    cfg = CONFIGS[config_name]
    devices = init_devices()
    import jax
    import jax.numpy as jnp

    from asyncframework_tpu.solvers import ASGD, SolverConfig
    from asyncframework_tpu.utils import flops as fl

    t0 = time.monotonic()
    ds = build_dataset(cfg, devices)
    for wid in range(NUM_WORKERS):
        ds.shard(wid).y.block_until_ready()
    print(f"# {config_name}: data {cfg['n']}x{cfg['d']} "
          f"({'sparse' if cfg['sparse'] else cfg['dtype']}) generated on "
          f"device in {time.monotonic() - t0:.1f}s", file=sys.stderr)

    scfg = SolverConfig(
        num_workers=NUM_WORKERS,
        num_iterations=cfg["iters"],
        gamma=cfg["gamma"],
        taw=2**31 - 1,
        batch_rate=cfg["batch_rate"],
        bucket_ratio=0.7,
        printer_freq=cfg.get("printer_freq", 25),
        coeff=0.0,
        seed=42,
        calibration_iters=100,
        run_timeout_s=RUN_TIMEOUT_S,
        trace_sample=0.0,  # tracing only when the parent asks (BENCH_TRACE)
    )
    # latency decomposition alongside throughput (bench.py --trace-jsonl):
    # sample update lifecycles through metrics/trace.py so the BENCH
    # artifact records per-stage p50/p95/p99 and staleness-in-ms, not just
    # updates/s -- every later perf PR becomes judgeable stage by stage
    if os.environ.get("BENCH_TRACE") == "1":
        from asyncframework_tpu.metrics import trace as trace_mod

        trace_mod.reset_aggregator()
        scfg.trace_sample = float(
            os.environ.get("BENCH_TRACE_SAMPLE", "0.125")
        )
    solver = ASGD(ds, None, scfg, devices=devices)

    # warm the XLA compile caches outside the timed region (the reference's
    # first blocking iteration plays the same role for Spark's caches)
    shard = ds.shard(0)
    key = jax.random.PRNGKey(0)
    w0 = jax.device_put(np.zeros(cfg["d"], np.float32), devices[0])
    if cfg["sparse"]:
        g, _ = solver._step(shard.cols, shard.vals, shard.y, w0, key)
    else:
        g, _ = solver._step(shard.X, shard.y, w0, key)
    solver._apply(
        jax.device_put(np.zeros(cfg["d"], np.float32), devices[0]),
        jax.device_put(g, devices[0]),
        jax.device_put(np.float32(0), devices[0]),
    )
    print("# compile warm-up done", file=sys.stderr)

    # dispatch round-trip diagnostic: on a tunneled/remote device the
    # per-dispatch RTT, not the framework, bounds updates/sec
    probe = jax.device_put(np.zeros(8, np.float32), devices[0])
    t0 = time.monotonic()
    for _ in range(20):
        probe = (probe + 1.0).block_until_ready()
    rtt_ms = (time.monotonic() - t0) / 20 * 1e3
    print(f"# device dispatch round-trip ~{rtt_ms:.2f} ms", file=sys.stderr)

    # kernel-window rate, measured APART from end-to-end (round-3 verdict:
    # 19.3 TFLOP/s kernel vs 56 updates/s e2e were published unlabeled and
    # read as a 275x contradiction).  Chained step->apply reps at two depths;
    # the SLOPE (T_hi - T_lo)/(hi - lo) cancels both constant dispatch
    # overhead and any lazy-completion bias in block_until_ready (observed on
    # this backend), and scaling with depth proves execution is real.  No
    # np.asarray here: the first device->host READBACK degrades dispatch for
    # the whole process (BASELINE.md round 2) and the timed run comes next.
    task_fl = solver._task_flops(0)

    def chained(reps: int) -> float:
        wk = jax.device_put(np.zeros(cfg["d"], np.float32), devices[0])
        kk = jax.device_put(np.float32(0.0), devices[0])
        kkey = jax.device_put(jax.random.PRNGKey(1), devices[0])
        t0 = time.monotonic()
        for _ in range(reps):
            if cfg["sparse"]:
                gg, kkey = solver._step(
                    shard.cols, shard.vals, shard.y, wk, kkey
                )
            else:
                gg, kkey = solver._step(shard.X, shard.y, wk, kkey)
            wk, kk = solver._apply(wk, gg, kk)
        wk.block_until_ready()
        return time.monotonic() - t0

    chained(2)  # absorb first-call overhead outside both measured depths
    t_lo, t_hi = chained(8), chained(40)
    per_update_s = (t_hi - t_lo) / 32.0
    if per_update_s > 0:
        kernel_gflops = task_fl / per_update_s / 1e9
    else:  # slope lost in timer noise: kernel is too fast to resolve here
        kernel_gflops = None
        per_update_s = None
    print(f"# kernel window: {per_update_s} s/update chained "
          f"(ceiling {kernel_gflops} GFLOP/s; t8={t_lo:.3f}s "
          f"t40={t_hi:.3f}s)", file=sys.stderr)

    res = solver.run()

    trace_snap = None
    if os.environ.get("BENCH_TRACE") == "1":
        from asyncframework_tpu.metrics import trace as trace_mod

        trace_snap = trace_mod.aggregator().snapshot()

    initial = res.trajectory[0][1]
    target = initial * TARGET_FRACTION
    t_hit_traj = None
    k_hit = None
    for i, (t_ms, obj) in enumerate(res.trajectory):
        if obj <= target:
            t_hit_traj = t_ms / 1e3
            k_hit = max(i * scfg.printer_freq, 1)
            break
    # HONEST time-to-target: trajectory timestamps are host dispatch times,
    # and this backend has been observed completing dispatches lazily --
    # so attribute wall-clock by the run's true (fenced) throughput:
    # t_hit = k_hit / (accepted / elapsed).  elapsed_s is measured after a
    # full device sync (solvers fence with np.asarray before timing).
    t_hit = None
    if k_hit is not None and res.accepted > 0 and res.elapsed_s > 0:
        t_hit = k_hit * res.elapsed_s / res.accepted
    gflops = res.total_flops / res.elapsed_s / 1e9 if res.elapsed_s > 0 else 0.0
    mfu = fl.mfu(res.total_flops, res.elapsed_s, devices[0])
    print(
        f"# {config_name}: accepted={res.accepted} dropped={res.dropped} "
        f"rounds={res.rounds} updates/s={res.updates_per_sec:.0f} "
        f"elapsed={res.elapsed_s:.1f}s obj {initial:.4f}->"
        f"{res.trajectory[-1][1]:.6f} target={target:.6f} t_hit={t_hit} "
        f"(traj={t_hit_traj}) gflops={gflops:.1f} mfu={mfu}",
        file=sys.stderr,
    )
    if t_hit is None:
        emit({"config": config_name, "ok": False,
              "note": "TARGET NOT REACHED",
              "elapsed_s": round(res.elapsed_s, 2),
              "final_over_initial": res.trajectory[-1][1] / initial,
              "trace": trace_snap,
              "telemetry": telemetry_block(res.trajectory,
                                           res.updates_per_sec)})
        return
    baseline = spark_equal_recipe_baseline(cfg, k_hit)

    # device-resident accept loop (VERDICT r3 item 2): the same recipe with
    # the host dispatch bound removed (taw=inf full-wave rounds fused into
    # lax.scan on the PS chip).  Recorded ALONGSIDE the engine number, both
    # labeled -- the engine path stays the metric of record.
    fused = None
    if os.environ.get("BENCH_FUSED", "1") != "0":
        try:
            fres = ASGD(ds, None, scfg, devices=devices).run_fused()
            f_initial = fres.trajectory[0][1]
            f_target = f_initial * TARGET_FRACTION
            f_khit = None
            for i, (_t, obj) in enumerate(fres.trajectory):
                if obj <= f_target:
                    f_khit = max(i * max(scfg.printer_freq, 1), 1)
                    break
            f_thit = (
                f_khit * fres.elapsed_s / fres.accepted
                if f_khit is not None and fres.accepted else None
            )
            fused = {
                "updates_per_sec": round(fres.updates_per_sec, 1),
                "elapsed_s": round(fres.elapsed_s, 2),
                "accepted": fres.accepted,
                "t_hit": round(f_thit, 4) if f_thit is not None else None,
                "vs_baseline": (
                    round(spark_equal_recipe_baseline(cfg, f_khit) / f_thit, 2)
                    if f_thit else None
                ),
                "gflops": round(
                    fres.total_flops / fres.elapsed_s / 1e9, 2
                ) if fres.elapsed_s > 0 else None,
            }
            print(f"# {config_name}: FUSED updates/s="
                  f"{fres.updates_per_sec:.0f} t_hit={f_thit} "
                  f"(engine updates/s={res.updates_per_sec:.0f})",
                  file=sys.stderr)
        except Exception as e:
            fused = {"error": f"{type(e).__name__}: {str(e)[:120]}"}
    emit({
        "config": config_name,
        "ok": True,
        "t_hit": round(t_hit, 3),
        "t_hit_traj": (round(t_hit_traj, 3) if t_hit_traj is not None
                       else None),
        "k_hit": k_hit,
        "vs_baseline": round(baseline / t_hit, 2),
        "baseline_s": round(baseline, 3),
        "updates_per_sec": round(res.updates_per_sec, 1),
        "accepted": res.accepted,
        "elapsed_s": round(res.elapsed_s, 2),
        "gflops": round(gflops, 2),           # END-TO-END: run flops/elapsed
        "mfu": (round(mfu, 6) if mfu is not None else None),
        "kernel_gflops": (round(kernel_gflops, 2)
                          if kernel_gflops is not None else None),
        "kernel_ms_per_update": (round(per_update_s * 1e3, 4)
                                 if per_update_s is not None else None),
        "fused": fused,   # device-resident accept loop, labeled apart
        "rtt_ms": round(rtt_ms, 2),
        # per-stage latency decomposition + staleness-in-ms (None unless
        # the parent ran with --trace-jsonl / BENCH_TRACE=1)
        "trace": trace_snap,
        # statistical efficiency: loss at 25/50/100% wallclock, trailing
        # slope, and the conf SLO rule set's verdicts for this run
        "telemetry": telemetry_block(res.trajectory, res.updates_per_sec),
    })


# ----------------------------------------------------------------- DCN bench
# Wire-plane microbench (always CPU: it measures the data plane, not the
# chip): the REAL ParameterServer + worker loop over loopback TCP, once per
# pull mode, recording updates/s, wire bytes per update, and pull/push
# payload shapes.  This is the artifact the delta-pull/vectored-framing/
# batched-apply overhaul is judged by.
DCN_CONFIGS = {
    # dense gradients touch every coordinate, so deltas degrade to full --
    # this config guards the "delta mode must not cost throughput" side
    "dense": dict(sparse=False, n=8192, d=2048, nnz=None, nw=4,
                  gamma=0.05 * 2048, batch_rate=0.05, iters=300),
    # rcv1-shaped: sparse pushes touch few coordinates, so consecutive
    # pulls reconstruct from small XOR deltas -- the bytes-per-update win
    "sparse": dict(sparse=True, n=4096, d=16384, nnz=8, nw=4,
                   gamma=500.0, batch_rate=0.02, iters=300),
}


def run_dcn_child() -> None:
    """One fresh-process DCN wire bench; prints one JSON line.

    Four arms per config: pull mode (full/delta) x update-loop pipelining
    (off/on, ``async.pipeline.depth``).  The ``*_pipe`` arms are the
    pipelined-update-loop A-B the tentpole is judged by: same wire modes,
    prefetched pulls + decoupled pushes + lock-free PULL serving on top.
    Each arm also records the trace decomposition (pull.wait/push.wait/
    pipeline p50s) and the pipeline counters."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from asyncframework_tpu.conf import AsyncConf, set_global_conf
    from asyncframework_tpu.data.sharded import ShardedDataset
    from asyncframework_tpu.data.sparse import SparseShardedDataset
    from asyncframework_tpu.metrics import profiler as prof_mod
    from asyncframework_tpu.metrics import trace as trace_mod
    from asyncframework_tpu.net import frame, reset_net_totals
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.solvers import SolverConfig

    devices = jax.devices()
    # continuous-profiling plane, once per child process; each arm
    # resets the accumulators so its profile block is arm-local
    prof_mod.install("bench-dcn", hz=197.0)
    # BENCH_DCN_PIPELINE=0 drops the pipelined arms entirely
    pipe_depth = max(0, int(os.environ.get("BENCH_DCN_PIPELINE", "2")))
    out = {}
    for name, c in DCN_CONFIGS.items():
        if c["sparse"]:
            ds = SparseShardedDataset.generate_on_device(
                c["n"], c["d"], c["nnz"], c["nw"], devices=devices,
                seed=7, noise=0.01,
            )
        else:
            ds = ShardedDataset.generate_on_device(
                c["n"], c["d"], c["nw"], devices=devices, seed=7,
                noise=0.01,
            )
        out[name] = {}
        arms = [("full", 0), ("delta", 0)]
        if pipe_depth > 0:
            arms += [("full", pipe_depth), ("delta", pipe_depth)]
        for mode, depth in arms:
            label = mode if depth == 0 else f"{mode}_pipe"
            conf = AsyncConf()
            conf.set("async.pull.mode", mode)
            conf.set("async.pipeline.depth", depth)
            # per-stage latency decomposition rides the artifact (same
            # sampling cost in every arm, so the A-B stays fair)
            conf.set("async.trace.sample", 1.0 / 8.0)
            set_global_conf(conf)
            reset_net_totals()
            ps_dcn.reset_pipeline_totals()
            trace_mod.reset_aggregator()
            prof_mod.reset_profile_totals()
            cfg = SolverConfig(
                num_workers=c["nw"], num_iterations=c["iters"],
                gamma=c["gamma"], taw=2**31 - 1,
                batch_rate=c["batch_rate"], bucket_ratio=0.5,
                printer_freq=100, coeff=0.0, seed=42,
                calibration_iters=20, run_timeout_s=120.0,
            )
            ps = ps_dcn.ParameterServer(
                cfg, c["d"], c["n"], device=devices[0], port=0
            ).start()
            arm_obs = ArmObserver()  # fleet-series artifact per arm
            shards = {w: ds.shard(w) for w in range(c["nw"])}
            t0 = time.monotonic()
            ps_dcn.run_worker_process(
                "127.0.0.1", ps.port, list(range(c["nw"])), shards, cfg,
                c["d"], c["n"], deadline_s=120.0,
            )
            done = ps.wait_done(timeout_s=5.0)
            elapsed = time.monotonic() - t0
            observer_block = arm_obs.finish()
            ps.stop()
            bt = frame.bytes_totals()
            pulls = max(sum(ps.pull_replies.values()), 1)
            pushes = max(ps.accepted + ps.dropped, 1)
            stages = trace_mod.aggregator().snapshot().get("stages_ms", {})
            rec = {
                "ok": bool(done),
                "accepted": ps.accepted,
                "updates_per_sec": round(ps.accepted / elapsed, 1)
                if elapsed > 0 else None,
                # sent counts both directions of the loopback pair once
                # (client requests + server replies): the wire volume
                "wire_bytes_per_update": round(
                    bt.get("sent", 0) / max(ps.accepted, 1)
                ),
                "pull_model_bytes_avg": round(ps.pull_model_bytes / pulls),
                "pull_replies": dict(ps.pull_replies),
                "push_payload_bytes_avg": round(ps.push_bytes / pushes),
                "max_staleness": ps.max_staleness,
                "merge": {"batches": ps.merge_batches,
                          "pushes": ps.merge_merged,
                          "max_batch": ps.merge_batch_max},
                # worker-loop stall decomposition: the pipelined arms
                # should show pull.wait/push.wait p50 shrinking with the
                # residual stall surfacing under "pipeline"
                "trace_p50_ms": {
                    st: round(s["p50"], 3) for st, s in stages.items()
                },
                # per-arm cluster-observer artifact (ISSUE 14): the
                # fleet series + derived signals a collector scraped
                # off this arm's PS while it ran (never-dark: an error
                # string on failure)
                "observer": observer_block,
                # per-arm continuous-profiling artifact (ISSUE 18):
                # zone decomposition + the trace consistency cross-check
                "profile": profile_block(prof_mod, stages),
            }
            if depth > 0:
                rec["pipeline"] = ps_dcn.pipeline_totals()
            out[name][label] = rec
        full_b = out[name]["full"]["wire_bytes_per_update"]
        delta_b = out[name]["delta"]["wire_bytes_per_update"]
        out[name]["wire_bytes_ratio_full_over_delta"] = (
            round(full_b / delta_b, 2) if delta_b else None
        )
        for mode in ("full", "delta"):
            if f"{mode}_pipe" not in out[name]:
                continue
            off = out[name][mode]["updates_per_sec"]
            on = out[name][f"{mode}_pipe"]["updates_per_sec"]
            out[name][f"pipeline_speedup_{mode}"] = (
                round(on / off, 3) if off and on else None
            )
    # sharded-PS arm (parallel/shardgroup.py): 1 vs 3 REAL shard child
    # processes serving the dense config, full and delta wire modes.  The
    # 1-shard control crosses the same process boundary (a managed child,
    # classic single-PS wire), so the A-B isolates the range-partition
    # fan-out cost/win rather than loopback-vs-process noise.
    # BENCH_DCN_SHARDS=0 drops the arm.
    if os.environ.get("BENCH_DCN_SHARDS", "1") != "0":
        from asyncframework_tpu.parallel.shardgroup import ShardGroup

        c = DCN_CONFIGS["dense"]
        ds = ShardedDataset.generate_on_device(
            c["n"], c["d"], c["nw"], devices=devices, seed=7, noise=0.01,
        )
        out["shards"] = {}
        for shard_count in (1, 3):
            for mode in ("full", "delta"):
                label = f"s{shard_count}_{mode}"
                conf = AsyncConf()
                conf.set("async.pull.mode", mode)
                conf.set("async.pipeline.depth", 0)
                set_global_conf(conf)
                reset_net_totals()
                cfg = SolverConfig(
                    num_workers=c["nw"], num_iterations=c["iters"],
                    gamma=c["gamma"], taw=2**31 - 1,
                    batch_rate=c["batch_rate"], bucket_ratio=0.5,
                    printer_freq=100, coeff=0.0, seed=42,
                    calibration_iters=20, run_timeout_s=120.0,
                    pull_mode=mode,
                )
                group = ShardGroup(
                    cfg, c["d"], c["n"], shard_count,
                    conf_overlays=conf.to_dict(),
                ).start()
                try:
                    primary_port = group.port_of(0)
                    shards = {w: ds.shard(w) for w in range(c["nw"])}
                    t0 = time.monotonic()
                    counts = ps_dcn.run_worker_process(
                        "127.0.0.1", primary_port, list(range(c["nw"])),
                        shards, cfg, c["d"], c["n"], deadline_s=120.0,
                    )
                    elapsed = time.monotonic() - t0
                    group.finish()
                    result = group.result_of(0, timeout_s=30.0) or {}
                finally:
                    group.stop()
                bt = frame.bytes_totals()
                accepted = int(result.get("accepted", 0))
                out["shards"][label] = {
                    "ok": bool(result.get("done")),
                    "shards": shard_count,
                    "accepted": accepted,
                    "gradients": int(sum(counts.values())),
                    "updates_per_sec": round(accepted / elapsed, 1)
                    if elapsed > 0 and accepted else None,
                    "wire_bytes_per_update": round(
                        bt.get("sent", 0) / max(accepted, 1)
                    ),
                    "restarts": group.restarts_of(0),
                }
        for mode in ("full", "delta"):
            one = out["shards"][f"s1_{mode}"]["updates_per_sec"]
            three = out["shards"][f"s3_{mode}"]["updates_per_sec"]
            out["shards"][f"shard_speedup_{mode}"] = (
                round(three / one, 3) if one and three else None
            )
    # failover arm (ISSUE 13): p99 pull latency through a seeded
    # primary SIGKILL, checkpoint-restart vs hot-standby promotion --
    # the number ROADMAP item 5's acceptance is judged by.  Per-arm
    # never-dark: an arm that wedges or errors records its error
    # string, not a hole.  BENCH_DCN_FAILOVER=0 drops the arm.
    if os.environ.get("BENCH_DCN_FAILOVER", "1") != "0":
        out["failover"] = {}
        for label, sb in (("restart", 0), ("promote", 1)):
            try:
                out["failover"][label] = _dcn_failover_arm(sb)
            except Exception as e:  # noqa: BLE001 - never-dark per arm
                out["failover"][label] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        r = out["failover"].get("restart", {}).get("gap_s")
        p = out["failover"].get("promote", {}).get("gap_s")
        out["failover"]["gap_ratio_restart_over_promote"] = (
            round(r / p, 2) if r and p else None
        )
    # adaptive arm (ISSUE 15): static conf vs controller-on under the
    # wan/DELAY deterministic heterogeneous cluster (the SAME seeded
    # wan wire schedule + the cloud long-tail DelayModel in both arms),
    # reporting time-to-target, updates/s, staleness p95, and the
    # controller's decision trace.  Per-arm never-dark: a wedged or
    # erroring arm records its error string, not a hole.
    # BENCH_DCN_ADAPTIVE=0 drops the arm.
    if os.environ.get("BENCH_DCN_ADAPTIVE", "1") != "0":
        out["adaptive"] = {}
        for label, on in (("static", False), ("controller", True)):
            try:
                out["adaptive"][label] = _dcn_adaptive_arm(on)
            except Exception as e:  # noqa: BLE001 - never-dark per arm
                out["adaptive"][label] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"
                }
        s = out["adaptive"].get("static", {})
        a = out["adaptive"].get("controller", {})
        tts, tta = s.get("time_to_target_s"), a.get("time_to_target_s")
        out["adaptive"]["time_to_target_ratio_static_over_controller"] = (
            round(tts / tta, 3) if tts and tta else None
        )
        us, ua = s.get("updates_per_sec"), a.get("updates_per_sec")
        out["adaptive"]["updates_ratio_controller_over_static"] = (
            round(ua / us, 3) if us and ua else None
        )
    emit({"dcn": out})


def _dcn_adaptive_arm(control_on: bool) -> dict:
    """One adaptive-control measurement: the dense config on a
    deterministic heterogeneous cluster -- every op pays the seeded wan
    profile's delay/jitter/loss, and the cloud long-tail DelayModel
    (``coeff=-1``) makes some logical workers persistently slow -- with
    the knobs static vs closed-loop (AsyncController on the PS).  The
    A-B shares the wire schedule and data seed, so the only difference
    is who tunes the knobs."""
    import jax

    import numpy as np

    from asyncframework_tpu.conf import AsyncConf, set_global_conf
    from asyncframework_tpu.data.sharded import ShardedDataset
    from asyncframework_tpu.metrics import trace as trace_mod
    from asyncframework_tpu.net import faults, reset_net_totals
    from asyncframework_tpu.parallel import controller as ctrl_mod
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.parallel.controller import AsyncController
    from asyncframework_tpu.solvers import SolverConfig

    devices = jax.devices()
    c = DCN_CONFIGS["dense"]
    seed = int(os.environ.get("BENCH_ADAPTIVE_SEED", "7"))
    conf = AsyncConf()
    conf.set("async.pull.mode", "delta")
    conf.set("async.pipeline.depth", 0)
    conf.set("async.trace.sample", 1.0 / 8.0)
    # fast decision cadence: bench arms run tens of seconds, not hours
    conf.set("async.control.interval.s", 0.25)
    conf.set("async.control.cooldown.s", 0.5)
    set_global_conf(conf)
    reset_net_totals()
    ps_dcn.reset_pipeline_totals()
    trace_mod.reset_aggregator()
    ctrl_mod.reset_control_totals()
    cfg = SolverConfig(
        num_workers=c["nw"], num_iterations=c["iters"],
        gamma=c["gamma"], taw=2**31 - 1, batch_rate=c["batch_rate"],
        bucket_ratio=0.75, printer_freq=50, coeff=-1.0, seed=42,
        calibration_iters=20, run_timeout_s=180.0,
    )
    ds = ShardedDataset.generate_on_device(
        c["n"], c["d"], c["nw"], devices=devices, seed=7, noise=0.01,
    )
    inj = faults.FaultInjector(faults.wan_profile_schedule(seed))
    ps = None
    ctl = None
    try:
        # inside the try: a startup failure must still clear the global
        # injector and stop the PS, or the OTHER adaptive arm (and any
        # later dcn measurement in this child) runs with a stacked wan
        # schedule -- corrupting the very A/B this arm exists for
        faults.install(inj)
        ps = ps_dcn.ParameterServer(
            cfg, c["d"], c["n"], device=devices[0], port=0
        ).start()
        if control_on:
            ctl = AsyncController(ps, conf=conf).start()
        shards = {w: ds.shard(w) for w in range(c["nw"])}
        t0 = time.monotonic()
        ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(c["nw"])), shards, cfg,
            c["d"], c["n"], deadline_s=180.0,
        )
        done = ps.wait_done(timeout_s=5.0)
        elapsed = time.monotonic() - t0
        times, W = ps.snapshot_stack()
        losses = (ps_dcn.evaluate_snapshots_on_shards(
            shards, times, W) / c["n"])
        target = float(losses[0]) * 0.05
        t_target = None
        for t_ms, loss in zip(times, losses):
            if float(loss) <= target:
                t_target = round(float(t_ms) / 1e3, 3)
                break
        stal = trace_mod.aggregator().snapshot().get(
            "staleness_versions", {})
        rec = {
            "ok": bool(done),
            "control": bool(control_on),
            "accepted": ps.accepted,
            "dropped": ps.dropped,
            "updates_per_sec": round(ps.accepted / elapsed, 1)
            if elapsed > 0 else None,
            "time_to_target_s": t_target,
            "target_loss": round(target, 6),
            "final_loss": round(float(losses[-1]), 6),
            "staleness_p95": stal.get("p95"),
            "max_staleness": ps.max_staleness,
            "wan_faults_fired": len(inj.fired),
        }
        if ctl is not None:
            decisions = ctl.decision_log()
            rec["decisions"] = decisions
            rec["control_totals"] = ctrl_mod.control_totals()
            rec["knobs"] = ctl.status()["knobs"]
            # controller_converged verdict on the REAL decision trace:
            # cumulative change count as a synthesized control.changes
            # series (flat tail = converged), judged by the conf rule
            changes = [[d["t"] * 1e3, i + 1]
                       for i, d in enumerate(decisions)]
            changes.append([elapsed * 1e3, float(len(decisions))])
            from asyncframework_tpu.metrics.slo import bench_verdicts

            verdicts = bench_verdicts(
                rec["updates_per_sec"],
                [[t, float(l)] for t, l in zip(times, losses)],
                extra_series={"control.changes": changes},
            )
            rec["slo"] = {"controller_converged":
                          verdicts.get("controller_converged")}
        return rec
    finally:
        if ctl is not None:
            ctl.stop()
        if ps is not None:
            ps.stop()
        faults.clear()


def _dcn_failover_arm(standbys: int) -> dict:
    """One failover measurement: a 2-shard REAL-process group (fence
    on; ``standbys`` warm standbys per shard) with in-process workers
    training through it, SIGKILL of shard 1's primary mid-run, and a
    20 ms-cadence read probe against the range's CURRENT endpoint.
    Records the availability gap (kill -> first answer from the
    recovered endpoint), p99 probe latency across the window, and HOW
    the range recovered (promotion vs restart-from-checkpoint)."""
    import signal as _signal
    import tempfile
    import threading

    import numpy as np  # noqa: F811 - child-scope import, bench style
    import jax

    from asyncframework_tpu.conf import AsyncConf, set_global_conf
    from asyncframework_tpu.data.sharded import ShardedDataset
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.parallel import shardgroup as sgm
    from asyncframework_tpu.solvers import SolverConfig

    n, d, nw = 2048, 64, 4
    kill_after = int(os.environ.get("BENCH_FAILOVER_KILL_AFTER", "60"))
    cfg = SolverConfig(
        num_workers=nw, num_iterations=10**6, gamma=0.5, taw=2**31 - 1,
        batch_rate=0.2, bucket_ratio=0.5, printer_freq=50, coeff=0.0,
        seed=42, calibration_iters=20, run_timeout_s=120.0,
    )
    conf = AsyncConf({"async.fence.enabled": True,
                      "async.ps.standby": standbys})
    set_global_conf(conf)
    tmp = tempfile.mkdtemp(prefix="bench-failover-")
    group = sgm.ShardGroup(
        cfg, d, n, 2, checkpoint_dir=tmp, conf_overlays=conf.to_dict(),
        dead_after_s=1.0, check_interval_s=0.2, stderr_dir=tmp,
    ).start()
    ds = ShardedDataset.generate_on_device(
        n, d, nw, devices=jax.devices(), seed=7, noise=0.01,
    )
    shards = {w: ds.shard(w) for w in range(nw)}

    def train():
        try:
            ps_dcn.run_worker_process(
                "127.0.0.1", group.port_of(0), list(range(nw)), shards,
                cfg, d, n, deadline_s=90.0,
            )
        except Exception:  # noqa: BLE001 - the probe owns the verdict
            pass

    worker = threading.Thread(target=train, name="bench-failover-worker",
                              daemon=True)
    worker.start()
    try:
        # wait for shard 1 to merge past the kill threshold (its
        # cadence checkpoint must exist so the restart arm actually
        # replays one)
        deadline = time.monotonic() + 45.0
        while time.monotonic() < deadline:
            try:
                hdr = sgm._oneshot("127.0.0.1", group.port_of(1),
                                   {"op": "SUBSCRIBE"}, timeout_s=1.0)
                if int(hdr.get("clock", 0)) >= kill_after:
                    break
            except (ConnectionError, OSError):
                pass
            time.sleep(0.02)
        else:
            return {"error": "shard 1 never reached the kill threshold"}
        lat_ms = []

        def probe_until(deadline_s, stop_when=None):
            """20 ms-cadence reads of range 1 at its CURRENT endpoint;
            successful round trips land in lat_ms.  Returns the
            monotonic time stop_when first held, else None."""
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                t0 = time.monotonic()
                try:
                    sgm._oneshot("127.0.0.1", group.port_of(1),
                                 {"op": "SUBSCRIBE"}, timeout_s=1.0)
                    lat_ms.append((time.monotonic() - t0) * 1e3)
                    if stop_when is not None and stop_when():
                        return time.monotonic()
                except (ConnectionError, OSError):
                    pass
                time.sleep(0.02)
            return None

        probe_until(2.0)  # healthy baseline window
        os.kill(group.pid_of(1), _signal.SIGKILL)
        t_kill = time.monotonic()
        recovered_at = probe_until(
            60.0,
            stop_when=lambda: (group.promotions_of(1) >= 1
                               or group.restarts_of(1) >= 1),
        )
        gap_s = (recovered_at - t_kill) if recovered_at is not None \
            else None
        probe_until(2.0)  # recovered window: post-failover latency
        group.finish()
        worker.join(timeout=30.0)
        result1 = group.result_of(1, timeout_s=15.0) or {}
        return {
            "ok": gap_s is not None,
            "standbys": standbys,
            "gap_s": round(gap_s, 3) if gap_s is not None else None,
            "pull_p99_ms": (round(float(np.percentile(lat_ms, 99)), 3)
                            if lat_ms else None),
            "pull_p50_ms": (round(float(np.percentile(lat_ms, 50)), 3)
                            if lat_ms else None),
            "probes": len(lat_ms),
            "recovered_by": ("promotion" if group.promotions_of(1)
                             else "restart" if group.restarts_of(1)
                             else None),
            "resumed_from": result1.get("resumed_from"),
            "promoted": result1.get("promoted"),
        }
    finally:
        group.stop()


def run_dcn_mesh_child() -> None:
    """Mesh-arm DCN bench (ISSUE 11): the dense config with the worker
    gradient step single-device (``async.mesh.devices=0``, the control)
    vs batch-parallel over an 8-device mesh, in a child whose platform
    is 8 FORCED-HOST CPU devices (the parent sets XLA_FLAGS; the rig's
    TPU tunnel is routinely dead, so the CPU arm is the control of
    record).  Records updates/s, the per-step compute p50 from the trace
    decomposition, the actual mesh shape, and -- like every MULTICHIP
    emit -- ``jax.device_count()`` + platform, so a dead-TPU fallback
    run is distinguishable from a real 1-chip run in the trajectory.

    Loopback reality check (same story as PR 4's delta bytes and PR 8's
    shard fan-out): on virtual CPU devices the psum and the P-way
    emulated dispatch are pure overhead -- the win this arm exists to
    price appears when the per-device partial gradient runs on a real
    chip and the all-reduce rides ICI.  The compute-p50 decomposition is
    what makes the A-B readable either way.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    from asyncframework_tpu.conf import AsyncConf, set_global_conf
    from asyncframework_tpu.data.sharded import ShardedDataset
    from asyncframework_tpu.metrics import trace as trace_mod
    from asyncframework_tpu.net import reset_net_totals
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.solvers import SolverConfig

    devices = jax.devices()
    mesh_n = max(1, int(os.environ.get("BENCH_DCN_MESH_DEVICES", "8")))
    c = DCN_CONFIGS["dense"]
    ds = ShardedDataset.generate_on_device(
        c["n"], c["d"], c["nw"], devices=devices, seed=7, noise=0.01,
    )
    out = {
        "device_count": jax.device_count(),
        "platform": devices[0].platform,
        "requested_mesh_devices": mesh_n,
    }
    for label, mesh_dev in (("mesh_off", 0), ("mesh_on", mesh_n)):
        conf = AsyncConf()
        conf.set("async.pull.mode", "full")
        conf.set("async.pipeline.depth", 0)
        conf.set("async.mesh.devices", mesh_dev)
        conf.set("async.trace.sample", 1.0 / 8.0)
        set_global_conf(conf)
        reset_net_totals()
        trace_mod.reset_aggregator()
        cfg = SolverConfig(
            num_workers=c["nw"], num_iterations=c["iters"],
            gamma=c["gamma"], taw=2**31 - 1,
            batch_rate=c["batch_rate"], bucket_ratio=0.5,
            printer_freq=100, coeff=0.0, seed=42,
            calibration_iters=20, run_timeout_s=120.0,
        )
        ps = ps_dcn.ParameterServer(
            cfg, c["d"], c["n"], device=devices[0], port=0
        ).start()
        shards = {w: ds.shard(w) for w in range(c["nw"])}
        t0 = time.monotonic()
        ps_dcn.run_worker_process(
            "127.0.0.1", ps.port, list(range(c["nw"])), shards, cfg,
            c["d"], c["n"], deadline_s=120.0,
        )
        done = ps.wait_done(timeout_s=5.0)
        elapsed = time.monotonic() - t0
        ps.stop()
        stages = trace_mod.aggregator().snapshot().get("stages_ms", {})
        eff = min(mesh_dev, len(devices)) if mesh_dev else 0
        out[label] = {
            "ok": bool(done),
            "accepted": ps.accepted,
            "updates_per_sec": round(ps.accepted / elapsed, 1)
            if elapsed > 0 else None,
            # the worker-side gradient step is the stage the mesh
            # parallelizes: its p50 is the per-step compute cost
            "compute_p50_ms": round(
                stages.get(trace_mod.COMPUTE, {}).get("p50", 0.0), 3
            ) or None,
            "mesh_shape": {"dp": eff} if eff >= 2 else None,
            "max_staleness": ps.max_staleness,
        }
    off = out["mesh_off"]["updates_per_sec"]
    on = out["mesh_on"]["updates_per_sec"]
    out["mesh_speedup"] = round(on / off, 3) if off and on else None
    emit({"dcn_mesh": out})


def collect_dcn_mesh_block(env: dict) -> dict:
    """Run the mesh arm in a disposable subprocess whose platform is
    forced to 8 virtual host devices (XLA latches the flag at backend
    init, so the fan-out must happen at process birth)."""
    env2 = dict(env)
    env2["JAX_PLATFORMS"] = "cpu"
    flags = env2.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env2["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--dcn-mesh"],
            capture_output=True, text=True, timeout=600, env=env2,
        )
    except subprocess.TimeoutExpired:
        return {"error": "dcn mesh bench timed out"}
    sys.stderr.write(res.stderr)
    line = next((l for l in reversed(res.stdout.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return {"error": f"no JSON from dcn mesh child "
                         f"(rc={res.returncode})"}
    return json.loads(line).get(
        "dcn_mesh", {"error": "malformed dcn mesh payload"}
    )


def collect_dcn_block(env: dict) -> dict:
    """Run the DCN wire bench in a disposable subprocess (same discipline
    as every other measurement: fresh process, parent owns the timeout)."""
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--dcn"],
            capture_output=True, text=True, timeout=600, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": "dcn bench timed out"}
    sys.stderr.write(res.stderr)
    line = next((l for l in reversed(res.stdout.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return {"error": f"no JSON from dcn child (rc={res.returncode})"}
    return json.loads(line).get("dcn", {"error": "malformed dcn payload"})


# --------------------------------------------------------------- serve bench
# Serving-tier bench (always CPU: it measures the read path's QPS vs
# freshness lag, not the chip): a REAL ParameterServer with training
# running on a worker thread, REAL replica OS processes subscribed over
# loopback TCP, a ServingFrontend routing a multi-threaded client load --
# and one arm where a replica is SIGKILLed mid-load to price failover.
SERVE_CONFIG = dict(n=4096, d=512, nw=2, gamma=0.05 * 512,
                    batch_rate=0.1, iters=200_000)
SERVE_LOAD_S = float(os.environ.get("BENCH_SERVE_LOAD_S", 3.0))
SERVE_CLIENTS = int(os.environ.get("BENCH_SERVE_CLIENTS", 4))
SERVE_BATCH = int(os.environ.get("BENCH_SERVE_BATCH", 16))


def _spawn_replica(ps_port: int, rid: int, env: dict,
                   timeout_s: float = 60.0):
    """One replica OS process; returns (Popen, predict_port).  The replica
    announces its bound port as one JSON line on stdout."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "asyncframework_tpu.serving.cli", "replica",
         "--ps", f"127.0.0.1:{ps_port}", "--host", "127.0.0.1",
         "--rid", str(rid)],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env,
    )
    line_box = {}

    def read_line():
        line_box["line"] = proc.stdout.readline()

    t = threading.Thread(target=read_line, name="bench-probe-read",
                         daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    line = line_box.get("line")
    if not line:
        proc.kill()
        raise RuntimeError(f"replica {rid} did not announce within "
                           f"{timeout_s:.0f}s")
    return proc, int(json.loads(line)["port"])


def _pcts(vals, nd=3):
    if not vals:
        return None
    v = sorted(vals)
    rank = lambda q: v[min(len(v) - 1, max(0, int(round(q * len(v))) - 1))]
    return {"p50": round(rank(0.50), nd), "p95": round(rank(0.95), nd),
            "p99": round(rank(0.99), nd), "max": round(v[-1], nd)}


def run_serve_child() -> None:
    """One fresh-process serving bench; prints one JSON line.

    Three arms: 1 replica, 2 replicas, and 2 replicas with one SIGKILLed
    mid-load.  Every arm runs with training concurrently advancing the
    model (the freshness-lag numbers are meaningless against a frozen
    PS), and records QPS, predict latency, freshness lag in versions AND
    ms, failovers, and the error rate."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import signal

    from asyncframework_tpu.data.sharded import ShardedDataset
    from asyncframework_tpu.metrics import reset_totals
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.serving import ServingFrontend
    from asyncframework_tpu.serving import metrics as smetrics
    from asyncframework_tpu.solvers import SolverConfig

    c = SERVE_CONFIG
    devices = jax.devices()
    ds = ShardedDataset.generate_on_device(
        c["n"], c["d"], c["nw"], devices=devices, seed=7, noise=0.01
    )
    shards = {w: ds.shard(w) for w in range(c["nw"])}
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["ASYNCTPU_FORCE_CPU"] = "1"
    rng = np.random.default_rng(3)
    X = rng.normal(size=(SERVE_BATCH, c["d"])).astype(np.float32)
    out = {}
    # replica count for the top arm comes from the declared knob (default
    # 2 keeps the historical r1/r2/r2_kill arms byte-identical); operators
    # bench wider via --conf async.serve.replicas / ASYNCTPU_ env
    from asyncframework_tpu.conf import SERVE_REPLICAS, global_conf

    n_top = max(1, int(global_conf().get(SERVE_REPLICAS)))
    arms = [("r1", 1, False)]
    if n_top > 1:
        arms.append((f"r{n_top}", n_top, False))
        # the kill arm needs a survivor to fail over to: with one replica
        # a SIGKILL measures a guaranteed outage, not failover
        arms.append((f"r{n_top}_kill", n_top, True))
    for label, n_rep, kill in arms:
        reset_totals()
        cfg = SolverConfig(
            num_workers=c["nw"], num_iterations=c["iters"],
            gamma=c["gamma"], taw=2**31 - 1, batch_rate=c["batch_rate"],
            bucket_ratio=0.5, printer_freq=10_000, coeff=0.0, seed=42,
            calibration_iters=20, run_timeout_s=SERVE_LOAD_S + 30.0,
        )
        ps = ps_dcn.ParameterServer(
            cfg, c["d"], c["n"], device=devices[0], port=0
        ).start()
        replicas = []
        try:
            for rid in range(n_rep):
                replicas.append(_spawn_replica(ps.port, rid, env))
            fe = ServingFrontend(
                [("127.0.0.1", port) for (_p, port) in replicas],
                deadline_s=1.0,
            ).start()
            # training runs CONCURRENTLY for the whole load window; the
            # worker deadline, not the iteration budget, ends it
            trainer = threading.Thread(
                target=ps_dcn.run_worker_process,
                args=("127.0.0.1", ps.port, list(range(c["nw"])), shards,
                      cfg, c["d"], c["n"]),
                kwargs=dict(deadline_s=SERVE_LOAD_S + 6.0),
                name=f"bench-serve-trainer-{label}", daemon=True,
            )
            trainer.start()
            # warm: first predict proves replicas refreshed and compiled
            warm_deadline = time.monotonic() + 30.0
            while True:
                try:
                    fe.predict(X)
                    break
                except Exception:
                    if time.monotonic() > warm_deadline:
                        raise
                    time.sleep(0.1)
            accepted0 = ps.accepted
            # counter baseline AFTER warm-up: boot-window failovers
            # (replicas still compiling/refreshing) must not pollute the
            # load window's numbers -- nonzero failovers is the KILL
            # arm's discriminator
            totals0 = smetrics.serving_totals()
            stats_lock = threading.Lock()
            oks, errs, lags_v, lags_ms, lat_ms = [0], [0], [], [], []
            stop_at = time.monotonic() + SERVE_LOAD_S
            kill_at = time.monotonic() + SERVE_LOAD_S / 2.0

            def client_loop():
                while time.monotonic() < stop_at:
                    t0 = time.monotonic()
                    try:
                        _y, meta = fe.predict_ex(X)
                    except Exception:
                        with stats_lock:
                            errs[0] += 1
                        continue
                    with stats_lock:
                        oks[0] += 1
                        lags_v.append(meta["lag_versions"])
                        lags_ms.append(meta["lag_ms"])
                        lat_ms.append((time.monotonic() - t0) * 1e3)

            clients = [threading.Thread(target=client_loop,
                                        name=f"bench-serve-client-{i}",
                                        daemon=True)
                       for i in range(SERVE_CLIENTS)]
            for t in clients:
                t.start()
            if kill:
                while time.monotonic() < kill_at:
                    time.sleep(0.01)
                os.kill(replicas[0][0].pid, signal.SIGKILL)
            for t in clients:
                t.join(timeout=SERVE_LOAD_S + 10.0)
            accepted_during = ps.accepted - accepted0
            totals = smetrics.serving_totals()
            n_ok, n_err = oks[0], errs[0]
            out[label] = {
                "replicas": n_rep,
                "killed_mid_load": kill,
                "load_s": SERVE_LOAD_S,
                "clients": SERVE_CLIENTS,
                "batch": SERVE_BATCH,
                "predicts": n_ok,
                "errors": n_err,
                "error_rate": round(n_err / max(n_ok + n_err, 1), 4),
                "qps": round(n_ok / SERVE_LOAD_S, 1),
                "rows_per_sec": round(n_ok * SERVE_BATCH / SERVE_LOAD_S),
                "failovers": (totals.get("failovers", 0)
                              - totals0.get("failovers", 0)),
                "unhealthy_rejects": (
                    totals.get("unhealthy_rejects", 0)
                    - totals0.get("unhealthy_rejects", 0)
                ),
                "predict_ms": _pcts(lat_ms),
                "lag_versions": _pcts(lags_v, nd=0),
                "lag_ms": _pcts(lags_ms),
                "train_accepted_during_load": accepted_during,
                "train_updates_per_sec": round(
                    accepted_during / SERVE_LOAD_S, 1
                ),
                "subscribe_replies": dict(ps.subscribe_replies),
            }
            print(f"# serve {label}: {json.dumps(out[label])}",
                  file=sys.stderr)
            fe.stop()
        finally:
            for proc, _port in replicas:
                try:
                    proc.kill()
                except OSError:
                    pass
            ps.stop()
    emit({"serve": out})


def collect_serve_block(env: dict) -> dict:
    """Run the serving bench in a disposable subprocess (fresh process,
    parent owns the timeout -- the same discipline as every arm)."""
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--serve"],
            capture_output=True, text=True, timeout=420, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": "serve bench timed out"}
    sys.stderr.write(res.stderr)
    line = next((l for l in reversed(res.stdout.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return {"error": f"no JSON from serve child (rc={res.returncode})"}
    return json.loads(line).get("serve", {"error": "malformed serve payload"})


# --------------------------------------------------------------- relay bench
# Relaycast wire bench (ISSUE 12; always CPU -- it measures wire bytes,
# not chips): an in-process PS plus N relay sources driven
# DETERMINISTICALLY (topo order per version, no background loops), so
# the byte counters are exact.  Three distribution arms -- direct
# SUBSCRIBE (the N x control), relay tree raw, relay tree compressed --
# plus the quantized-PUSH codec arm (off/fp16/int8 wire bytes per
# update).  Never-dark: each arm records its error instead of killing
# the block.
RELAY_REPLICAS = int(os.environ.get("BENCH_RELAY_REPLICAS", 8))
RELAY_VERSIONS = int(os.environ.get("BENCH_RELAY_VERSIONS", 18))


def run_relay_child() -> None:
    import numpy as np

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from asyncframework_tpu.metrics import profiler as prof_mod
    from asyncframework_tpu.metrics import reset_totals
    from asyncframework_tpu.net import wirecodec
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.relaycast import (
        ROOT,
        RelayNode,
        RelaySource,
        parent_index,
    )
    from asyncframework_tpu.relaycast import metrics as rmetrics
    from asyncframework_tpu.solvers import SolverConfig

    d, n = 4096, 1024
    fanout = 2

    def make_ps():
        cfg = SolverConfig(
            num_workers=2, num_iterations=10_000, gamma=0.5,
            taw=2 ** 31 - 1, batch_rate=0.3, bucket_ratio=0.0,
            printer_freq=1000, seed=42, calibration_iters=4,
            run_timeout_s=120.0,
        )
        return ps_dcn.ParameterServer(cfg, d, n, port=0).start()

    def push_version(cl, rng, v):
        ts, _w, _avg, _cal = cl.pull(0)
        # decaying update magnitudes: versions sweep from the hard
        # near-incompressible early regime (big random updates) into
        # the converged regime a serving fleet actually lives in (tiny
        # relative updates) -- the steady-state tail is reported
        # separately below
        scale = 0.5 * (0.45 ** v) + 1e-5
        cl.push(0, ts, (scale * rng.normal(size=d)).astype(np.float32))

    def distribution_arm(relay: bool, compress: bool) -> dict:
        reset_totals()
        ps = make_ps()
        nodes, sources = [], []
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full")
            for rid in range(RELAY_REPLICAS):
                node = RelayNode(rid=rid, port=0,
                                 compress=compress).start()
                p = parent_index(rid, fanout)
                parent = (None if (not relay or p == ROOT)
                          else ("127.0.0.1", nodes[p].port))
                nodes.append(node)
                sources.append(RelaySource("127.0.0.1", ps.port, node,
                                           parent=parent, rid=rid))
            rng = np.random.default_rng(7)
            fetch_by_version = []
            prev_fetch = 0
            for v in range(RELAY_VERSIONS):
                push_version(cl, rng, v)
                for rid in range(RELAY_REPLICAS):
                    got = sources[rid].subscribe(rid)
                    assert got[0] == v + 1
                cur = rmetrics.relay_totals().get("fetch_bytes_out", 0)
                fetch_by_version.append(cur - prev_fetch)
                prev_fetch = cur
            rt = rmetrics.relay_totals()
            ct = wirecodec.codec_totals()
            out = {
                "ps_subscribe_bytes_per_version":
                    round(ps.subscribe_model_bytes / RELAY_VERSIONS),
                "ps_subscribe_replies": dict(ps.subscribe_replies),
                "relay_fetch_bytes_per_version":
                    round(rt.get("fetch_bytes_out", 0) / RELAY_VERSIONS),
                "relay_fetch_bytes_by_version": fetch_by_version,
                "parent_fetches": rt.get("parent_fetches", 0),
                "root_fallbacks": rt.get("root_fallbacks", 0),
            }
            if ct.get("snap_bytes_wire"):
                out["snap_compression_ratio"] = round(
                    ct["snap_bytes_raw"] / ct["snap_bytes_wire"], 2)
            return out
        finally:
            for node in nodes:
                node.stop()
            ps.stop()

    def codec_arm(codec: str) -> dict:
        # reset_totals() clears every registry family, including the
        # profiler's -- so this arm's profile block is arm-local, and
        # `bin/async-prof --diff` between the codec-on and codec-off
        # arms shows wire.quantize only where encode_grad actually ran
        prof_mod.install("bench-relay", hz=197.0)
        reset_totals()
        ps = make_ps()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full",
                                 push_codec=codec)
            rng = np.random.default_rng(11)
            K = 40
            for v in range(K):
                push_version(cl, rng, v % 8)
            return {
                "push_payload_bytes_per_update":
                    round(ps.push_bytes / K),
                "accepted": ps.accepted,
                "profile": profile_block(prof_mod, {}),
            }
        finally:
            ps.stop()

    out = {"replicas": RELAY_REPLICAS, "versions": RELAY_VERSIONS,
           "d": d, "fanout": fanout, "platform": "cpu",
           "arms": {}, "codec": {}}
    for name, (relay, compress) in (
            ("direct", (False, False)),
            ("relay_raw", (True, False)),
            ("relay_z", (True, True))):
        try:
            out["arms"][name] = distribution_arm(relay, compress)
        except Exception as e:  # noqa: BLE001 - never-dark discipline
            out["arms"][name] = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"}
    raw_bv = out["arms"].get("relay_raw", {}).get(
        "relay_fetch_bytes_by_version")
    z_bv = out["arms"].get("relay_z", {}).get(
        "relay_fetch_bytes_by_version")
    if raw_bv and z_bv:
        # steady-state compression: the converged-regime tail (last
        # half of the deterministic schedule), which is the serving
        # fleet's actual operating point; the whole-run average above
        # includes the incompressible warm-up transient
        half = len(raw_bv) // 2
        raw_tail, z_tail = sum(raw_bv[half:]), sum(z_bv[half:])
        if z_tail > 0:
            out["steady_state_compression_ratio"] = round(
                raw_tail / z_tail, 2)
    for codec in ("off", "fp16", "int8"):
        try:
            out["codec"][codec] = codec_arm(codec)
        except Exception as e:  # noqa: BLE001 - never-dark discipline
            out["codec"][codec] = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"}
    emit({"relay": out})


def collect_relay_block(env: dict) -> dict:
    """Run the relaycast bench in a disposable subprocess (fresh
    process, parent owns the timeout -- the discipline of every arm)."""
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--relay"],
            capture_output=True, text=True, timeout=420, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": "relay bench timed out"}
    sys.stderr.write(res.stderr)
    line = next((l for l in reversed(res.stdout.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return {"error": f"no JSON from relay child (rc={res.returncode})"}
    return json.loads(line).get("relay",
                                {"error": "malformed relay payload"})


def run_native_child() -> None:
    """Native data-plane bench (PR 19, CPU loopback, device-independent):
    python vs native per wire-codec unit (bytes/s per core), DCN
    updates/s with the codecs in the loop, and shm-ring vs loopback-TCP
    transport throughput.  Per-pass profiler snapshots ride the payload
    so `bin/async-prof --diff` shows the wire.* zone shares shrinking."""
    import numpy as np

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    from asyncframework_tpu import conf as _conf
    from asyncframework_tpu.metrics import profiler as prof_mod
    from asyncframework_tpu.metrics import reset_totals
    from asyncframework_tpu.native_build import ensure_built, native_totals
    from asyncframework_tpu.net import wirecodec, wiredelta
    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.solvers import SolverConfig

    built = all(ensure_built(n) is not None
                for n in ("wiredelta", "wirecodec", "shmring"))
    cf = _conf.global_conf()
    prof_mod.install("bench-native", hz=197.0)

    # ------------------------------------------------ codec micro units
    d = 1 << 20  # 4 MiB f32: big enough that per-call overhead vanishes
    rng = np.random.default_rng(3)
    basis = rng.normal(size=d).astype(np.float32)
    cur = basis.copy()
    touched = rng.choice(d, size=d // 50, replace=False)
    cur[touched] += rng.normal(size=touched.size).astype(np.float32)
    cur_bytes = cur.tobytes()
    grad = (0.01 * rng.normal(size=d)).astype(np.float32)
    want_crc = wiredelta.crc(cur_bytes)  # backend-independent by contract

    def timed_mb_s(fn, nbytes: float, budget_s: float = 0.2) -> float:
        fn()  # warm: first-dispatch costs (CDLL config, allocations)
        reps, t0 = 0, time.perf_counter()
        while True:
            fn()
            reps += 1
            dt = time.perf_counter() - t0
            if dt >= budget_s:
                return round(nbytes * reps / dt / 1e6, 1)

    wenc, dpayload, nnz = wiredelta.encode(cur, basis, cur_bytes=cur_bytes)
    fhdr, fpay, _ = wirecodec.encode_grad(grad, "fp16", None)
    ihdr, ipay, _ = wirecodec.encode_grad(grad, "int8", None)
    units = {
        "crc": (lambda: wiredelta.crc(cur_bytes), d * 4),
        "delta_encode": (
            lambda: wiredelta.encode(cur, basis, cur_bytes=cur_bytes),
            d * 4),
        "delta_decode": (
            lambda: wiredelta.decode(wenc, dpayload, nnz, basis, want_crc),
            d * 4),
        "fp16_encode": (
            lambda: wirecodec.encode_grad(grad, "fp16", None), d * 4),
        "fp16_decode": (
            lambda: wirecodec.decode_grad(fhdr, fpay, d), d * 4),
        "int8_encode": (
            lambda: wirecodec.encode_grad(grad, "int8", None), d * 4),
        "int8_decode": (
            lambda: wirecodec.decode_grad(ihdr, ipay, d), d * 4),
        "shuffle4": (
            lambda: wirecodec._shuffle4(cur_bytes), d * 4),
    }

    backends = ["python"] + (["native"] if built else [])
    codec_out: dict = {u: {} for u in units}
    prof_out: dict = {}
    for backend in backends:
        cf.set("async.native.enabled", backend == "native")
        reset_totals()
        for unit, (fn, nbytes) in units.items():
            try:
                codec_out[unit][f"{backend}_mb_s"] = timed_mb_s(fn, nbytes)
            except Exception as e:  # noqa: BLE001 - never-dark per unit
                codec_out[unit][f"{backend}_error"] = (
                    f"{type(e).__name__}: {str(e)[:120]}")
        prof_out[backend] = profile_block(prof_mod, {})
        prof_out[backend]["native_totals"] = native_totals()
    for unit, row in codec_out.items():
        if row.get("python_mb_s") and row.get("native_mb_s"):
            row["speedup"] = round(row["native_mb_s"] / row["python_mb_s"],
                                   2)

    # ------------------------------------------- DCN loop with codecs in
    dcn_d, pushes, pulls = 1 << 18, 120, 60

    def make_ps():
        scfg = SolverConfig(
            num_workers=2, num_iterations=10_000, gamma=0.5,
            taw=2 ** 31 - 1, batch_rate=0.3, bucket_ratio=0.0,
            printer_freq=1000, seed=42, calibration_iters=4,
            run_timeout_s=120.0,
        )
        return ps_dcn.ParameterServer(scfg, dcn_d, 1024, port=0).start()

    def dcn_pass(codec: str, shm: bool) -> dict:
        ps = make_ps()
        try:
            cl = ps_dcn.PSClient("127.0.0.1", ps.port, pull_mode="full",
                                 push_codec=codec, shm=shm)
            g = (0.01 * np.random.default_rng(5).normal(size=dcn_d)
                 ).astype(np.float32)
            ts, _w, _avg, _cal = cl.pull(0)
            t0 = time.perf_counter()
            for _ in range(pushes):
                cl.push(0, ts, g)
            push_dt = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(pulls):
                ts, _w, _avg, _cal = cl.pull(0)
            pull_dt = time.perf_counter() - t0
            return {
                "push_updates_s": round(pushes / push_dt, 1),
                "pull_mb_s": round(pulls * dcn_d * 4 / pull_dt / 1e6, 1),
            }
        finally:
            ps.stop()

    dcn_out: dict = {}
    for backend in backends:
        cf.set("async.native.enabled", backend == "native")
        for codec in ("off", "int8"):
            try:
                dcn_out[f"{backend}_{codec}"] = dcn_pass(codec, shm=False)
            except Exception as e:  # noqa: BLE001 - never-dark per arm
                dcn_out[f"{backend}_{codec}"] = {
                    "error": f"{type(e).__name__}: {str(e)[:200]}"}

    # ------------------------------------- shm ring vs loopback transport
    shm_out: dict = {}
    cf.set("async.native.enabled", built)
    for label, use_shm in (("tcp", False), ("shm", True)):
        cf.set("async.shm.enabled", use_shm)
        reset_totals()
        try:
            shm_out[label] = dcn_pass("off", shm=use_shm)
            nt = native_totals()
            if use_shm:
                shm_out[label]["upgrades"] = nt.get("shm_upgrades", 0)
                shm_out[label]["frames"] = nt.get("shm_frames_sent", 0)
        except Exception as e:  # noqa: BLE001 - never-dark per arm
            shm_out[label] = {
                "error": f"{type(e).__name__}: {str(e)[:200]}"}
    cf.set("async.shm.enabled", False)
    for key in ("push_updates_s", "pull_mb_s"):
        t, s = shm_out.get("tcp", {}).get(key), shm_out.get(
            "shm", {}).get(key)
        if t and s:
            shm_out[f"{key}_speedup"] = round(s / t, 2)
    # a sub-1x shm speedup on cpus=1 is a scheduling artifact, not a
    # transport regression: two user-space ring endpoints cannot overlap
    # their copies on one core, while loopback TCP hands off through
    # kernel buffers with exact wakeups.  Record the count so artifacts
    # from single-core CI boxes explain themselves.
    shm_out["cpus"] = os.cpu_count()

    emit({"native": {
        "built": built, "platform": "cpu", "d_codec": d, "d_dcn": dcn_d,
        "codec": codec_out, "dcn": dcn_out, "shm": shm_out,
        "profile": prof_out,
    }})


def collect_native_block(env: dict) -> dict:
    """Run the native data-plane bench in a disposable subprocess (same
    never-dark discipline as every arm)."""
    try:
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--native"],
            capture_output=True, text=True, timeout=300, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": "native bench timed out"}
    sys.stderr.write(res.stderr)
    line = next((l for l in reversed(res.stdout.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return {"error": f"no JSON from native child (rc={res.returncode})"}
    return json.loads(line).get("native",
                                {"error": "malformed native payload"})


def run_probe() -> None:
    """Cheap backend-liveness check in a disposable process: init the backend
    and print one JSON line.  A dead TPU tunnel wedges jax.devices() forever
    in C code (round 3: 600s x 2 configs burned, rc=124), so the PARENT owns
    the timeout and this child just tries."""
    import jax

    forced = os.environ.get("BENCH_PLATFORM")
    if forced:
        jax.config.update("jax_platforms", forced)
    t0 = time.monotonic()
    devices = jax.devices()
    emit({"probe": True, "platform": devices[0].platform,
          "n_devices": len(devices), "init_s": round(time.monotonic() - t0, 1)})


# Probe FAILURES are cached per target platform for the life of this
# invocation: a dead TPU tunnel costs 2 x 75 s ONCE, not once per config /
# per fallback pass (BENCH_r05 burned the probe budget repeatedly before
# every CPU fallback).  Successes are deliberately NOT cached -- the
# wedge path re-probes precisely to detect a device link that died mid-run.
_PROBE_FAILURES: dict = {}


def _reap_detached(proc: subprocess.Popen) -> None:
    """Reap a killed probe child WITHOUT ever blocking the parent: the
    post-kill communicate() can hang forever when a grandchild inherited
    the pipe fds (the exact wedge the probe exists to detect), so it
    runs on a throwaway daemon thread."""
    def reap():
        try:
            proc.communicate(timeout=10)
        except Exception:  # noqa: BLE001 - best-effort cleanup only
            pass

    threading.Thread(target=_guarded(reap, "bench-probe-reap"),
                     name="bench-probe-reap", daemon=True).start()


def probe_backend(env: dict) -> Tuple[bool, str]:
    """Run the probe subprocess with a hard per-attempt timeout, bounded
    retries, AND a hard bound on the whole probe (BENCH_PROBE_BUDGET_S):
    whatever a dead device link does to the children, the probe itself
    returns within the budget.  Returns (alive, note); a failure is
    memoized per platform."""
    platform = env.get("BENCH_PLATFORM") or "default"
    cached = _PROBE_FAILURES.get(platform)
    if cached is not None:
        print(f"# backend probe: cached failure for platform "
              f"{platform!r} -- {cached[1]}", file=sys.stderr)
        return cached
    deadline = time.monotonic() + PROBE_BUDGET_S
    attempts_run = 0
    for attempt in range(1, PROBE_ATTEMPTS + 1):
        left = deadline - time.monotonic()
        if left <= 1.0:
            print(f"# backend probe: budget {PROBE_BUDGET_S:.0f}s "
                  f"exhausted after {attempts_run} attempt(s)",
                  file=sys.stderr)
            break
        attempts_run = attempt
        t0 = time.monotonic()
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        try:
            out_s, err_s = proc.communicate(
                timeout=min(PROBE_TIMEOUT_S, left)
            )
        except subprocess.TimeoutExpired:
            proc.kill()
            _reap_detached(proc)
            print(f"# backend probe {attempt}/{PROBE_ATTEMPTS}: hung past "
                  f"{min(PROBE_TIMEOUT_S, left):.0f}s (dead device link)",
                  file=sys.stderr)
            continue
        line = next((l for l in reversed(out_s.splitlines())
                     if l.startswith("{")), None)
        if line is not None and json.loads(line).get("probe"):
            rec = json.loads(line)
            note = (f"{rec['platform']} x{rec['n_devices']} "
                    f"(init {rec['init_s']}s)")
            print(f"# backend probe {attempt}: up -- {note} "
                  f"({time.monotonic() - t0:.0f}s)", file=sys.stderr)
            return True, note
        print(f"# backend probe {attempt}/{PROBE_ATTEMPTS}: rc="
              f"{proc.returncode} stderr tail: {err_s[-300:]}",
              file=sys.stderr)
    failed = (False,
              f"backend unavailable: {attempts_run} probe attempts "
              f"failed/hung inside the {PROBE_BUDGET_S:.0f}s budget")
    _PROBE_FAILURES[platform] = failed
    return failed


# -------------------------------------------------------------------- parent
def median_or_none(xs):
    return round(statistics.median(xs), 3) if xs else None


def run_fallback(names, deadline) -> dict:
    """Labeled CPU fallback when the TPU backend is dead (VERDICT r4 #1):
    run the SAME engine hot path on the host CPU backend at reduced scale so
    the round's artifact carries real engine rates instead of nulls.  Every
    field is marked not-TPU; these numbers never stand in for the metric of
    record."""
    env = dict(os.environ)
    env["BENCH_PLATFORM"] = "cpu"
    env["BENCH_SCALE"] = "fallback"
    env["BENCH_FUSED"] = env.get("BENCH_FUSED", "1")
    alive, note = probe_backend(env)
    block = {
        "platform": "cpu",
        "warning": "NOT TPU -- host CPU backend at reduced scale; "
                   "engine+fused rates for liveness evidence only",
        "configs": {},
    }
    if not alive:
        block["warning"] = f"cpu fallback probe failed too: {note}"
        return block
    for name in names:
        if time.monotonic() > deadline:
            block["configs"][name] = {"ok": False,
                                      "skipped": "budget exhausted"}
            continue
        t0 = time.monotonic()
        try:
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--config", name],
                capture_output=True, text=True, timeout=CHILD_TIMEOUT_S,
                env=env,
            )
        except subprocess.TimeoutExpired:
            block["configs"][name] = {"ok": False, "note": "child timed out"}
            continue
        sys.stderr.write(out.stderr)
        line = next((l for l in reversed(out.stdout.splitlines())
                     if l.startswith("{")), None)
        if line is None:
            block["configs"][name] = {"ok": False,
                                      "note": f"no JSON (rc={out.returncode})"}
            continue
        rec = json.loads(line)
        print(f"# fallback {name}: {line} "
              f"({time.monotonic() - t0:.0f}s wall)", file=sys.stderr)
        keep = {k: rec.get(k) for k in (
            "ok", "t_hit", "k_hit", "updates_per_sec", "accepted",
            "elapsed_s", "gflops", "kernel_gflops", "kernel_ms_per_update",
            "fused", "note", "telemetry",
        )}
        block["configs"][name] = keep
    try:
        block["microbench"] = _fallback_microbench(env)
    except Exception as e:  # evidence-only: never fail the artifact on it
        block["microbench"] = {"error": f"{type(e).__name__}: {str(e)[:120]}"}
    return block


def _fallback_microbench(env: dict) -> dict:
    """Small rig microbenches for the fallback artifact: the 2M-pair
    wordcount through the dispatch-routed shuffle plane, and GROUP BY vs
    pandas -- the CPU-measurable halves of the round-5 perf story, captured
    in a driver artifact instead of round-log prose."""
    code = r"""
import json, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from asyncframework_tpu.ops.shuffle import host_reduce_by_key
from asyncframework_tpu.sql import ColumnarFrame

out = {}
rs = np.random.default_rng(1)
n, vocab, P = 2_000_000, 100_000, 8
keys = rs.integers(0, vocab, size=n).astype(np.int32)
vals = np.ones(n, np.float32)
per = n // P
blocks = {w: (keys[w*per:(w+1)*per], vals[w*per:(w+1)*per])
          for w in range(P)}
ts = []
for _ in range(3):
    t0 = time.perf_counter()
    host_reduce_by_key(blocks, op="sum")
    ts.append(time.perf_counter() - t0)
out["wordcount_2m_host_vectorized_s"] = round(sorted(ts)[1], 4)

k = rs.integers(0, 1000, size=2_000_000).astype(np.int64)
v = rs.normal(size=2_000_000).astype(np.float32)
f = ColumnarFrame({"k": k, "v": v})
ts = []
for _ in range(3):
    t0 = time.perf_counter()
    f.groupby("k").agg(s=("v", "sum"))
    ts.append(time.perf_counter() - t0)
out["groupby_2m_s"] = round(sorted(ts)[1], 4)
try:
    import pandas as pd
    df = pd.DataFrame({"k": k, "v": v})
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        df.groupby("k")["v"].sum()
        ts.append(time.perf_counter() - t0)
    out["groupby_2m_pandas_s"] = round(sorted(ts)[1], 4)
except Exception:
    pass
print(json.dumps(out))
"""
    res = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env=env,
    )
    line = next((l for l in reversed(res.stdout.splitlines())
                 if l.startswith("{")), None)
    if line is None:
        return {"error": f"rc={res.returncode}: {res.stderr[-200:]}"}
    return json.loads(line)


def trace_jsonl_path():
    """--trace-jsonl PATH (or BENCH_TRACE_JSONL env): capture each run's
    per-stage latency decomposition + staleness-in-ms alongside throughput,
    one JSONL record per child sample."""
    if "--trace-jsonl" in sys.argv:
        i = sys.argv.index("--trace-jsonl")
        if i + 1 < len(sys.argv):
            return sys.argv[i + 1]
    return os.environ.get("BENCH_TRACE_JSONL") or None


def run_parent() -> None:
    names = [
        s for s in os.environ.get(
            "BENCH_CONFIGS", "epsilon,mnist8m,rcv1"
        ).split(",") if s
    ]
    deadline = time.monotonic() + TOTAL_BUDGET_S
    samples = {name: [] for name in names}
    env = dict(os.environ)
    trace_out = trace_jsonl_path()
    if trace_out:
        env["BENCH_TRACE"] = "1"
    # liveness gate BEFORE spending any child budget: round 3 burned 600s x 2
    # on a dead tunnel and left rc=124 with nothing; a dead backend must
    # yield a documented partial artifact instead
    skip_note = None
    alive, note = probe_backend(env)
    if not alive:
        skip_note = note
    # round-robin repeats so every config gets one sample before the budget
    # can run out
    arm_spent = {name: 0.0 for name in names}  # per-arm watchdog ledger
    for rep in range(REPEATS):
        if skip_note is not None:
            break
        for name in names:
            have = len(samples[name])
            if rep > 0 and have == 0:
                continue  # config is failing; don't burn budget re-proving it
            if arm_spent[name] > ARM_BUDGET_S:
                # per-arm watchdog: this config already burned its own
                # budget (wedged children count their full timeout) --
                # the remaining arms keep their share of the total
                print(f"# arm budget exhausted for {name} "
                      f"({arm_spent[name]:.0f}s > {ARM_BUDGET_S:.0f}s); "
                      f"skipping repeat {rep}", file=sys.stderr)
                continue
            if time.monotonic() > deadline and have >= 1:
                print(f"# budget exhausted; skipping {name} repeat {rep}",
                      file=sys.stderr)
                continue
            t0 = time.monotonic()
            child_wedged = False
            try:
                out = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--config", name],
                    capture_output=True, text=True, timeout=CHILD_TIMEOUT_S,
                    env=env,
                )
            except subprocess.TimeoutExpired:
                print(f"# {name} rep {rep}: child timed out", file=sys.stderr)
                child_wedged = True
            arm_spent[name] += time.monotonic() - t0
            if not child_wedged:
                sys.stderr.write(out.stderr)
                line = next(
                    (l for l in reversed(out.stdout.splitlines())
                     if l.startswith("{")), None,
                )
                if line is None:
                    print(f"# {name} rep {rep}: no JSON from child "
                          f"(rc={out.returncode})", file=sys.stderr)
                    child_wedged = True
                else:
                    rec = json.loads(line)
                    print(f"# {name} rep {rep}: {line} "
                          f"({time.monotonic() - t0:.0f}s wall)",
                          file=sys.stderr)
                    if rec.get("ok"):
                        samples[name].append(rec)
                    elif "WATCHDOG" in str(rec.get("note", "")):
                        child_wedged = True
            if child_wedged:
                # a wedge usually means the device link died mid-run;
                # re-probe before burning another child on a dead backend
                alive, note = probe_backend(env)
                if not alive:
                    skip_note = note
                    break
        if skip_note is not None:
            break

    configs_out = {}
    ratios = []
    headline_value = None
    gflops = None
    mfu_out = None
    for name in names:
        recs = samples[name]
        if not recs:
            configs_out[name] = {"ok": False, "runs": 0}
            if skip_note is not None:
                configs_out[name]["skipped"] = skip_note
            continue
        med_ratio = median_or_none([r["vs_baseline"] for r in recs])
        med_t = median_or_none([r["t_hit"] for r in recs])
        configs_out[name] = {
            "ok": True,
            "runs": len(recs),
            "t_hit_median_s": med_t,
            "vs_baseline_median": med_ratio,
            "t_hit_all": [r["t_hit"] for r in recs],
            "vs_baseline_all": [r["vs_baseline"] for r in recs],
            "updates_per_sec_median": median_or_none(
                [r["updates_per_sec"] for r in recs]
            ),
            "gflops_median": median_or_none([r["gflops"] for r in recs]),
            "kernel_gflops_median": median_or_none(
                [r["kernel_gflops"] for r in recs
                 if r.get("kernel_gflops") is not None]
            ),
            "kernel_ms_per_update_median": median_or_none(
                [r["kernel_ms_per_update"] for r in recs
                 if r.get("kernel_ms_per_update") is not None]
            ),
            "mfu_median": median_or_none(
                [r["mfu"] for r in recs if r.get("mfu") is not None]
            ),
            "fused_updates_per_sec_median": median_or_none([
                r["fused"]["updates_per_sec"] for r in recs
                if r.get("fused") and "updates_per_sec" in r["fused"]
            ]),
            "fused_vs_baseline_median": median_or_none([
                r["fused"]["vs_baseline"] for r in recs
                if r.get("fused")
                and r["fused"].get("vs_baseline") is not None
            ]),
        }
        traced = [r["trace"] for r in recs if r.get("trace")]
        if traced:
            # latest sample's full decomposition rides the artifact: the
            # BENCH trajectory gains per-stage p50/p95/p99 + staleness-ms
            configs_out[name]["trace"] = traced[-1]
        telem = [r["telemetry"] for r in recs if r.get("telemetry")]
        if telem:
            # latest sample's convergence summary + SLO verdicts: the
            # artifact records statistical efficiency, not just updates/s
            configs_out[name]["telemetry"] = telem[-1]
        ratios.append(med_ratio)
        if name == "epsilon":
            headline_value = med_t
        if name == "mnist8m":
            gflops = configs_out[name]["gflops_median"]
            mfu_out = configs_out[name]["mfu_median"]
    if headline_value is None:  # epsilon failed: fall back to any config
        for name in names:
            if configs_out[name].get("ok"):
                headline_value = configs_out[name]["t_hit_median_s"]
                break
    if gflops is None:
        for name in names:
            if configs_out[name].get("ok"):
                gflops = configs_out[name]["gflops_median"]
                mfu_out = configs_out[name]["mfu_median"]
                break
    ok_all = all(configs_out[n].get("ok") for n in names)
    # a failed config contributes ratio 0.0: vs_baseline is defined as
    # "EVERY dataset beats its reference estimate by at least this factor",
    # so a partial failure must not report the min over survivors
    for n in names:
        if not configs_out[n].get("ok"):
            ratios.append(0.0)
    if ok_all:
        unit = "s"
    elif skip_note is not None and not any(
        configs_out[n].get("ok") for n in names
    ):
        unit = "s (SKIPPED: backend unavailable)"
    else:
        unit = "s (SOME CONFIGS FAILED)"
    payload = {
        "metric": "asgd_time_to_target_3datasets",
        "value": headline_value if headline_value is not None else 0.0,
        "unit": unit,
        "vs_baseline": round(min(ratios), 2) if ratios else 0.0,
        "configs": configs_out,
        "gflops": gflops,
        "mfu": mfu_out,
    }
    if skip_note is not None:
        payload["note"] = skip_note
    # the CPU arm is ALWAYS recorded when any TPU arm went dark --
    # whether the probe failed up front (skip_note) or children wedged /
    # failed one by one while the probe kept passing (the r03-r05 mode:
    # nothing but nulls in the artifact).  The fallback never stands in
    # for the metric of record; it keeps the trajectory from going dark.
    dark = [n for n in names if not samples[n]]
    if dark and os.environ.get("BENCH_FALLBACK", "1") != "0":
        payload["fallback"] = run_fallback(dark, deadline)
        payload["fallback"]["reason"] = (
            skip_note if skip_note is not None
            else f"no TPU samples for {','.join(dark)}"
        )
    if os.environ.get("BENCH_DCN", "1") != "0":
        # DCN data-plane bench (CPU loopback, device-independent): wire
        # bytes per update and pull/push payload shapes per pull mode
        payload["dcn"] = collect_dcn_block(env)
        if (os.environ.get("BENCH_FALLBACK", "1") != "0"
                and os.environ.get("BENCH_DCN_SHARDS", "1") != "0"
                and "shards" not in payload["dcn"]):
            # dead-arm keep-list discipline (PR 6): the sharded-PS arm is
            # part of the trajectory of record and must never go dark --
            # if the full dcn pass wedged or errored before reaching it,
            # retry JUST that arm (pipelined arms dropped) and graft the
            # result in, labeled
            env2 = dict(env)
            env2["BENCH_DCN_PIPELINE"] = "0"
            retry = collect_dcn_block(env2)
            if "shards" in retry:
                if not isinstance(payload["dcn"], dict) \
                        or "error" in payload["dcn"]:
                    payload["dcn"] = {"error": payload["dcn"].get("error")
                                      if isinstance(payload["dcn"], dict)
                                      else str(payload["dcn"])}
                payload["dcn"]["shards"] = retry["shards"]
                payload["dcn"]["shards_note"] = "recovered by retry pass"
        if os.environ.get("BENCH_DCN_MESH", "1") != "0":
            # mesh gradient-plane arm (ISSUE 11): single-device vs
            # 8-forced-host-device worker step on the dense config; its
            # own child so the forced device count cannot perturb the
            # other arms' shard placement
            if not isinstance(payload["dcn"], dict):
                payload["dcn"] = {"error": str(payload["dcn"])}
            payload["dcn"]["mesh"] = collect_dcn_mesh_block(env)
    if os.environ.get("BENCH_SERVE", "1") != "0":
        # serving-tier bench (CPU loopback): QPS vs freshness lag per
        # replica count with training concurrently running, including the
        # SIGKILL-a-replica-mid-load failover arm
        payload["serve"] = collect_serve_block(env)
    if os.environ.get("BENCH_RELAY", "1") != "0":
        # relaycast wire bench (ISSUE 12, CPU loopback): PS subscribe
        # egress per distributed version -- direct (N x control) vs
        # relay tree raw vs compressed -- plus quantized-PUSH wire
        # bytes per update per codec
        payload["relay"] = collect_relay_block(env)
    if os.environ.get("BENCH_NATIVE", "1") != "0":
        # native data-plane bench (PR 19, CPU loopback): python vs
        # native per codec unit, DCN updates/s with the codecs in the
        # loop, shm-ring vs loopback transport throughput
        payload["native"] = collect_native_block(env)
    if trace_out:
        with open(trace_out, "w") as f:
            for name in names:
                for rep, rec in enumerate(samples[name]):
                    if rec.get("trace"):
                        f.write(json.dumps({
                            "config": name, "rep": rep,
                            "updates_per_sec": rec.get("updates_per_sec"),
                            "trace": rec["trace"],
                        }) + "\n")
        payload["trace_jsonl"] = trace_out
    emit(payload)


def main() -> None:
    if "--dcn-mesh" in sys.argv:
        try:
            run_dcn_mesh_child()
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"dcn_mesh":
                  {"error": f"{type(e).__name__}: {str(e)[:200]}"}})
        os._exit(0)
    if "--dcn" in sys.argv:
        try:
            run_dcn_child()
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"dcn": {"error": f"{type(e).__name__}: {str(e)[:200]}"}})
        os._exit(0)
    if "--serve" in sys.argv:
        try:
            run_serve_child()
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"serve": {"error": f"{type(e).__name__}: {str(e)[:200]}"}})
        os._exit(0)
    if "--relay" in sys.argv:
        try:
            run_relay_child()
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"relay": {"error": f"{type(e).__name__}: {str(e)[:200]}"}})
        os._exit(0)
    if "--native" in sys.argv:
        try:
            run_native_child()
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"native":
                  {"error": f"{type(e).__name__}: {str(e)[:200]}"}})
        os._exit(0)
    if "--probe" in sys.argv:
        # parent owns the timeout; nothing here may block interpreter exit
        try:
            run_probe()
        except Exception as e:
            emit({"probe": False,
                  "note": f"{type(e).__name__}: {str(e)[:200]}"})
        os._exit(0)
    if "--config" in sys.argv:
        name = sys.argv[sys.argv.index("--config") + 1]
        arm_watchdog(name)
        try:
            run_child(name)
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"config": name, "ok": False,
                  "note": f"FAILED: {type(e).__name__}: {str(e)[:200]}"})
            sys.exit(0)
    else:
        try:
            run_parent()
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            emit({"metric": "asgd_time_to_target_3datasets", "value": 0.0,
                  "unit": f"s (FAILED: {type(e).__name__}: {str(e)[:200]})",
                  "vs_baseline": 0.0})
            sys.exit(0)


if __name__ == "__main__":
    main()
