#!/usr/bin/env python
"""Benchmark: ASGD wall-clock to target objective on an epsilon-shaped problem.

Metric of record (BASELINE.md): wall-clock to target loss, asynchronous SGD.
The reference repo publishes recipes but no absolute numbers (its figures live
in the IPDPS 2020 paper, arXiv:1907.08526).  BASELINE_S below is the
paper-scale estimate for the 8-worker Spark CPU cluster reaching the target
objective band on epsilon (figures 3-4 place it at O(100 s) wall-clock for the
async runs); it is fixed so rounds are comparable against one number.

Workload: epsilon-shaped planted least squares (400k x 2000 dense f32,
generated directly in device HBM -- this container's host<->device link is a
high-latency tunnel, and shipping 3.2 GB through it would benchmark the
tunnel, not the framework).  Target: reduce the mean objective to 1% of its
initial value, i.e. into the planted noise floor's decade.

The run exercises the REAL framework hot path: executor threads, result
queue, tau filter, partial barrier, versioned model handles, on-device updates
-- 8 logical workers on however many chips are attached (1 in this harness).

Output: ONE json line {"metric", "value", "unit", "vs_baseline"};
vs_baseline > 1 means faster than the reference estimate.
"""

import json
import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")

from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.ops import steps
from asyncframework_tpu.solvers import ASGD, SolverConfig

N, D = 400_000, 2_000
NUM_WORKERS = 8
BASELINE_S = 120.0  # paper-scale estimate: 8-worker Spark CPU ASGD on epsilon
TARGET_FRACTION = 0.01


def main() -> None:
    devices = jax.devices()
    t0 = time.monotonic()
    ds = ShardedDataset.generate_on_device(
        N, D, NUM_WORKERS, devices=devices, seed=7, noise=0.01
    )
    for w in range(NUM_WORKERS):
        ds.shard(w).y.block_until_ready()
    gen_s = time.monotonic() - t0
    print(f"# data: {N}x{D} generated on device in {gen_s:.1f}s", file=sys.stderr)

    cfg = SolverConfig(
        num_workers=NUM_WORKERS,
        num_iterations=60_000,
        gamma=6.0,
        taw=2**31 - 1,
        batch_rate=0.1,
        bucket_ratio=0.7,
        printer_freq=250,
        coeff=0.0,
        seed=42,
        calibration_iters=100,
        run_timeout_s=600.0,
    )
    solver = ASGD(ds, None, cfg, devices=devices)

    # warm the XLA compile caches outside the timed region (the reference's
    # first blocking iteration plays the same role for Spark's caches)
    shard = ds.shard(0)
    key = jax.random.PRNGKey(0)
    g, _ = solver._step(shard.X, shard.y, jax.device_put(
        np.zeros(D, np.float32), devices[0]), key)
    solver._apply(
        jax.device_put(np.zeros(D, np.float32), devices[0]),
        jax.device_put(g, devices[0]),
        jax.device_put(np.float32(0), devices[0]),
    )
    print("# compile warm-up done", file=sys.stderr)

    res = solver.run()

    # wall-clock to target from the evaluated trajectory
    initial = res.trajectory[0][1]
    target = initial * TARGET_FRACTION
    t_hit = None
    for t_ms, obj in res.trajectory:
        if obj <= target:
            t_hit = t_ms / 1e3
            break
    print(
        f"# accepted={res.accepted} dropped={res.dropped} rounds={res.rounds} "
        f"updates/s={res.updates_per_sec:.0f} max_staleness={res.max_staleness} "
        f"elapsed={res.elapsed_s:.1f}s obj {initial:.4f}->{res.trajectory[-1][1]:.6f} "
        f"target={target:.6f} t_hit={t_hit}",
        file=sys.stderr,
    )
    if t_hit is None:
        # did not reach target: report elapsed as value with penalty ratio
        print(json.dumps({
            "metric": "asgd_epsilon_time_to_target",
            "value": round(res.elapsed_s, 2),
            "unit": "s (TARGET NOT REACHED)",
            "vs_baseline": 0.0,
        }))
        return
    print(json.dumps({
        "metric": "asgd_epsilon_time_to_target",
        "value": round(t_hit, 2),
        "unit": "s",
        "vs_baseline": round(BASELINE_S / t_hit, 2),
    }))


if __name__ == "__main__":
    main()
