// Fast LibSVM text parser (C ABI, loaded via ctypes).
//
// TPU-native equivalent of the reference's data-ingest hot path: there,
// MLUtils.loadLibSVMFile parses "label idx:val ..." lines inside Spark tasks
// on the JVM with Hadoop native I/O underneath
// (mllib/.../util/MLUtils.scala:71); here a single C++ pass over the mmap'd
// buffer fills a dense row-major float32 matrix directly -- the host-side
// feeder for device HBM uploads.  Indices are 1-based per the format.
//
// Exported functions:
//   count_lines(buf, len)                        -> number of data lines
//   parse_libsvm_dense(buf, len, d, X, y, max)   -> rows parsed, or -errno:
//       -1 bad label, -2 bad index token, -3 index out of range [1, d],
//       -4 row overflow (more data lines than max_rows)
//
// The parser is deliberately strtod/strtoll-free on the fast path: feature
// values use a hand-rolled float scan (digits, optional '.', optional
// exponent) that falls back to strtod for rare forms, which is what makes it
// an order of magnitude faster than line-splitting in Python.

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

static inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// Parse a float starting at *pp; advance *pp past it.  Returns NaN-free
// result; uses strtod fallback for unusual forms (hex, inf, nan).
static double scan_float(const char** pp, const char* end, bool* ok) {
  const char* p = *pp;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  double val = 0.0;
  bool any = false;
  while (p < end && *p >= '0' && *p <= '9') {
    val = val * 10.0 + (*p - '0');
    any = true;
    ++p;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      val += (*p - '0') * scale;
      scale *= 0.1;
      any = true;
      ++p;
    }
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      ++p;
    }
    int ex = 0;
    bool eany = false;
    while (p < end && *p >= '0' && *p <= '9') {
      // clamp: anything past float range over/underflows anyway, and an
      // unchecked accumulator would overflow int on hostile input
      if (ex < 10000) ex = ex * 10 + (*p - '0');
      eany = true;
      ++p;
    }
    if (!eany) {
      *ok = false;
      return 0.0;
    }
    double f = 1.0;
    double base = eneg ? 0.1 : 10.0;
    while (ex) {
      if (ex & 1) f *= base;
      base *= base;
      ex >>= 1;
    }
    val *= f;
  }
  if (!any) {
    // fall back to strtod for forms the fast scan rejects
    char tmp[64];
    size_t n = (size_t)(end - *pp);
    if (n > 63) n = 63;
    memcpy(tmp, *pp, n);
    tmp[n] = 0;
    char* q = nullptr;
    double v = strtod(tmp, &q);
    if (q == tmp) {
      *ok = false;
      return 0.0;
    }
    *pp += (q - tmp);
    *ok = true;
    return v;
  }
  *pp = p;
  *ok = true;
  return neg ? -val : val;
}

long long count_lines(const char* buf, long long len) {
  long long n = 0;
  const char* p = buf;
  const char* end = buf + len;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    const char* line_end = nl ? nl : end;
    const char* q = skip_ws(p, line_end);
    if (q < line_end && *q != '#') ++n;  // non-empty, non-comment
    p = nl ? nl + 1 : end;
  }
  return n;
}

long long parse_libsvm_dense(const char* buf, long long len, long long d,
                             float* X, float* y, long long max_rows) {
  const char* p = buf;
  const char* end = buf + len;
  long long row = 0;
  while (p < end) {
    const char* nl = (const char*)memchr(p, '\n', (size_t)(end - p));
    const char* line_end = nl ? nl : end;
    const char* q = skip_ws(p, line_end);
    if (q >= line_end || *q == '#') {  // blank or comment line
      p = nl ? nl + 1 : end;
      continue;
    }
    if (row >= max_rows) return -4;
    bool ok = false;
    double label = scan_float(&q, line_end, &ok);
    if (!ok) return -1;
    y[row] = (float)label;
    float* xrow = X + row * d;
    for (;;) {
      q = skip_ws(q, line_end);
      if (q >= line_end || *q == '#') break;
      // index; clamp the accumulator (like scan_float's exponent) so a
      // hostile digit run cannot overflow signed arithmetic (UB) -- any
      // clamped value already exceeds every valid d and fails the range check
      long long idx = 0;
      bool iany = false;
      while (q < line_end && *q >= '0' && *q <= '9') {
        if (idx <= (long long)d) idx = idx * 10 + (*q - '0');
        iany = true;
        ++q;
      }
      if (!iany || q >= line_end || *q != ':') return -2;
      ++q;  // ':'
      double v = scan_float(&q, line_end, &ok);
      if (!ok) return -2;
      if (idx < 1 || idx > d) return -3;
      xrow[idx - 1] = (float)v;
    }
    ++row;
    p = nl ? nl + 1 : end;
  }
  return row;
}

}  // extern "C"
