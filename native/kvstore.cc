// Append-only log-structured key/value store (C ABI, loaded via ctypes).
//
// TPU-native equivalent of the reference's LevelDB-backed kvstore
// (common/kvstore, leveldbjni in pom.xml:468) that holds app-status/history
// state.  Same design point -- a small embedded persistent KV used by
// observability, not the data path -- implemented as the simplest durable
// structure: an append-only record log with an in-memory hash index, plus
// compaction.  The Python fallback (storage/kvstore.py) speaks the identical
// file format, so stores are interchangeable between the two readers.
//
// File format (little-endian):
//   magic "AKV1" (4 bytes)
//   records: [u32 keylen][u32 vallen][key][val]
//            vallen == 0xFFFFFFFF marks a tombstone (no val bytes follow).
// A torn final record (crash mid-append) is detected by length checks and
// ignored on open.
//
// Exported C API (all lengths in bytes, handles are opaque pointers):
//   kv_open(path)                         -> handle or NULL
//   kv_put(h, key, klen, val, vlen)       -> 0 ok / -1 io error
//   kv_get_len(h, key, klen)              -> vlen, or -1 when absent
//   kv_get(h, key, klen, out, cap)        -> vlen copied, -1 absent, -2 cap
//   kv_delete(h, key, klen)               -> 0 ok (tombstone appended)
//   kv_count(h)                           -> live keys
//   kv_compact(h)                         -> 0 ok (rewrites live set)
//   kv_close(h)
//   kv_keys_size(h) / kv_keys_fill(h, out, cap) -> iterate key blob
//                     (keys serialized as [u32 klen][key]...)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <fcntl.h>   // open(2) for directory fsync
#include <unistd.h>  // truncate(2), fsync(2)

namespace {

constexpr uint32_t kTombstone = 0xFFFFFFFFu;
constexpr char kMagic[4] = {'A', 'K', 'V', '1'};

struct Store {
  std::string path;
  FILE* f = nullptr;  // append handle
  std::unordered_map<std::string, std::string> live;
};

// Replays the log.  On a torn final record (crash mid-append) the file is
// truncated at the record boundary -- appending after garbage would make
// the NEXT open misparse everything from the torn point on.
bool load(Store* s) {
  FILE* f = fopen(s->path.c_str(), "rb");
  if (!f) return true;  // fresh store
  char magic[4];
  size_t got = fread(magic, 1, 4, f);
  if (got < 4) {
    // crash between file creation and the magic write: treat as fresh
    // (consistent with the torn-tail truncation policy) instead of
    // permanently failing every subsequent open
    fclose(f);
    truncate(s->path.c_str(), 0);
    remove(s->path.c_str());
    return true;
  }
  if (memcmp(magic, kMagic, 4) != 0) {
    fclose(f);
    return false;
  }
  std::vector<char> key, val;
  long clean_end = ftell(f);  // last byte of a fully-parsed record
  for (;;) {
    uint32_t kl, vl;
    if (fread(&kl, 4, 1, f) != 1) break;
    if (fread(&vl, 4, 1, f) != 1) break;
    key.resize(kl);
    if (kl && fread(key.data(), 1, kl, f) != kl) break;  // torn record
    std::string k(key.data(), kl);
    if (vl == kTombstone) {
      s->live.erase(k);
      clean_end = ftell(f);
      continue;
    }
    val.resize(vl);
    if (vl && fread(val.data(), 1, vl, f) != vl) break;  // torn record
    s->live[k] = std::string(val.data(), vl);
    clean_end = ftell(f);
  }
  fseek(f, 0, SEEK_END);
  long file_end = ftell(f);
  fclose(f);
  if (file_end > clean_end) truncate(s->path.c_str(), clean_end);
  return true;
}

int append(Store* s, const char* key, uint32_t kl, const char* val,
           uint32_t vl) {
  if (!s->f) return -1;  // failed compact reopen: store is read-only now
  if (fwrite(&kl, 4, 1, s->f) != 1) return -1;
  if (fwrite(&vl, 4, 1, s->f) != 1) return -1;
  if (kl && fwrite(key, 1, kl, s->f) != kl) return -1;
  if (vl != kTombstone && vl && fwrite(val, 1, vl, s->f) != vl) return -1;
  fflush(s->f);
  return 0;
}

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  Store* s = new Store();
  s->path = path;
  if (!load(s)) {
    delete s;
    return nullptr;
  }
  FILE* probe = fopen(path, "rb");
  bool fresh = (probe == nullptr);
  if (probe) fclose(probe);
  s->f = fopen(path, "ab");
  if (!s->f) {
    delete s;
    return nullptr;
  }
  if (fresh) {
    fwrite(kMagic, 1, 4, s->f);
    fflush(s->f);
  }
  return s;
}

int kv_put(void* h, const char* key, uint32_t klen, const char* val,
           uint32_t vlen) {
  Store* s = (Store*)h;
  if (vlen == kTombstone) return -1;  // reserved
  if (append(s, key, klen, val, vlen) != 0) return -1;
  s->live[std::string(key, klen)] = std::string(val, vlen);
  return 0;
}

long long kv_get_len(void* h, const char* key, uint32_t klen) {
  Store* s = (Store*)h;
  auto it = s->live.find(std::string(key, klen));
  if (it == s->live.end()) return -1;
  return (long long)it->second.size();
}

long long kv_get(void* h, const char* key, uint32_t klen, char* out,
                 long long cap) {
  Store* s = (Store*)h;
  auto it = s->live.find(std::string(key, klen));
  if (it == s->live.end()) return -1;
  if ((long long)it->second.size() > cap) return -2;
  memcpy(out, it->second.data(), it->second.size());
  return (long long)it->second.size();
}

int kv_delete(void* h, const char* key, uint32_t klen) {
  Store* s = (Store*)h;
  if (append(s, key, klen, nullptr, kTombstone) != 0) return -1;
  s->live.erase(std::string(key, klen));
  return 0;
}

long long kv_count(void* h) { return (long long)((Store*)h)->live.size(); }

int kv_compact(void* h) {
  Store* s = (Store*)h;
  std::string tmp = s->path + ".compact";
  FILE* f = fopen(tmp.c_str(), "wb");
  if (!f) return -1;
  // every write checked: a short write (ENOSPC) must NOT be renamed over
  // the intact log -- that would silently drop keys on the next open
  bool ok = fwrite(kMagic, 1, 4, f) == 4;
  for (auto it = s->live.begin(); ok && it != s->live.end(); ++it) {
    uint32_t kl = (uint32_t)it->first.size();
    uint32_t vl = (uint32_t)it->second.size();
    ok = fwrite(&kl, 4, 1, f) == 1 && fwrite(&vl, 4, 1, f) == 1 &&
         (kl == 0 || fwrite(it->first.data(), 1, kl, f) == kl) &&
         (vl == 0 || fwrite(it->second.data(), 1, vl, f) == vl);
  }
  // durability: the temp file must be ON DISK before rename commits it --
  // otherwise power loss after the rename can leave a truncated .compact
  // as the only copy of the store
  if (ok) ok = (fflush(f) == 0) && (fsync(fileno(f)) == 0);
  ok = (fclose(f) == 0) && ok;
  if (!ok) {
    remove(tmp.c_str());
    return -1;
  }
  fclose(s->f);
  s->f = nullptr;
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    remove(tmp.c_str());
    s->f = fopen(s->path.c_str(), "ab");
    return -1;
  }
  // best-effort directory fsync so the rename itself is durable
  std::string dir = s->path;
  size_t slash = dir.find_last_of('/');
  dir = (slash == std::string::npos) ? std::string(".") : dir.substr(0, slash);
  int dfd = open(dir.c_str(), O_RDONLY);
  if (dfd >= 0) {
    fsync(dfd);
    close(dfd);
  }
  s->f = fopen(s->path.c_str(), "ab");
  return s->f ? 0 : -1;
}

long long kv_keys_size(void* h) {
  Store* s = (Store*)h;
  long long n = 0;
  for (auto& kv : s->live) n += 4 + (long long)kv.first.size();
  return n;
}

long long kv_keys_fill(void* h, char* out, long long cap) {
  Store* s = (Store*)h;
  long long off = 0;
  for (auto& kv : s->live) {
    uint32_t kl = (uint32_t)kv.first.size();
    if (off + 4 + kl > cap) return -2;
    memcpy(out + off, &kl, 4);
    off += 4;
    memcpy(out + off, kv.first.data(), kl);
    off += kl;
  }
  return off;
}

void kv_close(void* h) {
  Store* s = (Store*)h;
  if (s->f) fclose(s->f);
  delete s;
}

// Java String.hashCode-compatible hash (s[0]*31^(n-1) + ... + s[n-1], i32
// overflow); parity with the reference's only in-tree C function
// (R/pkg/src-native/string_hash_code.c) which exists so R-side hashing
// matches the JVM's partitioner.
int string_hash_code(const char* s, long long n) {
  int32_t hv = 0;
  for (long long i = 0; i < n; ++i) hv = hv * 31 + (int32_t)(unsigned char)s[i];
  return hv;
}

}  // extern "C"
