// wiredelta: GIL-free XOR-delta + CRC32 hot paths (net/wiredelta.py).
//
// Exact-bit twins of the numpy implementations in
// asyncframework_tpu/net/wiredelta.py -- the Python side stays the
// registered oracle and every function here must match it byte-for-byte
// (tests/test_native.py property-tests the pair over random sequences
// including NaN/inf/-0 bit patterns).  C ABI, ctypes-loaded; all sizes
// are long long, all buffers caller-owned.  Called through ctypes these
// run with the GIL released for the whole pass.

#include <cstdint>
#include <cstring>

extern "C" {

// ------------------------------------------------------------------ crc32
// Standard CRC-32 (IEEE 802.3, reflected, poly 0xEDB88320) -- the same
// function zlib.crc32 computes.  Slice-by-8 table kept build-free by
// generating it on first use (cheap, done once per process).
static uint32_t g_crc_tab[8][256];
static int g_crc_ready = 0;

static void crc_init() {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t c = i;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
        g_crc_tab[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; i++)
        for (int t = 1; t < 8; t++)
            g_crc_tab[t][i] =
                (g_crc_tab[t - 1][i] >> 8) ^
                g_crc_tab[0][g_crc_tab[t - 1][i] & 0xFF];
    g_crc_ready = 1;
}

uint32_t wd_crc32(const uint8_t* buf, long long n) {
    if (!g_crc_ready) crc_init();
    uint32_t c = 0xFFFFFFFFu;
    long long i = 0;
    // slice-by-8 over the aligned middle
    while (i + 8 <= n) {
        c ^= (uint32_t)buf[i] | ((uint32_t)buf[i + 1] << 8) |
             ((uint32_t)buf[i + 2] << 16) | ((uint32_t)buf[i + 3] << 24);
        uint32_t hi = (uint32_t)buf[i + 4] | ((uint32_t)buf[i + 5] << 8) |
                      ((uint32_t)buf[i + 6] << 16) |
                      ((uint32_t)buf[i + 7] << 24);
        c = g_crc_tab[7][c & 0xFF] ^ g_crc_tab[6][(c >> 8) & 0xFF] ^
            g_crc_tab[5][(c >> 16) & 0xFF] ^ g_crc_tab[4][c >> 24] ^
            g_crc_tab[3][hi & 0xFF] ^ g_crc_tab[2][(hi >> 8) & 0xFF] ^
            g_crc_tab[1][(hi >> 16) & 0xFF] ^ g_crc_tab[0][hi >> 24];
        i += 8;
    }
    for (; i < n; i++)
        c = g_crc_tab[0][(c ^ buf[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

// ------------------------------------------------------------- XOR deltas
// Sparse encode: write the changed-word indices and xor words of
// cur^basis into idx_out/xor_out.  Returns nnz, or -1 the moment nnz
// would exceed max_nnz -- the caller ships FULL then, exactly like the
// numpy path's `nz.size * 8 < cur.nbytes` cutoff (max_nnz is that
// threshold minus one word, supplied by the Python wrapper so the two
// implementations share one cutoff).
long long wd_encode(const uint32_t* cur, const uint32_t* basis,
                    long long n, uint32_t* idx_out, uint32_t* xor_out,
                    long long max_nnz) {
    long long nnz = 0;
    for (long long i = 0; i < n; i++) {
        uint32_t x = cur[i] ^ basis[i];
        if (x) {
            if (nnz >= max_nnz) return -1;
            idx_out[nnz] = (uint32_t)i;
            xor_out[nnz] = x;
            nnz++;
        }
    }
    return nnz;
}

// Dense xor (XFULL encode, and the XFULL decode's basis^payload pass).
void wd_xor_dense(const uint32_t* a, const uint32_t* b, uint32_t* out,
                  long long n) {
    for (long long i = 0; i < n; i++) out[i] = a[i] ^ b[i];
}

// XDELTA decode: bits[idx[k]] ^= words[k], bounds-checked against n.
// Returns 0, or -1 on any out-of-range index (caller -> full-pull
// fallback, the numpy path's idx.max() >= basis.size check).
int wd_apply_xdelta(uint32_t* bits, long long n, const uint32_t* idx,
                    const uint32_t* words, long long nnz) {
    for (long long k = 0; k < nnz; k++)
        if ((long long)idx[k] >= n) return -1;
    for (long long k = 0; k < nnz; k++) bits[idx[k]] ^= words[k];
    return 0;
}

// ------------------------------------------------------------ frame pump
// Gather copy: concatenate count buffers into dst (the frame pump's
// b"".join twin; also the shm-ring socket's vectored send path).
// Returns total bytes copied.
long long wd_gather(uint8_t* dst, const uint8_t** srcs,
                    const long long* lens, long long count) {
    long long off = 0;
    for (long long i = 0; i < count; i++) {
        if (lens[i] > 0) {
            memcpy(dst + off, srcs[i], (size_t)lens[i]);
            off += lens[i];
        }
    }
    return off;
}

}  // extern "C"
