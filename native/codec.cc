// AZ1: a byte-oriented LZ77 block codec (the framework's native
// compression component).
//
// Role parity: the reference wires lz4/snappy/zstd through JNI for shuffle,
// broadcast, and event-log compression (core/.../io/CompressionCodec.scala).
// This framework's equivalent hot consumers are the write-ahead log and any
// host-side blob that leaves memory.  AZ1 is an original, deliberately
// simple design in the LZ4 family's spirit -- greedy hash-chain matching,
// byte-aligned tokens -- tuned for "fast and safe" rather than maximal
// ratio.
//
// Block format (little-endian):
//   [u32 raw_len] followed by tokens until the block ends:
//     control byte c:
//       c & 0x80 == 0: literal run of (c & 0x7f) bytes (1..127), bytes follow
//       c & 0x80 != 0: match; length = (c & 0x7f) + MIN_MATCH (4..131),
//                      followed by u16 offset (1..65535) back from the
//                      current output position
//   matches may overlap forward (offset < length), enabling RLE.
// The decoder is fully bounds-checked: any out-of-range offset, overlong
// run, or truncated token fails with -1 instead of reading/writing OOB.
//
// Exported (C ABI, used via ctypes from utils/codec.py):
//   long long az1_max_compressed_size(long long n);
//   long long az1_compress(const uint8_t* src, long long n,
//                          uint8_t* dst, long long cap);   // -1 = cap
//   long long az1_decompress(const uint8_t* src, long long n,
//                            uint8_t* dst, long long cap); // -1 = corrupt

#include <cstdint>
#include <cstring>

namespace {

constexpr int kMinMatch = 4;
constexpr int kMaxMatchToken = 0x7f;             // match len 4..131
constexpr int kMaxLiteralRun = 0x7f;             // 1..127
constexpr long long kMaxOffset = 0xffff;
constexpr int kHashBits = 15;
constexpr uint32_t kHashMul = 2654435761u;       // Knuth multiplicative

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint32_t hash4(const uint8_t* p) {
  return (load32(p) * kHashMul) >> (32 - kHashBits);
}

}  // namespace

extern "C" {

long long az1_max_compressed_size(long long n) {
  // worst case: all literals -> ceil(n/127) control bytes + n + header
  if (n < 0) return -1;
  return 4 + n + (n / kMaxLiteralRun + 1);
}

long long az1_compress(const uint8_t* src, long long n, uint8_t* dst,
                       long long cap) {
  if (n < 0 || cap < 4 || n > 0x7fffffffLL) return -1;
  uint8_t* out = dst;
  uint8_t* out_end = dst + cap;
  uint32_t raw = (uint32_t)n;
  if (out + 4 > out_end) return -1;
  std::memcpy(out, &raw, 4);
  out += 4;

  long long table[1 << kHashBits];
  for (auto& t : table) t = -1;

  long long i = 0;
  long long lit_start = 0;

  auto flush_literals = [&](long long upto) -> bool {
    long long len = upto - lit_start;
    while (len > 0) {
      int run = len > kMaxLiteralRun ? kMaxLiteralRun : (int)len;
      if (out + 1 + run > out_end) return false;
      *out++ = (uint8_t)run;
      std::memcpy(out, src + lit_start, run);
      out += run;
      lit_start += run;
      len -= run;
    }
    return true;
  };

  while (i + kMinMatch <= n) {
    uint32_t h = hash4(src + i);
    long long cand = table[h];
    table[h] = i;
    if (cand >= 0 && i - cand <= kMaxOffset &&
        load32(src + cand) == load32(src + i)) {
      // extend the match
      long long len = kMinMatch;
      long long max_len = n - i;
      if (max_len > kMaxMatchToken + kMinMatch)
        max_len = kMaxMatchToken + kMinMatch;
      while (len < max_len && src[cand + len] == src[i + len]) ++len;
      if (!flush_literals(i)) return -1;
      if (out + 3 > out_end) return -1;
      *out++ = (uint8_t)(0x80 | (len - kMinMatch));
      uint16_t off = (uint16_t)(i - cand);
      std::memcpy(out, &off, 2);
      out += 2;
      // seed the table inside the match so later data can reference it
      long long stop = i + len - kMinMatch;
      for (long long j = i + 1; j <= stop; ++j) table[hash4(src + j)] = j;
      i += len;
      lit_start = i;
    } else {
      ++i;
    }
  }
  if (!flush_literals(n)) return -1;
  return out - dst;
}

long long az1_decompress(const uint8_t* src, long long n, uint8_t* dst,
                         long long cap) {
  if (n < 4) return -1;
  uint32_t raw;
  std::memcpy(&raw, src, 4);
  if ((long long)raw > cap) return -1;
  const uint8_t* in = src + 4;
  const uint8_t* in_end = src + n;
  uint8_t* out = dst;
  uint8_t* out_end = dst + raw;

  while (out < out_end) {
    if (in >= in_end) return -1;  // truncated token
    uint8_t c = *in++;
    if (c & 0x80) {
      long long len = (c & 0x7f) + kMinMatch;
      if (in + 2 > in_end) return -1;
      uint16_t off;
      std::memcpy(&off, in, 2);
      in += 2;
      if (off == 0 || (long long)(out - dst) < off) return -1;
      if (out + len > out_end) return -1;
      // byte-by-byte on purpose: overlapping matches (offset < len) must
      // replicate forward, memcpy semantics would be undefined
      const uint8_t* from = out - off;
      for (long long j = 0; j < len; ++j) out[j] = from[j];
      out += len;
    } else {
      if (c == 0) return -1;  // zero-length literal run is invalid
      if (in + c > in_end) return -1;
      if (out + c > out_end) return -1;
      std::memcpy(out, in, c);
      in += c;
      out += c;
    }
  }
  if (in != in_end) return -1;  // trailing garbage
  return (long long)raw;
}

}  // extern "C"
