// shmring: lock-free SPSC byte ring over a shared-memory segment
// (net/shmring.py).
//
// One ring = one 192-byte header + a capacity-byte data region inside an
// mmap'd file; one writer process, one reader process.  head (bytes
// consumed, reader-owned) and tail (bytes produced, writer-owned) are
// monotone u64s -- tail-head is the readable span, capacity-(tail-head)
// the writable one.  Release/acquire atomics order the data copies
// against the counter publishes, which is the entire correctness story
// of an SPSC ring.  net/shmring.py carries a layout-identical pure-
// Python twin (struct.pack_into on the same mmap) as the registered
// oracle; tests cross-drive native-write/python-read and the reverse.
//
// Header layout (all little-endian on every platform this runs on):
//   0   u32 magic 'SRNG'     32 u32 writer_pid     64  u64 head
//   4   u32 version (2)      36 u32 reader_pid     128 u64 tail
//   8   u64 capacity         40 u32 flags          192.. data
// flags: bit0 = writer closed, bit1 = reader closed.
//
// head and tail each own a full cache line (v2; v1 packed them 8 bytes
// apart): the writer's tail publishes and the reader's head publishes
// no longer invalidate each OTHER's hot line, which under concurrent
// streaming turned every counter read into a cross-core miss.  The
// cold first line (magic/capacity/pids/flags) is read-mostly and stays
// Shared in both caches.
//
// On an empty read / full write the call spins briefly IN HERE (pause
// loop, GIL already released by ctypes) before returning 0: during
// active streaming the matching publish usually lands within
// microseconds, and catching it here saves a round-trip through the
// Python pacing loop per chunk.  The Python twin returns immediately
// instead -- spinning while holding the GIL would starve the very
// thread it is waiting on; semantics (bytes moved, 0 = try again) are
// identical either way.

#include <cstdint>
#include <cstring>

extern "C" {

static const uint32_t MAGIC = 0x53524E47u;  // 'SRNG'
static const uint64_t HDR = 192;

#define HEAD(base) ((uint64_t*)((base) + 64))
#define TAIL(base) ((uint64_t*)((base) + 128))
#define FLAGS(base) ((uint32_t*)((base) + 40))

// ~a few microseconds of in-call waiting: SPIN_ROUNDS re-checks of the
// peer's counter, PAUSES_PER_ROUND pause instructions apart
static const int SPIN_ROUNDS = 64;
static const int PAUSES_PER_ROUND = 64;

static inline void cpu_pause() {
#if defined(__x86_64__) || defined(__i386__)
    __asm__ __volatile__("pause");
#elif defined(__aarch64__)
    __asm__ __volatile__("yield");
#endif
}

int shm_ring_init(uint8_t* base, unsigned long long capacity) {
    if (capacity == 0) return -1;
    memset(base, 0, HDR);
    *(uint32_t*)(base + 0) = MAGIC;
    *(uint32_t*)(base + 4) = 2;
    *(uint64_t*)(base + 8) = capacity;
    __atomic_thread_fence(__ATOMIC_SEQ_CST);
    return 0;
}

int shm_ring_ok(const uint8_t* base) {
    return *(const uint32_t*)(base + 0) == MAGIC &&
           *(const uint32_t*)(base + 4) == 2;
}

void shm_ring_close(uint8_t* base, int writer) {
    __atomic_fetch_or(FLAGS(base), writer ? 1u : 2u, __ATOMIC_SEQ_CST);
}

// Bytes written (0..n; 0 = ring full, caller paces).  -1 = the reader
// side is closed: nothing will ever drain the ring again.
long long shm_ring_write(uint8_t* base, const uint8_t* data,
                         long long n) {
    uint32_t flags = __atomic_load_n(FLAGS(base), __ATOMIC_ACQUIRE);
    if (flags & 2u) return -1;
    uint64_t cap = *(uint64_t*)(base + 8);
    uint64_t tail = __atomic_load_n(TAIL(base), __ATOMIC_RELAXED);
    uint64_t head = __atomic_load_n(HEAD(base), __ATOMIC_ACQUIRE);
    if (cap - (tail - head) == 0) {
        for (int r = 0; r < SPIN_ROUNDS; ++r) {
            for (int i = 0; i < PAUSES_PER_ROUND; ++i) cpu_pause();
            head = __atomic_load_n(HEAD(base), __ATOMIC_ACQUIRE);
            if (cap - (tail - head) != 0) break;
        }
    }
    uint64_t free_b = cap - (tail - head);
    uint64_t take = (uint64_t)n < free_b ? (uint64_t)n : free_b;
    if (!take) return 0;
    uint64_t pos = tail % cap;
    uint64_t first = take < cap - pos ? take : cap - pos;
    memcpy(base + HDR + pos, data, (size_t)first);
    if (take > first) memcpy(base + HDR, data + first,
                             (size_t)(take - first));
    __atomic_store_n(TAIL(base), tail + take, __ATOMIC_RELEASE);
    return (long long)take;
}

// Bytes read (0..maxn; 0 = ring empty).  -1 = empty AND writer closed:
// clean EOF, no more bytes are coming.
long long shm_ring_read(uint8_t* base, uint8_t* out, long long maxn) {
    uint64_t cap = *(uint64_t*)(base + 8);
    uint64_t head = __atomic_load_n(HEAD(base), __ATOMIC_RELAXED);
    uint64_t tail = __atomic_load_n(TAIL(base), __ATOMIC_ACQUIRE);
    if (tail == head) {
        for (int r = 0; r < SPIN_ROUNDS; ++r) {
            for (int i = 0; i < PAUSES_PER_ROUND; ++i) cpu_pause();
            tail = __atomic_load_n(TAIL(base), __ATOMIC_ACQUIRE);
            if (tail != head) break;
        }
    }
    uint64_t avail = tail - head;
    if (!avail) {
        uint32_t flags = __atomic_load_n(FLAGS(base), __ATOMIC_ACQUIRE);
        return (flags & 1u) ? -1 : 0;
    }
    uint64_t take = (uint64_t)maxn < avail ? (uint64_t)maxn : avail;
    uint64_t pos = head % cap;
    uint64_t first = take < cap - pos ? take : cap - pos;
    memcpy(out, base + HDR + pos, (size_t)first);
    if (take > first) memcpy(out + first, base + HDR,
                             (size_t)(take - first));
    __atomic_store_n(HEAD(base), head + take, __ATOMIC_RELEASE);
    return (long long)take;
}

}  // extern "C"
