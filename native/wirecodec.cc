// wirecodec: GIL-free quantize/shuffle/index-transform hot paths
// (net/wirecodec.py).
//
// Bit-exact twins of the numpy reference implementations -- the Python
// functions stay the registered oracles and tests/test_native.py
// property-tests equality over random inputs (NaN/inf/-0 included on
// the paths that admit them).  The contracts that make bitwise equality
// hold:
//
// - fp16 conversion is IEEE binary16 round-to-nearest-even, the same
//   rule numpy's astype(float16) applies (hand-rolled below so no
//   FP16C/F16C ISA assumption leaks in);
// - int8 uses scale = double(absmax)/127.0, the DIVISION x/scale runs
//   in float32 against float(scale) (NEP 50: a python-float scalar is
//   demoted to the array dtype), rounding is rint = round-half-to-even
//   (nearbyintf under the default FE_TONEAREST mode), and the applied
//   value is float(q) * float(scale);
// - the error-feedback residual is x - applied in float32.
//
// C ABI, ctypes-loaded, caller-owned buffers, long long sizes.

#include <cmath>
#include <cstdint>
#include <cstring>

extern "C" {

// ----------------------------------------------------- fp16 conversions
// float32 -> IEEE binary16 bits, round-to-nearest-even (numpy's rule).
static uint16_t f32_to_f16(float f) {
    uint32_t x;
    memcpy(&x, &f, 4);
    uint32_t sign = (x >> 16) & 0x8000u;
    uint32_t mant = x & 0x007FFFFFu;
    int32_t exp = (int32_t)((x >> 23) & 0xFF) - 127;
    if (exp == 128) {  // inf / NaN
        if (mant) return (uint16_t)(sign | 0x7E00u | (mant >> 13));
        return (uint16_t)(sign | 0x7C00u);
    }
    if (exp > 15) return (uint16_t)(sign | 0x7C00u);  // overflow -> inf
    if (exp >= -14) {  // normal half
        uint32_t m = mant >> 13;
        uint32_t rem = mant & 0x1FFFu;
        uint16_t h = (uint16_t)(sign | ((uint32_t)(exp + 15) << 10) | m);
        if (rem > 0x1000u || (rem == 0x1000u && (m & 1))) h++;
        return h;  // mantissa carry rolls into the exponent correctly
    }
    if (exp < -25) return (uint16_t)sign;  // underflow -> signed zero
    // subnormal half: value = M * 2^(exp-23) with the implicit bit set;
    // the half-subnormal unit is 2^-24, so the kept mantissa is
    // M >> (-exp-1), rounded half-to-even on the dropped bits
    uint32_t m = mant | 0x00800000u;
    int shift = -exp - 1;  // 14..24
    uint32_t kept = m >> shift;
    uint32_t rem = m & ((1u << shift) - 1u);
    uint32_t half = 1u << (shift - 1);
    uint16_t h = (uint16_t)(sign | kept);
    if (rem > half || (rem == half && (kept & 1))) h++;
    return h;
}

static float f16_to_f32(uint16_t h) {
    uint32_t sign = (uint32_t)(h & 0x8000u) << 16;
    uint32_t exp = (h >> 10) & 0x1F;
    uint32_t mant = h & 0x3FFu;
    uint32_t x;
    if (exp == 0x1F) {
        x = sign | 0x7F800000u | (mant << 13);
    } else if (exp == 0) {
        if (mant == 0) {
            x = sign;
        } else {  // subnormal half -> normal float
            int e = -1;
            do {
                mant <<= 1;
                e++;
            } while (!(mant & 0x400u));
            x = sign | ((uint32_t)(127 - 15 - e) << 23)
                | ((mant & 0x3FFu) << 13);
        }
    } else {
        x = sign | ((exp + 112u) << 23) | (mant << 13);
    }
    float f;
    memcpy(&f, &x, 4);
    return f;
}

// ------------------------------------------------------- gradient encode
// x = g + err (f32), reject non-finite (status 1) / fp16 overflow
// (status 2, absmax compared as double against safe_max like the Python
// float compare), else quantize with error feedback.  err may be NULL
// (first push).  q_out is u16 half bits; newerr_out the next residual.
int wc_enc_fp16(const float* g, const float* err, long long n,
                uint16_t* q_out, float* newerr_out, double safe_max) {
    double absmax = 0.0;
    for (long long i = 0; i < n; i++) {
        float x = err ? g[i] + err[i] : g[i];
        if (!std::isfinite(x)) return 1;
        double a = std::fabs((double)x);
        if (a > absmax) absmax = a;
    }
    if (absmax > safe_max) return 2;
    for (long long i = 0; i < n; i++) {
        float x = err ? g[i] + err[i] : g[i];
        uint16_t q = f32_to_f16(x);
        q_out[i] = q;
        newerr_out[i] = x - f16_to_f32(q);
    }
    return 0;
}

// int8: scale = double(absmax)/127 reported via scale_out for the wire
// header; quantization itself runs in f32 against float(scale).
int wc_enc_int8(const float* g, const float* err, long long n,
                int8_t* q_out, float* newerr_out, double* scale_out) {
    float absmax = 0.0f;
    for (long long i = 0; i < n; i++) {
        float x = err ? g[i] + err[i] : g[i];
        if (!std::isfinite(x)) return 1;
        float a = std::fabs(x);
        if (a > absmax) absmax = a;
    }
    double scale = (double)absmax / 127.0;
    *scale_out = scale;
    float fs = (float)scale;
    for (long long i = 0; i < n; i++) {
        float x = err ? g[i] + err[i] : g[i];
        float applied;
        if (scale > 0.0) {
            float r = nearbyintf(x / fs);  // rint: round-half-to-even
            if (r > 127.0f) r = 127.0f;
            if (r < -127.0f) r = -127.0f;
            int8_t q = (int8_t)r;
            q_out[i] = q;
            applied = (float)q * fs;
        } else {
            q_out[i] = 0;
            applied = 0.0f;
        }
        newerr_out[i] = x - applied;
    }
    return 0;
}

// ------------------------------------------------------- gradient decode
void wc_dec_fp16(const uint16_t* q, long long n, float* out) {
    for (long long i = 0; i < n; i++) out[i] = f16_to_f32(q[i]);
}

void wc_dec_int8(const int8_t* q, long long n, float gs, float* out) {
    for (long long i = 0; i < n; i++) out[i] = (float)q[i] * gs;
}

// ------------------------------------------------- shuffle + index paths
// Byte-plane transposition over 4-byte words (the Blosc/HDF5 shuffle):
// n is the BYTE length, a multiple of 4.  dst[plane*words + w] =
// src[w*4 + plane].
void wc_shuffle4(const uint8_t* src, long long n, uint8_t* dst) {
    long long words = n / 4;
    for (long long w = 0; w < words; w++) {
        dst[w] = src[w * 4];
        dst[words + w] = src[w * 4 + 1];
        dst[2 * words + w] = src[w * 4 + 2];
        dst[3 * words + w] = src[w * 4 + 3];
    }
}

void wc_unshuffle4(const uint8_t* src, long long n, uint8_t* dst) {
    long long words = n / 4;
    for (long long w = 0; w < words; w++) {
        dst[w * 4] = src[w];
        dst[w * 4 + 1] = src[words + w];
        dst[w * 4 + 2] = src[2 * words + w];
        dst[w * 4 + 3] = src[3 * words + w];
    }
}

// Delta-encode an ascending u32 index list (np.diff with prepend=0) and
// its inverse (u32 wrapping cumulative sum -- numpy's u64 cumsum cast
// back to u32 is exactly mod-2^32 accumulation).
void wc_delta_idx(const uint32_t* idx, long long n, uint32_t* out) {
    uint32_t prev = 0;
    for (long long i = 0; i < n; i++) {
        out[i] = idx[i] - prev;
        prev = idx[i];
    }
}

void wc_cumsum_idx(const uint32_t* d, long long n, uint32_t* out) {
    uint32_t acc = 0;
    for (long long i = 0; i < n; i++) {
        acc += d[i];
        out[i] = acc;
    }
}

}  // extern "C"
