"""The submit CLI: drop-in experiment recipes with the reference's arguments.

Parity: each reference driver takes 13 positional args
(``SparkASGDThread.scala:28-48``; example submit in ``README.md:46``)::

    <path> <file> <d> <N> <numPart> <numIter> <gamma> <taw> <batchRate>
    <bucketRatio> <printerFreq> <coeff> <seed>

Here the same recipe is::

    python -m asyncframework_tpu.cli SparkASGDThread \
        /data mnist8m.scale 784 8100000 64 16000 1.5625e-3 20000000 \
        0.01 0.7 200 -1 42

Driver names accept both the reference class names (``SparkASGDThread``,
``SparkASGDSync``, ``SparkASAGAThread``, ``SparkASAGASync``,
``SparkSGDMLLIB``) and short forms (``asgd``, ``asgd-sync``, ``asaga``,
``asaga-sync``, ``sgd-mllib``), plus the device-resident fast paths
``asgd-fused`` / ``asaga-fused`` (recipes whose tau filter provably never
fires, fused into on-device scan rounds -- asgd: taw >= numPart-1; asaga:
taw >= numIter; single-process, no runtime flags -- see
``ASGD.run_fused``).  ``--conf key=value`` overlays any registered
:class:`~asyncframework_tpu.conf.ConfigEntry` (CLI > conf file > env >
default precedence, like ``spark-submit --conf``).

Data: ``<path>/<file>`` is a LibSVM file loaded with ``d`` features; the
special path ``synthetic`` generates an ``N x d`` planted least-squares
problem directly in device HBM instead (no reference analog -- Spark always
reads files -- but indispensable on a TPU host with no dataset mounted).

Output: the loss trajectory is printed as ``(ms, objective)`` pairs exactly
like the drivers' final loop (``SparkASGDThread.scala:386-401``), followed by
one JSON summary line (machine-readable; consumed by bench harnesses).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional

from asyncframework_tpu.conf import AsyncConf, registry

# registered ConfigEntry key -> SolverConfig field, for --conf overlays
CONF_TO_FIELD: Dict[str, str] = {
    "async.num.workers": "num_workers",
    "async.num.iterations": "num_iterations",
    "async.step.size": "gamma",
    "async.taw": "taw",
    "async.batch.rate": "batch_rate",
    "async.bucket.ratio": "bucket_ratio",
    "async.printer.freq": "printer_freq",
    "async.delay.coeff": "coeff",
    "async.seed": "seed",
    # engine knobs (spark.speculation / dynamicAllocation analogs)
    "async.drain.batch": "drain_batch",
    "async.speculation.quantile": "speculation_quantile",
    "async.speculation.multiplier": "speculation_multiplier",
    "async.speculation.min.ms": "speculation_min_ms",
    "async.allocation.max.extra": "allocation_max_extra",
    "async.allocation.backlog.threshold": "allocation_backlog_threshold",
    "async.allocation.idle.timeout.s": "allocation_idle_timeout_s",
    "async.heartbeat.timeout.ms": "heartbeat_timeout_ms",
    "async.max.slot.failures": "max_slot_failures",
    "async.broadcast.versions": "max_live_versions",
    "async.ui.port": "ui_port",
    "async.trace.sample": "trace_sample",
    # DCN data-plane knobs (parallel/ps_dcn.py)
    "async.pull.mode": "pull_mode",
    "async.push.merge": "push_merge",
    "async.codec.push": "push_codec",
    "async.pipeline.depth": "pipeline_depth",
    "async.mesh.devices": "mesh_devices",
    # telemetry plane (metrics/timeseries.py)
    "async.convergence.sample": "conv_sample",
}

DRIVER_ALIASES: Dict[str, str] = {
    "sparkasgdthread": "asgd",
    "asgd": "asgd",
    "sparkasgdsync": "asgd-sync",
    "asgd-sync": "asgd-sync",
    "sparkasagathread": "asaga",
    "asaga": "asaga",
    "sparkasagasync": "asaga-sync",
    "asaga-sync": "asaga-sync",
    "sparksgdmllib": "sgd-mllib",
    "sgd-mllib": "sgd-mllib",
    # the device-resident fast path (taw=inf recipes; see ASGD.run_fused)
    "asgd-fused": "asgd-fused",
    "asaga-fused": "asaga-fused",
}

POSITIONAL = [
    ("path", str, "data directory, or 'synthetic'"),
    ("file", str, "LibSVM file name (ignored for synthetic)"),
    ("d", int, "number of features (columns)"),
    ("N", int, "number of rows"),
    ("num_partitions", int, "number of workers/partitions"),
    ("num_iterations", int, "iterations (accepted updates)"),
    ("gamma", float, "step size"),
    ("taw", int, "staleness bound tau"),
    ("batch_rate", float, "Bernoulli batch rate b"),
    ("bucket_ratio", float, "cohort availability threshold"),
    ("printer_freq", int, "trajectory snapshot period"),
    ("coeff", float, "delay intensity (-1 = cloud long-tail)"),
    ("seed", int, "root PRNG seed"),
]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="async-submit",
        description=__doc__.split("\n\n")[0],
    )
    p.add_argument("driver", help="driver class (SparkASGDThread/asgd, ...)")
    for name, typ, doc in POSITIONAL:
        p.add_argument(name, type=typ, help=doc)
    p.add_argument("--conf", action="append", default=[], metavar="K=V",
                   help="config overlay (repeatable)")
    p.add_argument("--loss", default="least_squares",
                   choices=["least_squares", "logistic"])
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-freq", type=int, default=0)
    p.add_argument("--output", default=None,
                   help="write the trajectory as CSV to this path")
    p.add_argument("--devices", type=int, default=None,
                   help="use only the first N jax devices")
    p.add_argument("--quiet", action="store_true",
                   help="suppress the per-snapshot trajectory lines")
    p.add_argument("--master", default=None,
                   metavar="HOST:PORT[,HOST:PORT...]",
                   help="submit to a standalone master daemon (first addr "
                        "primary, rest standbys) instead of running locally "
                        "-- spark-submit --master parity")
    p.add_argument("--processes", type=int, default=1,
                   help="executor processes for a --master submission")
    p.add_argument("--supervise", action="store_true",
                   help="worker daemons restart failed executors "
                        "(spark-submit --supervise parity; --master only)")
    p.add_argument("--no-wait", action="store_true",
                   help="return after submission without waiting for a "
                        "terminal state (cluster deploy-mode)")
    p.add_argument("--wait-timeout", type=float, default=600.0,
                   help="--master wait budget in seconds")
    p.add_argument("--event-log", default=None,
                   help="write a JSONL event log (.gz = compressed) of the run")
    p.add_argument("--report", default=None,
                   help="render an HTML run report to this path "
                        "(requires --event-log)")
    p.add_argument("--metrics-csv", default=None,
                   help="periodic metrics samples as CSV")
    p.add_argument("--ui-port", type=int, default=None, metavar="PORT",
                   help="serve a live run dashboard on this HTTP port "
                        "during the run (0 = ephemeral; SparkUI parity)")
    p.add_argument("--trace-sample", type=float, default=None,
                   metavar="RATE",
                   help="distributed-trace sampling rate per update "
                        "lifecycle (1 = every update, 0 = off; default "
                        "async.trace.sample = 1/64).  Spans land in the "
                        "event log / live UI; inspect with bin/async-trace")
    p.add_argument("--speculation", action="store_true",
                   help="launch speculative copies of straggling tasks")
    p.add_argument("--dynamic-allocation", action="store_true",
                   help="scale slot capacity with task backlog (sibling "
                        "executors added/retired, ExecutorAllocationManager "
                        "parity)")
    p.add_argument("--stale-read", type=int, default=None, metavar="OFFSET",
                   help="ASYNCbroadcast experiment: workers read model "
                        "version (latest - OFFSET) from the versioned store")
    p.add_argument("--no-heartbeat", action="store_true",
                   help="disable executor liveness monitoring")
    p.add_argument("--sparse", action="store_true",
                   help="rcv1-class path: keep data sparse on device "
                        "(padded-ELL shards; never densified)")
    p.add_argument("--sparse-density", type=float, default=0.002,
                   help="row density for synthetic --sparse data")
    return p


def parse_conf_overlays(pairs: List[str]) -> AsyncConf:
    conf = AsyncConf()
    known = registry()
    for pair in pairs:
        if "=" not in pair:
            raise SystemExit(f"--conf expects key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        k = k.strip()
        if k not in known:
            raise SystemExit(
                f"--conf: unknown key {k!r}; registered keys: "
                + ", ".join(sorted(known))
            )
        conf.set(k, v.strip())
    # make the overlays visible to components that resolve conf defaults
    # themselves (e.g. receiver backpressure knobs)
    from asyncframework_tpu.conf import set_global_conf

    set_global_conf(conf)
    return conf


def load_data(args, cfg, devices, need_host: bool = False):
    """Resolve (X, y) or a device-resident ShardedDataset per the recipe.

    Sharding follows the post-overlay ``cfg`` (worker count / seed may have
    been changed by ``--conf``).  ``need_host=True`` (the SPMD mllib
    baseline) forces host arrays even for synthetic data -- it shards the
    *global* arrays over the mesh itself.
    """
    from asyncframework_tpu.data.sharded import ShardedDataset

    if getattr(args, "sparse", False):
        if need_host:
            raise SystemExit(
                "--sparse is not supported by the sgd-mllib SPMD baseline "
                "(it shards dense global arrays); use asgd/asaga drivers"
            )
        from asyncframework_tpu.data.sparse import SparseShardedDataset

        if args.path == "synthetic":
            from asyncframework_tpu.data.synthetic import make_sparse_regression

            indptr, indices, values, y = make_sparse_regression(
                args.N, args.d, density=args.sparse_density, seed=cfg.seed
            )
        else:
            path = os.path.join(args.path, args.file)
            if not os.path.exists(path):
                raise SystemExit(f"no such data file: {path}")
            from asyncframework_tpu.data.libsvm import load_libsvm_sparse

            indptr, indices, values, y = load_libsvm_sparse(path, args.d)
            if args.N and len(indptr) - 1 > args.N:
                indptr = indptr[: args.N + 1]
                indices = indices[: indptr[-1]]
                values = values[: indptr[-1]]
                y = y[: args.N]
        ds = SparseShardedDataset(
            indptr, indices, values, y, args.d, cfg.num_workers, devices
        )
        return ds, None

    if args.path == "synthetic":
        if need_host:
            from asyncframework_tpu.data import make_regression

            X, y, _ = make_regression(args.N, args.d, seed=cfg.seed)
            return X, y
        ds = ShardedDataset.generate_on_device(
            args.N, args.d, cfg.num_workers, devices=devices,
            seed=cfg.seed,
        )
        return ds, None
    path = os.path.join(args.path, args.file)
    if not os.path.exists(path):
        raise SystemExit(f"no such data file: {path}")
    from asyncframework_tpu.data.libsvm import load_libsvm

    X, y = load_libsvm(path, num_features=args.d)
    if args.N and X.shape[0] > args.N:
        X, y = X[: args.N], y[: args.N]
    return X, y


def run_driver(args, conf: AsyncConf) -> Dict[str, object]:
    import jax

    from asyncframework_tpu.parallel import multihost
    from asyncframework_tpu.solvers import ASAGA, ASGD, MiniBatchSGD, SolverConfig

    driver = DRIVER_ALIASES.get(args.driver.lower())
    if driver is None:
        raise SystemExit(
            f"unknown driver {args.driver!r}; one of "
            f"{sorted(set(DRIVER_ALIASES.values()))} (or reference class names)"
        )
    # Multi-host: the SPMD sgd-mllib driver joins a jax.distributed global
    # mesh; the ASYNC drivers instead run the DCN parameter server
    # (parallel/ps_dcn.py): process 0 IS the PS (the driver IS the server --
    # now across the process boundary), processes 1..N-1 push tau-stamped
    # gradients over the coordinator address's TCP channel.
    if os.environ.get("ASYNCTPU_COORDINATOR") and driver in ("asgd", "asaga"):
        nproc = int(os.environ.get("ASYNCTPU_NUM_PROCESSES", "1"))
        if nproc > 1:
            return run_async_cluster(args, conf, algo=driver)
        # a 1-process placement (e.g. a master-scheduled single-executor
        # app) is just a normal single-process run; DCN mode needs peers.
        # ensure_initialized below also no-ops for nproc <= 1.
    if multihost.ensure_initialized() and driver != "sgd-mllib":
        raise SystemExit(
            "multi-process runs support the SPMD sgd-mllib driver (global "
            "mesh) and the DCN parameter-server asgd/asaga drivers; the "
            "sync and fused drivers run single-process"
        )
    devices = jax.devices()
    if args.devices is not None:
        devices = devices[: args.devices]

    # drivers without the async engine runtime (no updater thread, no
    # executor pool): one predicate, every runtime-flag guard below uses it
    no_runtime = (
        driver.endswith("-sync") or driver.endswith("-fused")
        or driver == "sgd-mllib"
    )
    fused = driver.endswith("-fused")
    if args.checkpoint_dir and no_runtime:
        raise SystemExit(
            "--checkpoint-dir is supported by the async engine drivers "
            "only (asgd, asaga); sync/fused/sgd-mllib runs do not "
            "checkpoint"
        )

    if args.report and not args.event_log:
        raise SystemExit("--report requires --event-log (it renders the log)")
    if args.stale_read is not None and no_runtime:
        raise SystemExit(
            "--stale-read applies to the async engine drivers only"
        )
    if fused:
        # flag guards use raw args (overlays cannot change flags)
        for flag, name in (
            (args.speculation, "--speculation"),
            (args.dynamic_allocation, "--dynamic-allocation"),
            (args.ui_port is not None, "--ui-port"),
            (args.metrics_csv, "--metrics-csv"),
        ):
            if flag:
                raise SystemExit(
                    f"{name} needs the async engine runtime; the fused "
                    "drivers run a closed on-device loop -- use asgd/asaga"
                )

    cfg = SolverConfig(
        num_workers=args.num_partitions,
        num_iterations=args.num_iterations,
        gamma=args.gamma,
        taw=args.taw,
        batch_rate=args.batch_rate,
        bucket_ratio=args.bucket_ratio,
        printer_freq=args.printer_freq,
        coeff=args.coeff,
        seed=args.seed,
        loss=args.loss,
        checkpoint_dir=args.checkpoint_dir,
        checkpoint_freq=args.checkpoint_freq,
        event_log=args.event_log,
        metrics_csv=args.metrics_csv,
        ui_port=args.ui_port,
        trace_sample=args.trace_sample,
        speculation=args.speculation,
        dynamic_allocation=args.dynamic_allocation,
        stale_read_offset=args.stale_read,
        heartbeat=not args.no_heartbeat,
    )
    # conf overlays beat recipe args for every registered solver knob
    for key, field in CONF_TO_FIELD.items():
        if conf.contains(key):
            setattr(cfg, field, conf.get(key))

    if fused:
        # numeric guards run AFTER the overlays (a --conf async.taw /
        # async.num.workers rewrite must be what is judged) and BEFORE the
        # (possibly large) dataset loads -- run_fused's own checks would
        # surface as tracebacks after the load.  Thresholds differ by
        # family: ASGD's staleness filter is wave-bounded (taw >= nw-1
        # never fires); ASAGA's quirk binds on iteration count (taw >=
        # num_iterations never fires) -- see each solver's run_fused.
        if driver.startswith("asgd") and cfg.taw < cfg.num_workers - 1:
            raise SystemExit(
                "asgd-fused admits taw >= num_workers-1 (its wave "
                "staleness never exceeds that); a tighter taw needs the "
                "engine's tau filter -- use asgd"
            )
        if driver.startswith("asaga") and cfg.taw < cfg.num_iterations:
            raise SystemExit(
                "asaga-fused requires taw >= num_iterations (the ASAGA "
                "filter quirk binds on iteration count); a tighter taw "
                "needs the engine -- use asaga"
            )
        if cfg.coeff != 0.0:
            raise SystemExit(
                "fused drivers cannot inject stragglers (no host between "
                "updates); use asgd/asaga"
            )

    X, y = load_data(args, cfg, devices, need_host=(driver == "sgd-mllib"))
    t0 = time.monotonic()
    if driver == "sgd-mllib":
        from asyncframework_tpu.parallel import make_mesh

        Xh, yh = (X, y) if y is not None else X.global_arrays()
        n_mesh = len(devices)
        sgd = MiniBatchSGD(  # reads cfg so --conf overlays apply here too
            gamma=cfg.gamma, batch_rate=cfg.batch_rate,
            num_iterations=cfg.num_iterations, loss=cfg.loss,
            seed=cfg.seed, snapshot_every=cfg.printer_freq,
            trace_sample=cfg.trace_sample,
        )
        mesh = make_mesh(n_mesh, devices=devices)
        w, losses, snaps = sgd.run(Xh, yh, mesh=mesh)
        elapsed = time.monotonic() - t0
        # the whole run is one fused scan, so per-iteration wall time is
        # uniform: spread elapsed evenly to keep the (ms, objective) output
        # contract comparable with the async drivers' trajectories
        per_iter_ms = elapsed * 1e3 / max(len(losses), 1)
        trajectory = [
            ((i + 1) * per_iter_ms, float(l)) for i, l in enumerate(losses)
        ]
        summary = {
            "driver": driver,
            "final_objective": float(losses[-1]) if len(losses) else None,
            "iterations": len(losses),
            "elapsed_s": elapsed,
            "snapshots": len(snaps),
        }
        if args.event_log:
            # the fused-scan baseline has no per-task events; log the
            # trajectory so the report/history tooling still works on it
            from asyncframework_tpu.solvers.instrumentation import log_trajectory

            log_trajectory(args.event_log, trajectory, cfg.printer_freq)
    else:
        solver_cls = ASGD if driver.startswith("asgd") else ASAGA
        solver = solver_cls(X, y, cfg, devices=devices)
        if driver.endswith("-sync"):
            res = solver.run_sync()
        elif driver.endswith("-fused"):
            res = solver.run_fused()
            if args.event_log:
                # the fused loop has no per-task events; log the trajectory
                # so --event-log/--report keep working (same fallback as
                # the fused-scan sgd-mllib baseline)
                from asyncframework_tpu.solvers.instrumentation import (
                    log_trajectory,
                )

                log_trajectory(args.event_log, res.trajectory,
                               cfg.printer_freq)
        else:
            res = solver.run()
        trajectory = res.trajectory
        summary = {
            "driver": driver,
            "final_objective": res.final_objective,
            "accepted": res.accepted,
            "dropped": res.dropped,
            "rounds": res.rounds,
            "max_staleness": res.max_staleness,
            "avg_delay_ms": res.avg_delay_ms,
            "updates_per_sec": res.updates_per_sec,
            "elapsed_s": res.elapsed_s,
        }
        for key in ("workers_lost", "shards_moved", "speculated"):
            if key in res.extras:
                summary[key] = res.extras[key]
    if args.report:
        from asyncframework_tpu.metrics.report import render_report

        render_report(args.event_log, args.report,
                      title=f"async-submit {driver} run")
        summary["report"] = args.report
    summary["trajectory"] = trajectory
    return summary


def run_async_cluster(args, conf, algo: str = "asgd"):
    """Multi-process ASGD/ASAGA over the DCN parameter server.

    Roles by ``ASYNCTPU_PROCESS_ID``: 0 = PS (binds the coordinator
    address's port; owns the model + updater semantics -- and for ASAGA the
    scalar-history table and sampling), 1..N-1 = worker processes
    (generate/load their shard slice locally, push gradients).  The PS
    prints the run summary; workers print a small role record.
    """
    import numpy as np

    import jax

    from asyncframework_tpu.parallel import ps_dcn
    from asyncframework_tpu.solvers import SolverConfig

    coord = os.environ["ASYNCTPU_COORDINATOR"]
    host, port_s = coord.rsplit(":", 1)
    nproc = int(os.environ.get("ASYNCTPU_NUM_PROCESSES", "1"))
    pid = int(os.environ.get("ASYNCTPU_PROCESS_ID", "0"))
    if nproc < 2:
        raise SystemExit(f"DCN {algo} needs >= 2 processes (PS + workers)")

    # version-gated delta pulls are ON by default for the multi-process
    # cluster path (the wire is where they pay off; the equivalence suite
    # in tests/test_dataplane.py guards byte-exactness) -- an explicit
    # --conf async.pull.mode=full restores the legacy full-pull wire
    if not conf.contains("async.pull.mode"):
        conf.set("async.pull.mode", "delta")
    # the pipelined update loop is likewise ON by default for the cluster
    # path: prefetched pulls + a bounded in-flight push sender overlap the
    # DCN round trips with compute (tests/test_pipeline.py guards depth=0
    # byte-identity and the chaos behavior) -- an explicit
    # --conf async.pipeline.depth=0 restores the serial loop
    if not conf.contains("async.pipeline.depth"):
        conf.set("async.pipeline.depth", 2)
    # convergence telemetry likewise defaults ON for the cluster path:
    # every 16th update per logical worker ships (version, loss,
    # grad_norm) on its PUSH header for the PS's loss-vs-wallclock /
    # loss-vs-version curves (metrics/timeseries.py) -- an explicit
    # --conf async.convergence.sample=0 restores the silent wire
    if not conf.contains("async.convergence.sample"):
        conf.set("async.convergence.sample", 16)
    # epoch fencing defaults ON for the cluster path: servers mint
    # fencing epochs, ops carry them, and a partitioned-then-replaced
    # member's stale writes are REJECT_FENCED instead of silently
    # double-applied (tests/test_fencing.py guards the protocol and the
    # fencing-off byte identity) -- an explicit
    # --conf async.fence.enabled=false restores the legacy wire
    if not conf.contains("async.fence.enabled"):
        conf.set("async.fence.enabled", True)
    # the adaptive asynchrony controller likewise defaults ON for the
    # cluster path: the primary PS closes the loop from the observed
    # signals (per-worker staleness/RTT/compute EWMAs, merge-queue
    # depth, prefetch stalls) to the declared tunables -- delay-adaptive
    # step damping, cohort size, pipeline depth, push-merge budget
    # (parallel/controller.py; tests/test_controller.py guards the
    # control-off byte identity) -- an explicit
    # --conf async.control.enabled=false restores the static knobs
    if not conf.contains("async.control.enabled"):
        conf.set("async.control.enabled", True)
    # the native data plane likewise defaults ON for the cluster path:
    # GIL-free wire codecs (XOR delta, CRC, quantize, byte-shuffle --
    # native/*.cc, bit-identical to the pure-Python oracles, which
    # remain the no-toolchain fallback) and the shared-memory ring
    # transport for colocated role pairs (net/shmring.py; same framed
    # bytes, opportunistic upgrade, TCP degrade).  Explicit
    # --conf async.native.enabled=false / async.shm.enabled=false
    # restore the pure-Python/loopback paths
    if not conf.contains("async.native.enabled"):
        conf.set("async.native.enabled", True)
    if not conf.contains("async.shm.enabled"):
        conf.set("async.shm.enabled", True)

    cfg = SolverConfig(
        num_workers=args.num_partitions,
        num_iterations=args.num_iterations,
        gamma=args.gamma,
        taw=args.taw,
        batch_rate=args.batch_rate,
        bucket_ratio=args.bucket_ratio,
        printer_freq=args.printer_freq,
        coeff=args.coeff,
        seed=args.seed,
        loss=args.loss,
    )
    for key, field in CONF_TO_FIELD.items():
        if conf.contains(key):
            setattr(cfg, field, conf.get(key))

    n_workers_procs = nproc - 1
    if n_workers_procs > cfg.num_workers:
        raise SystemExit(
            f"DCN {algo}: {n_workers_procs} worker processes but only "
            f"{cfg.num_workers} logical workers; every worker process "
            f"needs at least one partition"
        )
    if pid == 0:
        from asyncframework_tpu.conf import ELASTIC_ENABLED, PS_SHARDS

        # sharded PS group (async.ps.shards > 1, ASGD only): this driver
        # process runs shard 0 (the primary -- wave gate, worker
        # supervision, eval plane) on the coordinator port and a
        # ShardGroup controller spawning + supervising the secondary
        # shard processes; workers resolve the map at HELLO.
        ps_shards = max(1, int(conf.get(PS_SHARDS)))
        if ps_shards > 1 and algo != "asgd":
            raise SystemExit("async.ps.shards > 1 supports asgd only "
                             "(ASAGA's PS-side sampling is range-global)")
        ckpt_dir = args.checkpoint_dir
        if ps_shards > 1 and not ckpt_dir:
            # sharded failover is checkpoint-based: a shard relaunched
            # with no durable state would serve a ZERO model for its
            # range mid-run (silent convergence loss).  "Kill any shard,
            # lose nothing" therefore defaults to a run-scoped dir
            # rather than degrading quietly; --checkpoint-dir overrides.
            import tempfile

            ckpt_dir = tempfile.mkdtemp(prefix="async-ps-shards-")
            print(f"async.ps.shards={ps_shards}: no --checkpoint-dir; "
                  f"using {ckpt_dir} for shard failover checkpoints",
                  file=sys.stderr)
        ckpt_path = None
        if ckpt_dir:
            os.makedirs(ckpt_dir, exist_ok=True)
            ckpt_path = (
                os.path.join(ckpt_dir, "ps_shard0.npz")
                if ps_shards > 1
                else os.path.join(ckpt_dir, f"ps_{algo}.npz")
            )
        sup = None
        if conf.get(ELASTIC_ENABLED):
            from asyncframework_tpu.parallel.supervisor import (
                ElasticSupervisor,
            )

            sup = ElasticSupervisor.from_conf(cfg.num_workers, conf)
        # PS-side observability spine: merges + trace spans (the PS's own
        # server-side stages plus the spans workers piggyback on PUSH) flow
        # bus -> event log -> live UI, same as the single-process solvers
        bus = writer = ui = live_state = None
        # cluster cfg is built from the recipe's positional args; the
        # observability flags live on argparse (plus conf overlays)
        ui_port = args.ui_port
        if ui_port is None and conf.contains("async.ui.port"):
            ui_port = int(conf.get("async.ui.port"))
        want_ui = ui_port is not None and ui_port >= 0
        if args.event_log or want_ui:
            from asyncframework_tpu.metrics.bus import ListenerBus
            from asyncframework_tpu.metrics.eventlog import EventLogWriter

            bus = ListenerBus()
            if args.event_log:
                writer = EventLogWriter(args.event_log)
                bus.add_listener(writer)
            if want_ui:
                from asyncframework_tpu.metrics.live import (
                    LiveStateListener,
                    LiveUIServer,
                )

                live_state = LiveStateListener(cfg.num_workers)
                bus.add_listener(live_state)
                ui = LiveUIServer(live_state, port=ui_port).start()
            bus.start()
        group = None
        controller = None
        try:
            ps_d = args.d
            shard_map_wire = None
            if ps_shards > 1:
                from asyncframework_tpu.parallel.shardgroup import (
                    ShardGroup,
                    shard_ranges,
                )

                # the driver IS shard 0 (primary: wave gate, worker
                # supervision, eval plane) on the coordinator port; the
                # ShardGroup controller spawns, probes, and restarts the
                # secondary shard processes on this host.  Workers learn
                # the assembled map from the primary's WELCOME.
                group = ShardGroup(
                    cfg, args.d, args.N, ps_shards, host=host, algo=algo,
                    checkpoint_dir=ckpt_dir,
                    indices=range(1, ps_shards),
                    fixed_entries={0: (host, int(port_s))},
                    conf_overlays=conf.to_dict(),
                    worker_procs=0,
                    stderr_dir=os.environ.get("ASYNC_SHARD_STDERR_DIR"),
                ).start()
                shard_map_wire = group.smap.to_wire()
                ps_d = shard_ranges(args.d, ps_shards)[0][1]
            ps = ps_dcn.ParameterServer(
                cfg, ps_d, args.N, host="0.0.0.0", port=int(port_s),
                algo=algo, checkpoint_path=ckpt_path, supervisor=sup,
                bus=bus, shard_map=shard_map_wire, shard_index=0,
                shard_epochs=(group.epochs_wire()
                              if group is not None else None),
            )
            if conf.get("async.control.enabled"):
                # adaptive asynchrony controller on the primary PS:
                # telemetry -> decisions -> CTRL over WELCOME/PULL (and
                # SETMAP to the shard group, surviving promotions).
                # Started BEFORE ps.start(): the first WELCOME served
                # must already carry the CTRL payload, or a worker that
                # HELLOs in the gap never builds a ControlSink and
                # ignores every decision for the whole run.
                from asyncframework_tpu.parallel.controller import (
                    AsyncController,
                )

                controller = AsyncController(ps, conf=conf,
                                             group=group).start()
            ps.start()
            ok = ps.wait_done(timeout_s=cfg.run_timeout_s)
            if not ok:
                # progress-aware diagnostic: who went silent, who
                # contributed
                print(ok.diagnostic, file=sys.stderr)
            if group is not None:
                # group-wide DONE backstop (workers' BYE already broadcast
                # FINISH best-effort); also stops treating child exits as
                # deaths so teardown is not mistaken for a crash
                group.finish()
            total = ps.collect_eval(n_workers_procs, timeout_s=120.0)
            trajectory = []
            if total is not None:
                times, _W = ps.snapshot_stack()
                # sharded eval stacks are tail-aligned worker-side (the
                # assembled trajectory is the min length across shards),
                # so the loss rows pair with the TAIL of the primary's
                # snapshot times; at shards=1 the slice is the whole list
                times = times[-len(total):]
                trajectory = [
                    (t, float(l) / args.N) for t, l in zip(times, total)
                ]
            ps.stop()
            summary = {
                "driver": f"{algo}-dcn-ps",
                "done": bool(ok),
                "accepted": ps.accepted,
                "dropped": ps.dropped,
                "max_staleness": ps.max_staleness,
                "resumed_from": ps.resumed_from_k,
                "recovery": sup.counters() if sup is not None else None,
                "trace_spans": ps.trace_spans,
                "final_objective": trajectory[-1][1] if trajectory else None,
                "trajectory": trajectory,
            }
            if group is not None:
                # same section /api/status serves (metrics/live.py reads
                # the active group) -- one assembly, no drift
                summary["ps_shards"] = group.status_section()
            if ui is not None:
                summary["ui_port"] = ui.port
            return summary
        finally:
            # teardown on EVERY path: a crash between start() and the
            # summary must still seal the event log (a .gz without its end
            # marker forces every later read through the torn-tail path)
            # and stop the UI/bus threads
            if controller is not None:
                controller.stop()
            if group is not None:
                group.stop()
            if ui is not None:
                ui.stop()
            if bus is not None:
                bus.stop()
            if writer is not None:
                writer.close()
    # ---------------------------------------------------------- worker role
    # per-process telemetry endpoint (async.metrics.port; -1 = off, so a
    # stock cluster run adds no ports): /metrics + /api/status on every
    # worker process, not just the PS/driver dashboard
    from asyncframework_tpu.metrics.live import start_telemetry_from_conf

    start_telemetry_from_conf(f"worker-{pid}", labels={"proc": str(pid)})
    devices = jax.devices()
    if args.devices is not None:
        devices = devices[: args.devices]
    X, _y = load_data(args, cfg, devices, need_host=False)
    wids = [
        w for w in range(cfg.num_workers)
        if w % n_workers_procs == (pid - 1)
    ]
    shards = {w: X.shard(w) for w in wids}
    counts = ps_dcn.run_worker_process(
        host, int(port_s), wids, shards, cfg, args.d, args.N,
        eval_wid=wids[0], deadline_s=cfg.run_timeout_s, algo=algo,
        # every worker process holds the full (deterministic) dataset, so
        # it can materialize ANY shard on adoption orders from the PS
        shard_factory=X.shard,
        proc_token=f"dcn-{os.getpid()}-p{pid}",
    )
    return {
        "driver": f"{algo}-dcn-worker",
        "process_id": pid,
        "gradients": int(sum(counts.values())),
        "trajectory": [],
    }


_CLUSTER_ONLY_FLAGS = {"--master": 1, "--processes": 1,
                       "--wait-timeout": 1, "--supervise": 0, "--no-wait": 0}


def _submit_to_master(args, argv: Optional[List[str]]) -> int:
    """spark-submit --master parity: ship the recipe argv (cluster-only
    flags stripped) to the standalone master daemon; by default wait for a
    terminal state and exit 0 only on FINISHED."""
    from asyncframework_tpu.deploy.client import _client, wait_app

    raw = list(sys.argv[1:] if argv is None else argv)
    submit_argv: List[str] = []
    i = 0
    while i < len(raw):
        tok = raw[i]
        flag = tok.split("=", 1)[0]
        if flag in _CLUSTER_ONLY_FLAGS:
            i += 1
            if _CLUSTER_ONLY_FLAGS[flag] and "=" not in tok:
                i += 1  # consume the flag's value token
            continue
        submit_argv.append(tok)
        i += 1
    cl = _client(args.master)
    app_id = cl.submit(submit_argv, num_processes=args.processes,
                       supervise=args.supervise)
    print(json.dumps({"app_id": app_id, "master": args.master,
                      "num_processes": args.processes,
                      "supervise": bool(args.supervise)}))
    if args.no_wait:
        return 0
    try:
        st = wait_app(args.master, app_id, timeout_s=args.wait_timeout)
    except TimeoutError:
        print(json.dumps({"app_id": app_id, "state": "TIMEOUT",
                          "wait_timeout_s": args.wait_timeout}))
        return 1
    print(json.dumps({"app_id": app_id, "state": st["state"],
                      "exits": st["exits"]}))
    return 0 if st["state"] == "FINISHED" else 1


def main(argv: Optional[List[str]] = None) -> int:
    if os.environ.get("ASYNCTPU_FORCE_CPU"):
        # the local-cluster launcher's test-rig mode: the env var alone
        # cannot force CPU (the image's sitecustomize latches the TPU
        # plugin first); the config API set before any device touch can
        import jax

        jax.config.update("jax_platforms", "cpu")
    args = build_parser().parse_args(argv)
    if args.master:
        return _submit_to_master(args, argv)
    conf = parse_conf_overlays(args.conf)
    if args.trace_sample is not None:
        # install in the process conf too: the DCN worker/PS paths resolve
        # their recorders from async.trace.sample, not SolverConfig
        conf.set("async.trace.sample", args.trace_sample)
    summary = run_driver(args, conf)
    trajectory = summary.pop("trajectory")
    if not args.quiet:
        for t_ms, obj in trajectory:
            print(f"({t_ms:.1f},{obj:.8g})")
    if args.output:
        with open(args.output, "w") as f:
            f.write("ms,objective\n")
            for t_ms, obj in trajectory:
                f.write(f"{t_ms:.3f},{obj:.10g}\n")
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
