"""Versioned model distribution: the broadcast layer.

Parity (studied, not copied):
- ``broadcast/TorrentBroadcast.scala:57`` -- each round the driver broadcasts
  a fresh model snapshot to every worker (the parameter-server "push").
- ``broadcast/Broadcast.scala:74-80`` + ``broadcast/ASYNCbroadcast.scala:12-46``
  -- broadcast handles carry a *version id* that can be re-pointed so a worker
  can read an **older** model version (the stale-read experiment mechanism).

TPU mapping: "broadcast" is ``jax.device_put`` of the host-resident ``w`` to
each participating device -- a DMA into HBM, asynchronous by default, fanned
out over PCIe/ICI by the runtime (no torrent protocol needed; the
interconnect is the torrent).  A version is an integer; the store keeps the
last ``max_live_versions`` snapshots per device (HBM ring buffer), so

- ``store.publish(w)``                       = ``sc.broadcast(w)``
- ``store.value(device)``                    = ``bc.value`` (latest)
- ``store.value(device, version=v)``         = ``ASYNCbroadcast.value(index)``
- eviction of old versions                   = ``Broadcast.destroy``

The updater owns the host ``w``; workers only ever see published snapshots
(single-writer discipline replacing the reference's benign torn-read races).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional

import jax
import numpy as np


class VersionedModelStore:
    def __init__(self, max_live_versions: int = 4):
        if max_live_versions < 1:
            raise ValueError("max_live_versions must be >= 1")
        self._max_live = max_live_versions
        self._lock = threading.Lock()
        self._next_version = 0
        # version -> (host snapshot, {device -> device buffer})
        self._versions: "OrderedDict[int, tuple]" = OrderedDict()

    # ---------------------------------------------------------------- publish
    def publish(self, w: np.ndarray, devices=None) -> int:
        """Snapshot ``w`` as a new version and start its device transfers.

        ``device_put`` is asynchronous: the host thread returns while DMAs
        proceed; a worker touching the buffer later blocks only if its copy
        has not landed yet.
        """
        host = np.array(w, copy=True)  # snapshot: updater keeps mutating w
        with self._lock:
            v = self._next_version
            self._next_version += 1
            buffers: Dict = {}
            if devices:
                seen = set()
                for dev in devices:
                    if dev is not None and dev not in seen:
                        seen.add(dev)
                        buffers[dev] = jax.device_put(host, dev)
            self._versions[v] = (host, buffers)
            while len(self._versions) > self._max_live:
                self._versions.popitem(last=False)  # evict oldest
            return v

    # ------------------------------------------------------------------ reads
    def latest_version(self) -> int:
        with self._lock:
            if not self._versions:
                raise KeyError("no version published yet")
            return next(reversed(self._versions))

    def value(self, device=None, version: Optional[int] = None):
        """Device buffer (or host snapshot when device is None) of a version.

        ``version=None`` reads the latest (``bc.value``); an explicit older
        version is the ``ASYNCbroadcast.value(index)`` stale read.  Raises
        ``KeyError`` for evicted/unknown versions.
        """
        with self._lock:
            v = version if version is not None else (
                next(reversed(self._versions)) if self._versions else None
            )
            if v is None or v not in self._versions:
                raise KeyError(f"model version {v} not live")
            host, buffers = self._versions[v]
            if device is None:
                return host
            buf = buffers.get(device)
        if buf is not None:
            return buf
        # lazy fan-out: first read from a device not in the publish set
        buf = jax.device_put(host, device)
        with self._lock:
            if v in self._versions:
                self._versions[v][1][device] = buf
        return buf

    def live_versions(self):
        with self._lock:
            return list(self._versions.keys())
