"""Sparse (rcv1-class) device-resident sharded dataset.

Parity: the reference loads rcv1_full.binary (47,236 features, ~0.16% dense)
through ``MLUtils.loadLibSVMFile`` into sparse vectors and runs the same
ASGD/ASAGA recipes on it (``README.md:44-46,64``).

TPU-first representation: CSR's ragged rows defeat XLA's static-shape
compilation, and densifying rcv1 is impossible (47k x 700k f32 = 131 GB).
Each shard is stored as **padded ELL**: per-row fixed-width ``cols (n_p, K)``
/ ``vals (n_p, K)`` arrays where ``K`` is the shard's max row nnz rounded up
to a lane multiple; padding entries have ``col=0, val=0`` so they contribute
exactly zero to every product.  The worker step then needs no dynamic shapes:

- residual: ``r_i = sum_k vals[i,k] * w[cols[i,k]] - y_i``  (gather + reduce)
- gradient: ``g = scatter_add(zeros(d), cols, vals * coeff[:, None])``

both of which XLA compiles to static gather/scatter kernels.  This is the
SURVEY section-7 "densify per batch" alternative done one better: the batch
is never densified at all; only the (d,) gradient is dense, which the
parameter server needs dense anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from asyncframework_tpu.data.sharded import balanced_sizes


def _round_up(k: int, mult: int = 8) -> int:
    return max(mult, ((k + mult - 1) // mult) * mult)


@dataclass
class SparseShard:
    worker_id: int
    cols: jax.Array  # (n_p, K) int32, padded with 0
    vals: jax.Array  # (n_p, K) f32, padded with 0.0
    y: jax.Array     # (n_p,)
    start: int
    size: int

    @property
    def device(self):
        return self.vals.device


class SparseShardedDataset:
    """Immutable row-sharded CSR data in padded-ELL device residency."""

    is_sparse = True

    def __init__(
        self,
        indptr: np.ndarray,
        indices: np.ndarray,
        values: np.ndarray,
        y: np.ndarray,
        d: int,
        num_workers: int,
        devices: Optional[Sequence] = None,
    ):
        n = len(indptr) - 1
        if y.shape[0] != n:
            raise ValueError(f"indptr implies {n} rows but y has {y.shape[0]}")
        self.n, self.d, self.num_workers = n, int(d), num_workers
        sizes = balanced_sizes(n, num_workers)
        devs = list(devices) if devices is not None else jax.devices()
        cum = np.concatenate([[0], np.cumsum(sizes)])
        self.partition_cum: List[int] = [int(c) for c in cum]
        self.shards: Dict[int, SparseShard] = {}
        indptr = np.asarray(indptr, np.int64)
        for w in range(num_workers):
            lo, hi = self.partition_cum[w], self.partition_cum[w + 1]
            row_nnz = indptr[lo + 1 : hi + 1] - indptr[lo:hi]
            K = _round_up(int(row_nnz.max()) if len(row_nnz) else 1)
            size = hi - lo
            cols = np.zeros((size, K), np.int32)
            vals = np.zeros((size, K), np.float32)
            # vectorized CSR -> ELL packing (a Python per-row loop would be
            # an interpreter-speed O(n) pass on exactly the rcv1-scale data
            # this class exists for): destination (row, slot) of the shard's
            # j-th nonzero is (its row, offset within its row)
            a0, b0 = int(indptr[lo]), int(indptr[hi])
            if b0 > a0:
                rows = np.repeat(np.arange(size), row_nnz)
                slots = np.arange(b0 - a0) - np.repeat(
                    (indptr[lo:hi] - a0), row_nnz
                )
                cols[rows, slots] = indices[a0:b0]
                vals[rows, slots] = values[a0:b0]
            dev = devs[w % len(devs)]
            self.shards[w] = SparseShard(
                worker_id=w,
                cols=jax.device_put(cols, dev),
                vals=jax.device_put(vals, dev),
                y=jax.device_put(np.asarray(y[lo:hi], np.float32), dev),
                start=lo,
                size=size,
            )

    # ------------------------------------------------------------------ views
    def shard(self, worker_id: int) -> SparseShard:
        return self.shards[worker_id]

    def partition_sizes(self) -> Dict[int, int]:
        return {w: s.size for w, s in self.shards.items()}

    def nnz(self) -> int:
        """True non-padding entries across all shards (for HBM accounting
        use ``padded_nnz``; padding occupies real memory)."""
        total = 0
        for s in self.shards.values():
            total += int(np.count_nonzero(np.asarray(s.vals)))
        return total

    def padded_nnz(self) -> int:
        return sum(int(np.prod(s.vals.shape)) for s in self.shards.values())

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"SparseShardedDataset(n={self.n}, d={self.d}, "
            f"workers={self.num_workers})"
        )


def densify(ds: SparseShardedDataset) -> Tuple[np.ndarray, np.ndarray]:
    """Small-fixture helper (tests / baselines): padded-ELL -> dense host X."""
    X = np.zeros((ds.n, ds.d), np.float32)
    ys = []
    for w in range(ds.num_workers):
        s = ds.shard(w)
        cols = np.asarray(s.cols)
        vals = np.asarray(s.vals)
        for j in range(s.size):
            # unbuffered accumulate: fancy += would drop duplicate indices
            # (padding shares col 0 with real entries)
            np.add.at(X[s.start + j], cols[j], vals[j])
        ys.append(np.asarray(s.y))
    return X, np.concatenate(ys)
