"""Sparse (rcv1-class) device-resident sharded dataset.

Parity: the reference loads rcv1_full.binary (47,236 features, ~0.16% dense)
through ``MLUtils.loadLibSVMFile`` into sparse vectors and runs the same
ASGD/ASAGA recipes on it (``README.md:44-46,64``).

TPU-first representation: CSR's ragged rows defeat XLA's static-shape
compilation, and densifying rcv1 is impossible (47k x 700k f32 = 131 GB).
Each shard is stored as **padded ELL**: per-row fixed-width ``cols (n_p, K)``
/ ``vals (n_p, K)`` arrays where ``K`` is the shard's max row nnz rounded up
to a lane multiple; padding entries have ``col=0, val=0`` so they contribute
exactly zero to every product.  The worker step then needs no dynamic shapes:

- residual: ``r_i = sum_k vals[i,k] * w[cols[i,k]] - y_i``  (gather + reduce)
- gradient: ``g = scatter_add(zeros(d), cols, vals * coeff[:, None])``

both of which XLA compiles to static gather/scatter kernels.  This is the
SURVEY section-7 "densify per batch" alternative done one better: the batch
is never densified at all; only the (d,) gradient is dense, which the
parameter server needs dense anyway.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from asyncframework_tpu.data.sharded import balanced_sizes


def _round_up(k: int, mult: int = 8) -> int:
    return max(mult, ((k + mult - 1) // mult) * mult)


@dataclass
class SparseShard:
    worker_id: int
    cols: jax.Array  # (n_p, K) int32, padded with 0
    vals: jax.Array  # (n_p, K) f32, padded with 0.0
    y: jax.Array     # (n_p,)
    start: int
    size: int

    @property
    def device(self):
        return self.vals.device


class SparseShardedDataset:
    """Immutable row-sharded CSR data in padded-ELL device residency."""

    is_sparse = True

    @classmethod
    def generate_on_device(
        cls,
        n: int,
        d: int,
        nnz_per_row: int,
        num_workers: int,
        devices: Optional[Sequence] = None,
        seed: int = 42,
        noise: float = 0.01,
    ) -> "SparseShardedDataset":
        """Synthesize a planted rcv1-shaped sparse problem directly in HBM.

        Each row has ``nnz_per_row`` entries at uniform random columns with
        values N(0, 1/nnz), so ``E[x x^T] = I/d`` -- the same conditioning as
        the dense generator, which keeps step-size tuning commensurable
        across bench configs.  Labels are ``x . w* + noise`` computed on
        device.  Rows are padded to a lane multiple exactly like the CSR
        path; padding slots carry ``col=0, val=0``.
        """
        import functools

        import jax.numpy as jnp

        obj = cls.__new__(cls)
        sizes = balanced_sizes(n, num_workers)
        obj.n, obj.d, obj.num_workers = n, int(d), num_workers
        devs = list(devices) if devices is not None else jax.devices()
        cum = np.concatenate([[0], np.cumsum(sizes)])
        obj.partition_cum = [int(c) for c in cum]
        K = _round_up(int(nnz_per_row))

        @functools.partial(jax.jit, static_argnums=(2,))
        def gen_shard(key, w_true, size):
            kc, kv, kn = jax.random.split(key, 3)
            cols = jax.random.randint(kc, (size, K), 0, d, jnp.int32)
            vals = jax.random.normal(kv, (size, K), jnp.float32) / jnp.sqrt(
                float(nnz_per_row)
            )
            live = (jnp.arange(K) < nnz_per_row)[None, :]
            cols = jnp.where(live, cols, 0)
            vals = jnp.where(live, vals, 0.0)
            yp = jnp.sum(vals * w_true[cols], axis=1) + noise * (
                jax.random.normal(kn, (size,), jnp.float32)
            )
            return cols, vals, yp

        obj.row_perm = np.arange(n)
        root = jax.random.fold_in(jax.random.PRNGKey(seed), 0x53505253)  # "SPRS"
        w_true = jax.random.normal(
            jax.random.fold_in(root, 2**30), (d,), jnp.float32
        )
        obj.shards = {}
        for w in range(num_workers):
            dev = devs[w % len(devs)]
            key = jax.device_put(jax.random.fold_in(root, w), dev)
            cols, vals, yp = gen_shard(
                key, jax.device_put(w_true, dev), sizes[w]
            )
            obj.shards[w] = SparseShard(
                worker_id=w, cols=cols, vals=vals, y=yp,
                start=obj.partition_cum[w], size=sizes[w],
            )
        return obj

    #: warn when a shard's padded footprint exceeds its true nnz by this
    #: factor AND the max/mean row-nnz ratio exceeds SKEW_RATIO -- one dense
    #: outlier row multiplies the whole shard's HBM cost under padded ELL
    PAD_OVERHEAD_WARN = 4.0
    SKEW_RATIO_WARN = 8.0

    def __init__(
        self,
        indptr: np.ndarray,
        indices: np.ndarray,
        values: np.ndarray,
        y: np.ndarray,
        d: int,
        num_workers: int,
        devices: Optional[Sequence] = None,
        nnz_partition: bool = False,
    ):
        """``nnz_partition=True`` assigns rows to shards in row-nnz-sorted
        order (a stable permutation, recorded in ``row_perm``) so each
        shard's pad width tracks its own densest row instead of the global
        outlier -- the skew guard's *fix*.  Statistically neutral for the
        solvers (workers Bernoulli-sample within their shard either way);
        ``start``/``partition_cum`` then index the permuted order, and
        shard ``j``'s original row id is ``row_perm[start + j]``.  Without
        it, a skewed matrix still loads but emits a detailed warning
        (``skew_report``).
        """
        n = len(indptr) - 1
        if y.shape[0] != n:
            raise ValueError(f"indptr implies {n} rows but y has {y.shape[0]}")
        self.n, self.d, self.num_workers = n, int(d), num_workers
        sizes = balanced_sizes(n, num_workers)
        devs = list(devices) if devices is not None else jax.devices()
        cum = np.concatenate([[0], np.cumsum(sizes)])
        self.partition_cum: List[int] = [int(c) for c in cum]
        self.shards: Dict[int, SparseShard] = {}
        indptr = np.asarray(indptr, np.int64)
        all_nnz = indptr[1:] - indptr[:-1]
        if nnz_partition:
            self.row_perm = np.argsort(all_nnz, kind="stable")
        else:
            self.row_perm = np.arange(n)
        y = np.asarray(y, np.float32)
        for w in range(num_workers):
            lo, hi = self.partition_cum[w], self.partition_cum[w + 1]
            rows = self.row_perm[lo:hi]
            row_nnz = all_nnz[rows]
            K = _round_up(int(row_nnz.max()) if len(row_nnz) else 1)
            size = hi - lo
            cols = np.zeros((size, K), np.int32)
            vals = np.zeros((size, K), np.float32)
            # vectorized CSR -> ELL packing (a Python per-row loop would be
            # an interpreter-speed O(n) pass on exactly the rcv1-scale data
            # this class exists for): the shard's j-th nonzero comes from
            # source position indptr[row]+slot and lands at (row, slot)
            total = int(row_nnz.sum())
            if total > 0:
                dst_rows = np.repeat(np.arange(size), row_nnz)
                slots = np.arange(total) - np.repeat(
                    np.cumsum(row_nnz) - row_nnz, row_nnz
                )
                src = np.repeat(indptr[rows], row_nnz) + slots
                cols[dst_rows, slots] = indices[src]
                vals[dst_rows, slots] = values[src]
            dev = devs[w % len(devs)]
            self.shards[w] = SparseShard(
                worker_id=w,
                cols=jax.device_put(cols, dev),
                vals=jax.device_put(vals, dev),
                y=jax.device_put(y[rows], dev),
                start=lo,
                size=size,
            )
        # the guard only *suggests* nnz_partition when it is off; with it on,
        # residual padding is inherent (a dense row among light rows in the
        # same shard) and re-warning would be noise
        if not nnz_partition:
            self._maybe_warn_skew(all_nnz)

    # ----------------------------------------------------------- skew guard
    def skew_report(self) -> Dict[str, float]:
        """Padding-cost accounting: the true nnz, what padded ELL actually
        occupies, and the worst per-shard max/mean row-nnz ratio."""
        true_nnz = 0
        padded = 0
        worst_ratio = 0.0
        for s in self.shards.values():
            v = np.asarray(s.vals)
            row_nnz = np.count_nonzero(v, axis=1)
            true_nnz += int(row_nnz.sum())
            padded += int(np.prod(v.shape))
            mean = max(float(row_nnz.mean()), 1e-9)
            worst_ratio = max(worst_ratio, float(row_nnz.max()) / mean)
        return {
            "nnz": true_nnz,
            "padded_nnz": padded,
            "pad_overhead": padded / max(true_nnz, 1),
            "worst_shard_skew": worst_ratio,
        }

    def _maybe_warn_skew(self, all_nnz: np.ndarray) -> None:
        """rcv1-class real data is skewed: one dense row pads the whole
        shard to its width.  Computed from host-side CSR stats (free) --
        not :meth:`skew_report`, which reads device buffers back."""
        import warnings

        padded = sum(int(np.prod(s.vals.shape)) for s in self.shards.values())
        true_nnz = max(int(all_nnz.sum()), 1)
        overhead = padded / true_nnz
        mean = max(float(all_nnz.mean()), 1e-9)
        skew = float(all_nnz.max()) / mean
        if overhead > self.PAD_OVERHEAD_WARN and skew > self.SKEW_RATIO_WARN:
            warnings.warn(
                f"padded-ELL overhead {overhead:.1f}x true nnz (max/mean "
                f"row nnz = {skew:.1f}): a few dense rows are inflating "
                f"every shard's pad width; rebuild with nnz_partition=True "
                f"to bound padding per shard",
                RuntimeWarning,
                stacklevel=3,
            )

    # ------------------------------------------------------------------ views
    def shard(self, worker_id: int) -> SparseShard:
        return self.shards[worker_id]

    def partition_sizes(self) -> Dict[int, int]:
        return {w: s.size for w, s in self.shards.items()}

    def nnz(self) -> int:
        """True non-padding entries across all shards (for HBM accounting
        use ``padded_nnz``; padding occupies real memory)."""
        total = 0
        for s in self.shards.values():
            total += int(np.count_nonzero(np.asarray(s.vals)))
        return total

    def padded_nnz(self) -> int:
        return sum(int(np.prod(s.vals.shape)) for s in self.shards.values())

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"SparseShardedDataset(n={self.n}, d={self.d}, "
            f"workers={self.num_workers})"
        )


def densify(ds: SparseShardedDataset) -> Tuple[np.ndarray, np.ndarray]:
    """Small-fixture helper (tests / baselines): padded-ELL -> dense host X.

    Rows come back in SHARD order (the dataset's own ordering): under
    ``nnz_partition`` that is the permuted order, with original row ids in
    ``ds.row_perm`` -- X and y stay mutually consistent either way."""
    X = np.zeros((ds.n, ds.d), np.float32)
    ys = []
    for w in range(ds.num_workers):
        s = ds.shard(w)
        cols = np.asarray(s.cols)
        vals = np.asarray(s.vals)
        for j in range(s.size):
            # unbuffered accumulate: fancy += would drop duplicate indices
            # (padding shares col 0 with real entries)
            np.add.at(X[s.start + j], cols[j], vals[j])
        ys.append(np.asarray(s.y))
    return X, np.concatenate(ys)
