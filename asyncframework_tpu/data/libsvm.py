"""LibSVM-format data loading.

Parity: ``mllib/.../util/MLUtils.scala:71`` (``loadLibSVMFile``) -- the input
format of every reference experiment (mnist8m.scale, epsilon, rcv1: lines of
``label idx:val idx:val ...`` with 1-based indices).

Two paths:
- pure-Python/numpy parser (always available);
- a C++ fast parser (``native/libsvm_parser.cc``) loaded via ctypes when the
  shared library has been built (``python -m asyncframework_tpu.data.libsvm
  --build`` or ``make -C native``), ~10-30x faster on mnist8m-scale text --
  the TPU-native equivalent of the reference reading through Hadoop's native
  I/O stack.

Output is dense ``(X, y)`` float32 by default (TPU-friendly); sparse CSR
triplets are available for very sparse data (rcv1) via ``as_sparse=True``.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional, Tuple

import numpy as np

_NATIVE = None

#: native symbol -> pure-Python twin (native-oracle lint contract).
#: Both native symbols serve one dense-load fast path whose single
#: fallback is the line parser.
NATIVE_ORACLES = {
    "parse_libsvm_dense": "parse_libsvm_lines",
    "count_lines": "parse_libsvm_lines",
}


def _native_lib():
    """Load (building on demand) the C++ parser; None when unavailable."""
    global _NATIVE
    if _NATIVE is not None:
        return _NATIVE or None
    try:
        from asyncframework_tpu.native_build import ensure_built

        built = ensure_built("libsvm_parser")
    except Exception:
        built = None
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = ([built] if built else []) + [
        os.path.join(here, "..", "native", "libsvm_parser.so"),
        os.path.join(here, "native", "libsvm_parser.so"),
    ]
    for c in candidates:
        c = os.path.abspath(c)
        if os.path.exists(c):
            try:
                lib = ctypes.CDLL(c)
                lib.parse_libsvm_dense.restype = ctypes.c_longlong
                lib.parse_libsvm_dense.argtypes = [
                    ctypes.c_char_p,   # buffer
                    ctypes.c_longlong, # buffer len
                    ctypes.c_longlong, # num features (0 = infer not supported)
                    ctypes.POINTER(ctypes.c_float),  # X out (rows*d)
                    ctypes.POINTER(ctypes.c_float),  # y out (rows)
                    ctypes.c_longlong, # max rows
                ]
                lib.count_lines.restype = ctypes.c_longlong
                lib.count_lines.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
                _NATIVE = lib
                return lib
            except OSError:
                continue
    _NATIVE = False
    return None


def parse_libsvm_lines(
    lines, num_features: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray]:
    """Parse an iterable of LibSVM text lines to dense ``(X, y)`` (pure Python)."""
    labels = []
    rows = []  # list of (idx_array, val_array)
    max_idx = 0
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        idxs = np.empty(len(parts) - 1, np.int64)
        vals = np.empty(len(parts) - 1, np.float32)
        for j, tok in enumerate(parts[1:]):
            k, v = tok.split(":")
            idxs[j] = int(k)
            vals[j] = float(v)
        if len(idxs) and idxs[-1] > max_idx:
            max_idx = int(idxs[-1])
        rows.append((idxs, vals))
    d = num_features if num_features is not None else max_idx
    X = np.zeros((len(rows), d), np.float32)
    for i, (idxs, vals) in enumerate(rows):
        X[i, idxs - 1] = vals  # libsvm indices are 1-based
    return X, np.asarray(labels, np.float32)


def parse_libsvm_lines_sparse(
    lines, num_features: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Parse LibSVM text to CSR triplets ``(indptr, indices, values, y)``
    with 0-based ``indices`` -- the rcv1-class path that must never densify
    (``MLUtils.loadLibSVMFile`` parity; 47k-dim rcv1 would be 131 GB dense)."""
    labels = []
    indptr = [0]
    indices: list = []
    values: list = []
    max_idx = 0
    for line in lines:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        labels.append(float(parts[0]))
        for tok in parts[1:]:
            k, v = tok.split(":")
            ki = int(k)
            if ki < 1 or (num_features is not None and ki > num_features):
                # must fail HERE: downstream the jitted gather/scatter clamps
                # out-of-range indices, which would silently corrupt training
                raise ValueError(
                    f"libsvm feature index {ki} out of range "
                    f"[1, {num_features}]"
                )
            if ki > max_idx:
                max_idx = ki
            indices.append(ki - 1)  # libsvm is 1-based
            values.append(float(v))
        indptr.append(len(indices))
    del max_idx  # callers size d themselves (sharding requires explicit d)
    return (
        np.asarray(indptr, np.int64),
        np.asarray(indices, np.int32),
        np.asarray(values, np.float32),
        np.asarray(labels, np.float32),
    )


def load_libsvm_sparse(
    path: str, num_features: Optional[int] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Load a LibSVM file as CSR triplets (see
    :func:`parse_libsvm_lines_sparse`)."""
    with open(path, "r") as f:
        return parse_libsvm_lines_sparse(f, num_features)


def load_libsvm(
    path: str,
    num_features: Optional[int] = None,
    use_native: bool = True,
) -> Tuple[np.ndarray, np.ndarray]:
    """Load a LibSVM file to dense ``(X, y)``; uses the C++ parser if built."""
    lib = _native_lib() if (use_native and num_features is not None) else None
    if lib is not None:
        with open(path, "rb") as f:
            buf = f.read()
        n_rows = lib.count_lines(buf, len(buf))
        X = np.zeros((n_rows, num_features), np.float32)
        y = np.zeros((n_rows,), np.float32)
        parsed = lib.parse_libsvm_dense(
            buf,
            len(buf),
            num_features,
            X.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            n_rows,
        )
        if parsed < 0:
            raise ValueError(f"native libsvm parse failed with code {parsed}")
        return X[:parsed], y[:parsed]
    with open(path, "r") as f:
        return parse_libsvm_lines(f, num_features)
