from asyncframework_tpu.data.libsvm import (
    load_libsvm,
    load_libsvm_sparse,
    parse_libsvm_lines,
    parse_libsvm_lines_sparse,
)
from asyncframework_tpu.data.synthetic import (
    make_classification,
    make_regression,
    make_sparse_regression,
)
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.data.sparse import SparseShardedDataset, densify
from asyncframework_tpu.data.dataset import DistributedDataset
from asyncframework_tpu.data import random as random_datasets

__all__ = [
    "random_datasets",
    "load_libsvm",
    "load_libsvm_sparse",
    "parse_libsvm_lines",
    "parse_libsvm_lines_sparse",
    "make_regression",
    "make_classification",
    "make_sparse_regression",
    "ShardedDataset",
    "SparseShardedDataset",
    "densify",
    "DistributedDataset",
]
