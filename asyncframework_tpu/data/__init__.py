from asyncframework_tpu.data.libsvm import load_libsvm, parse_libsvm_lines
from asyncframework_tpu.data.synthetic import make_regression, make_classification
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.data.dataset import DistributedDataset

__all__ = [
    "load_libsvm",
    "parse_libsvm_lines",
    "make_regression",
    "make_classification",
    "ShardedDataset",
    "DistributedDataset",
]
