"""Synthetic dataset generators shaped like the reference's workloads.

The reference benchmarks on mnist8m.scale (8.1M x 784), epsilon (400k x 2000,
dense), and rcv1_full.binary (~697k x 47,236, ~0.16% dense).  This container
has no network egress, so benchmarks and tests use seeded synthetic datasets
with the same shapes/statistics; loaders accept the real files when present.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def make_regression(
    n: int, d: int, seed: int = 42, noise: float = 0.01, dtype=np.float32
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Dense least-squares problem: returns (X, y, w_true)."""
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, d)).astype(dtype) / np.sqrt(d)
    w_true = rs.normal(size=(d,)).astype(dtype)
    y = (X @ w_true + noise * rs.normal(size=(n,))).astype(dtype)
    return X, y, w_true


def make_classification(
    n: int, d: int, seed: int = 42, dtype=np.float32
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Binary {0,1} logistic problem: returns (X, y, w_true)."""
    rs = np.random.default_rng(seed)
    X = rs.normal(size=(n, d)).astype(dtype) / np.sqrt(d)
    w_true = rs.normal(size=(d,)).astype(dtype)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rs.random(n) < p).astype(dtype)
    return X, y, w_true


def make_sparse_regression(
    n: int, d: int, density: float = 0.002, seed: int = 42
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """rcv1-like sparse problem in CSR triplets: (indptr, indices, values, y)."""
    rs = np.random.default_rng(seed)
    nnz_per_row = max(1, int(density * d))
    indptr = np.arange(0, (n + 1) * nnz_per_row, nnz_per_row, dtype=np.int32)
    indices = np.empty(n * nnz_per_row, np.int32)
    for i in range(n):
        indices[i * nnz_per_row : (i + 1) * nnz_per_row] = rs.choice(
            d, nnz_per_row, replace=False
        )
    values = rs.normal(size=n * nnz_per_row).astype(np.float32)
    w_true = rs.normal(size=(d,)).astype(np.float32)
    y = np.empty(n, np.float32)
    for i in range(n):
        cols = indices[indptr[i] : indptr[i + 1]]
        vals = values[indptr[i] : indptr[i + 1]]
        y[i] = vals @ w_true[cols]
    return indptr, indices, values, y
