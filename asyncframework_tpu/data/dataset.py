"""Distributed dataset: the user-facing partitioned-collection API (L5).

Parity (studied, not copied): the reference's RDD surface --
transformations ``map`` / ``filter`` / ``mapPartitions`` / ``sample``
(``rdd/RDD.scala:488``) / ``zipWithIndex`` (``rdd/RDD.scala:1527``), actions
``reduce`` / ``aggregate`` (``rdd/RDD.scala:1227-1261``) / ``treeAggregate``
(``rdd/RDD.scala:1358+``) / ``count`` / ``collect``, caching, and the ASYNC
delta ops ``ASYNCreduce`` (``rdd/RDD.scala:1087-1171``), ``ASYNCaggregate``
(``rdd/RDD.scala:1268-1345``) and ``ASYNCbarrier`` (``rdd/RDD.scala:1050-1077``).

TPU mapping / design deltas:
- A partition is a lazily-computed payload produced by a compute closure; the
  closure runs on the partition's worker (an executor thread owning a device
  slot), so a payload is typically a ``jax.Array`` batch resident in that
  worker's HBM -- lineage is closure composition, not a DAG of shuffle files.
- Payloads are iterables of elements.  Device-array users produce one-element
  payloads (e.g. ``[gradient]``) via :meth:`map_partitions`; the engine never
  forces a host transfer -- reduction combines whatever the elements are.
- ``ASYNCbarrier``'s global mutable ``RDD.WorkerList`` (``rdd/RDD.scala:2152``)
  is replaced by an explicit cohort value: :meth:`barrier` *returns* the
  selected worker ids, and the async actions take a ``cohort`` argument.
- The driver-side merge in ``ASYNCreduce``'s ``mergeResult`` callback
  (staleness stamp, STAT update, clock bump -- ``rdd/RDD.scala:1144-1165``)
  is ``AsyncContext.merge_result`` here, invoked from the completing
  executor's thread exactly as the reference invokes it from the DAG
  event-loop thread.
"""

from __future__ import annotations

import bisect
import pickle
import threading
from typing import (
    Any,
    Callable,
    Dict,
    Generic,
    Iterable,
    List,
    Optional,
    Sequence,
    Tuple,
    TypeVar,
)

import numpy as np

from asyncframework_tpu.context import AsyncContext, WorkerState
from asyncframework_tpu.data.pairs import PairOpsMixin
from asyncframework_tpu.engine.barrier import partial_barrier
from asyncframework_tpu.engine.job import JobWaiter
from asyncframework_tpu.engine.scheduler import ASYNC, SYNC, JobScheduler

E = TypeVar("E")
U = TypeVar("U")


class DistributedDataset(PairOpsMixin, Generic[E]):
    """A partitioned collection whose partitions compute on engine workers.

    Construction is cheap and lazy; partition payloads materialize only when
    an action runs (or :meth:`cache` pins them).  Transformations compose
    compute closures -- the functional-lineage analog of RDD chaining.
    """

    def __init__(
        self,
        scheduler: JobScheduler,
        parts: Dict[int, Callable[[], Iterable[E]]],
    ):
        bad = [wid for wid in parts if not 0 <= wid < scheduler.num_workers]
        if bad:
            raise ValueError(
                f"partition ids {bad} out of range for a "
                f"{scheduler.num_workers}-worker scheduler (a partition is "
                f"pinned to the worker with its id)"
            )
        self.scheduler = scheduler
        self._parts = dict(parts)
        self._cache: Optional[Dict[int, List[E]]] = None
        self._cache_lock = threading.Lock()

    # ------------------------------------------------------------ constructors
    @classmethod
    def from_list(
        cls,
        scheduler: JobScheduler,
        data: Sequence[E],
        num_partitions: Optional[int] = None,
    ) -> "DistributedDataset[E]":
        """``sc.parallelize`` analog: contiguous balanced split of a sequence."""
        p = num_partitions or scheduler.num_workers
        if p > scheduler.num_workers:
            raise ValueError(
                f"num_partitions={p} exceeds num_workers="
                f"{scheduler.num_workers}; partitions are worker-pinned"
            )
        n = len(data)
        sizes = [n // p + (1 if i < n % p else 0) for i in range(p)]
        parts: Dict[int, Callable[[], Iterable[E]]] = {}
        lo = 0
        for wid, s in enumerate(sizes):
            chunk = list(data[lo : lo + s])
            parts[wid] = (lambda c=chunk: c)
            lo += s
        return cls(scheduler, parts)

    @classmethod
    def from_partitions(
        cls,
        scheduler: JobScheduler,
        payloads: Dict[int, Iterable[E]],
    ) -> "DistributedDataset[E]":
        return cls(
            scheduler,
            {wid: (lambda p=list(pl): p) for wid, pl in payloads.items()},
        )

    @classmethod
    def from_array_pairs(
        cls,
        scheduler: JobScheduler,
        blocks: Dict[int, Tuple],
        devices: Optional[Sequence] = None,
    ) -> "DistributedDataset":
        """Column-format pair partitions for the DEVICE shuffle path: each
        partition's payload is ONE element -- a ``(keys, values)`` pair of
        device arrays on the partition's worker device.  ``reduce_by_key``
        with a string op then shuffles entirely on device
        (ops/shuffle.py)."""
        import jax
        import jax.numpy as jnp

        devs = list(devices) if devices is not None else jax.devices()
        placed: Dict[int, List] = {}
        for wid, (k, v) in blocks.items():
            dev = devs[wid % len(devs)]
            placed[wid] = [(
                jax.device_put(jnp.asarray(k), dev),
                jax.device_put(jnp.asarray(v), dev),
            )]
        return cls(
            scheduler,
            {wid: (lambda p=pl: p) for wid, pl in placed.items()},
        )

    # ---------------------------------------------------------------- plumbing
    @property
    def num_partitions(self) -> int:
        return len(self._parts)

    def partition_ids(self) -> List[int]:
        return sorted(self._parts)

    def _compute(self, wid: int) -> List[E]:
        """Materialize one partition (on the calling thread).

        Cache hits return a fresh list (shallow copy) so downstream in-place
        list mutation cannot corrupt the cached payload.  The cache dict is
        captured once per call: :meth:`checkpoint` may null ``_cache`` from
        another thread mid-action (writing into the dead dict is harmless).
        """
        cache = self._cache
        if cache is not None:
            with self._cache_lock:
                hit = cache.get(wid)
            if hit is not None:
                return list(hit)
        out = list(self._parts[wid]())
        if cache is not None:
            with self._cache_lock:
                cache[wid] = out
                out = list(out)
        return out

    def cache(self) -> "DistributedDataset[E]":
        """Pin computed payloads (``RDD.cache`` parity: compute-once)."""
        if self._cache is None:
            self._cache = {}
        return self

    # ------------------------------------------------------------- checkpoint
    def checkpoint(self, directory: str) -> "DistributedDataset[E]":
        """Materialize every partition to reliable storage and TRUNCATE
        lineage.

        Parity: ``RDD.checkpoint`` (``rdd/RDD.scala:1773``) +
        ``ReliableCheckpointRDD`` (``rdd/ReliableCheckpointRDD.scala:38``) --
        after this call the (possibly long) upstream closure chain is cut:
        this dataset's partitions read back from ``directory``, upstream
        compute never runs again, and the data survives process restart via
        :meth:`from_checkpoint`.  Two deliberate deltas from the reference:
        materialization is EAGER (the reference defers to the end of the
        next job -- with lazy closures there is no "next job" hook worth the
        surprise), and payload device arrays are stored as host numpy (a
        restarted process re-places them; device residency is a property of
        the worker, not the bytes).

        Layout (FsHistoryProvider-style): ``part-NNNNN.pkl`` per partition,
        ``_meta.json``, then a ``_SUCCESS`` marker written LAST -- a reader
        never trusts a directory without it (torn writes are invisible).
        """
        import json
        import os

        os.makedirs(directory, exist_ok=True)
        # invalidate any previous checkpoint FIRST: a crash mid-rewrite must
        # never leave an old _SUCCESS blessing a torn mix of old/new parts
        for marker in ("_SUCCESS", "_meta.json"):
            try:
                os.remove(os.path.join(directory, marker))
            except FileNotFoundError:
                pass

        def write_part(wid: int):
            # runs ON the partition's worker: one partition in memory at a
            # time per worker (ReliableCheckpointRDD writes per-task too),
            # not the whole dataset staged on the driver
            def task():
                payload = self._compute(wid)
                path = os.path.join(directory, f"part-{wid:05d}.pkl")
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    pickle.dump(
                        [_payload_to_host(e) for e in payload],
                        f,
                        protocol=pickle.HIGHEST_PROTOCOL,
                    )
                os.replace(tmp, path)
                return wid

            return task

        written = self._run_sync(write_part)
        meta = {"format": 1, "partitions": sorted(written)}
        with open(os.path.join(directory, "_meta.json"), "w") as f:
            json.dump(meta, f)
        with open(os.path.join(directory, "_SUCCESS"), "w") as f:
            f.write("")
        # lineage truncation: from here on, partitions come from disk
        with self._cache_lock:
            self._parts = {
                wid: _checkpoint_loader(directory, wid) for wid in written
            }
            self._cache = None  # payloads may be large; disk is the pin now
        return self

    @classmethod
    def from_checkpoint(
        cls, scheduler: JobScheduler, directory: str
    ) -> "DistributedDataset[E]":
        """Reconstruct a checkpointed dataset in a (possibly new) process."""
        import json
        import os

        if not os.path.exists(os.path.join(directory, "_SUCCESS")):
            raise FileNotFoundError(
                f"no complete checkpoint at {directory!r} (missing _SUCCESS)"
            )
        with open(os.path.join(directory, "_meta.json")) as f:
            meta = json.load(f)
        return cls(
            scheduler,
            {
                int(wid): _checkpoint_loader(directory, int(wid))
                for wid in meta["partitions"]
            },
        )

    def _run_job_dict(
        self,
        fns: Dict[int, Callable[[], Any]],
        timeout: Optional[float] = None,
    ) -> Dict[int, Any]:
        """One blocking job from an explicit task dict; per-wid results."""
        results: Dict[int, Any] = {}
        lock = threading.Lock()

        def handler(wid: int, res: Any) -> None:
            with lock:
                results[wid] = res

        mode = self.scheduler.get_mode()
        self.scheduler.set_mode(SYNC)
        try:
            self.scheduler.run_job(fns, handler, timeout=timeout)
        finally:
            self.scheduler.set_mode(mode)
        return results

    def _run_sync(
        self,
        fn_of_wid: Callable[[int], Callable[[], Any]],
        timeout: Optional[float] = None,
    ) -> Dict[int, Any]:
        """One blocking job, one task per partition; collects per-wid results."""
        return self._run_job_dict(
            {wid: fn_of_wid(wid) for wid in self.partition_ids()},
            timeout=timeout,
        )

    # --------------------------------------------------------- transformations
    def map_partitions(
        self, f: Callable[[List[E]], Iterable[U]]
    ) -> "DistributedDataset[U]":
        return DistributedDataset(
            self.scheduler,
            {
                wid: (lambda w=wid: f(self._compute(w)))
                for wid in self._parts
            },
        )

    def map(self, f: Callable[[E], U]) -> "DistributedDataset[U]":
        return self.map_partitions(lambda xs: [f(x) for x in xs])

    def flat_map(self, f: Callable[[E], Iterable[U]]) -> "DistributedDataset[U]":
        """``RDD.flatMap`` parity: one-to-many element expansion."""
        return self.map_partitions(
            lambda xs: [y for x in xs for y in f(x)]
        )

    def filter(self, pred: Callable[[E], bool]) -> "DistributedDataset[E]":
        return self.map_partitions(lambda xs: [x for x in xs if pred(x)])

    def union(self, other: "DistributedDataset[E]") -> "DistributedDataset[E]":
        """``RDD.union`` parity: partition-wise concatenation (both datasets
        are worker-pinned, so partition ``wid`` unions with partition
        ``wid``; a partition present in only one side passes through)."""
        if other.scheduler is not self.scheduler:
            raise ValueError("union requires datasets on the same scheduler")
        parts: Dict[int, Callable[[], Iterable[E]]] = {}
        for wid in sorted(set(self._parts) | set(other._parts)):
            def compute(w=wid):
                out: List[E] = []
                if w in self._parts:
                    out.extend(self._compute(w))
                if w in other._parts:
                    out.extend(other._compute(w))
                return out

            parts[wid] = compute
        return DistributedDataset(self.scheduler, parts)

    def distinct(self) -> "DistributedDataset[E]":
        """``RDD.distinct`` parity.  The reference shuffles by key so each
        value lands on one partition; worker-pinned partitions have no
        shuffle, so dedup is two-phase: per-partition local dedup in the
        tasks, then a driver-side global pass that keeps each value's first
        (lowest-partition) occurrence and re-pins survivors in place."""
        local = self._run_sync(
            lambda wid: (lambda w=wid: list(dict.fromkeys(self._compute(w))))
        )
        seen: set = set()
        payloads: Dict[int, List[E]] = {}
        for wid in sorted(local):
            keep = [x for x in local[wid] if not (x in seen or seen.add(x))]
            payloads[wid] = keep
        return DistributedDataset.from_partitions(self.scheduler, payloads)

    def sample(self, fraction: float, seed: int) -> "DistributedDataset[E]":
        """Per-partition Bernoulli sampling, deterministic in (seed, wid).

        Parity: ``RDD.sample(false, b, seed)`` backed by
        ``PartitionwiseSampledRDD`` -- independent per-partition streams from
        a shared seed.
        """
        if not 0.0 <= fraction <= 1.0:
            raise ValueError(f"fraction must be in [0, 1], got {fraction}")

        def sampler(wid: int) -> Callable[[], Iterable[E]]:
            def compute() -> Iterable[E]:
                from asyncframework_tpu.data.pairs import partition_draws

                xs = self._compute(wid)
                mask = partition_draws(seed, wid, len(xs)) < fraction
                return [x for x, m in zip(xs, mask) if m]

            return compute

        return DistributedDataset(
            self.scheduler, {wid: sampler(wid) for wid in self._parts}
        )

    def zip_with_index(self) -> "DistributedDataset[Tuple[E, int]]":
        """Global contiguous indices; runs a count job for partition offsets
        (parity: ``zipWithIndex`` launching its size-scan job,
        ``rdd/ZippedWithIndexRDD``)."""
        sizes = self._run_sync(
            lambda wid: (lambda w=wid: len(self._compute(w)))
        )
        offsets: Dict[int, int] = {}
        acc = 0
        for wid in self.partition_ids():
            offsets[wid] = acc
            acc += sizes[wid]

        def indexer(wid: int) -> Callable[[], Iterable[Tuple[E, int]]]:
            def compute() -> Iterable[Tuple[E, int]]:
                xs = self._compute(wid)
                base = offsets[wid]
                return [(x, base + j) for j, x in enumerate(xs)]

            return compute

        return DistributedDataset(
            self.scheduler, {wid: indexer(wid) for wid in self._parts}
        )

    def glom(self) -> "DistributedDataset[List[E]]":
        """``RDD.glom`` parity: each partition becomes one list element."""
        return self.map_partitions(lambda xs: [xs])

    def key_by(self, f: Callable[[E], Any]) -> "DistributedDataset":
        """``RDD.keyBy`` parity: element -> (f(element), element)."""
        return self.map(lambda x: (f(x), x))

    def coalesce(self, num_partitions: int) -> "DistributedDataset[E]":
        """``RDD.coalesce(n)`` parity (shuffle=false spirit): adjacent
        partitions concatenate into ``num_partitions`` groups, preserving
        element order; growing the partition count requires a reshuffle
        (use :meth:`partition_by` on keyed data)."""
        ids = self.partition_ids()
        if num_partitions < 1:
            raise ValueError("num_partitions must be >= 1")
        if num_partitions >= len(ids):
            return self
        groups: Dict[int, List[int]] = {i: [] for i in range(num_partitions)}
        for j, wid in enumerate(ids):
            groups[j * num_partitions // len(ids)].append(wid)

        def compute_group(members):
            def run(ms=tuple(members)):
                out: List[E] = []
                for w in ms:
                    out.extend(self._compute(w))
                return out

            return run

        return DistributedDataset(
            self.scheduler,
            {i: compute_group(m) for i, m in groups.items()},
        )

    def sort_by(
        self, key: Callable[[E], Any], ascending: bool = True,
        num_partitions: Optional[int] = None,
    ) -> "DistributedDataset[E]":
        """``RDD.sortBy`` parity, riding the pair layer's range-partitioned
        ``sort_by_key``."""
        return self.key_by(key).sort_by_key(
            ascending=ascending, num_partitions=num_partitions
        ).values()

    def count_by_value(self) -> Dict[E, int]:
        """``RDD.countByValue`` parity (driver-side dict)."""
        return self.map(lambda x: (x, 1)).count_by_key()

    def stats(self) -> "StatCounter":
        """``DoubleRDDFunctions.stats`` parity: one pass merging per-
        partition (count, mean, M2, min, max) with Chan's parallel-moments
        update -- the numerically stable merge ``StatCounter.scala`` uses."""
        def seq(acc: "StatCounter", x) -> "StatCounter":
            acc.merge_value(float(x))
            return acc

        def comb(a: "StatCounter", b: "StatCounter") -> "StatCounter":
            a.merge_stats(b)
            return a

        return self.aggregate(StatCounter(), seq, comb)

    def histogram(self, buckets):
        """``DoubleRDDFunctions.histogram`` parity.

        ``buckets`` int: ``buckets`` evenly spaced bins over [min, max],
        returns ``(bucket_edges, counts)``.  ``buckets`` sequence: custom
        edges (len B+1, ascending), returns counts only.  The last bucket
        is closed on the right (reference semantics); values outside custom
        edges are ignored.
        """
        if isinstance(buckets, int):
            if buckets < 1:
                raise ValueError("buckets must be >= 1")
            st = self.stats()
            if st.count == 0:
                raise ValueError("histogram of an empty dataset")
            lo, hi = st.min, st.max
            if not (np.isfinite(lo) and np.isfinite(hi)):
                # DoubleRDDFunctions.histogram parity: an infinite/NaN
                # range has no meaningful even buckets -- raise, never
                # fabricate a distribution
                raise ValueError(
                    f"histogram range is not finite: [{lo}, {hi}]"
                )
            edges = [
                lo + (hi - lo) * i / buckets for i in range(buckets + 1)
            ]
            # float rounding can land edges[-1] BELOW the true max (which
            # would silently drop the maximum values), and a range tiny
            # relative to |lo| can collapse interior edges entirely
            edges[-1] = hi
            if lo == hi or any(
                a >= b for a, b in zip(edges, edges[1:])
            ):
                # constant (or unresolvably narrow) data: one occupied
                # bucket with edges spaced representably at lo's magnitude
                span = max(1.0, abs(lo) * 1e-9)
                edges = [lo + span * i for i in range(buckets + 1)]
                counts = [0] * buckets
                counts[0] = int(st.count)
                return edges, counts
            return edges, self.histogram(edges)
        edges = [float(b) for b in buckets]
        if len(edges) < 2 or any(
            a >= b for a, b in zip(edges, edges[1:])
        ):
            raise ValueError("bucket edges must be ascending, len >= 2")
        nb = len(edges) - 1

        def seq(counts, x):
            x = float(x)
            if edges[0] <= x <= edges[-1]:
                # right-closed last bucket, like the reference
                i = min(bisect.bisect_right(edges, x) - 1, nb - 1)
                counts[i] += 1
            return counts

        def comb(a, b):
            return [x + y for x, y in zip(a, b)]

        return self.aggregate([0] * nb, seq, comb)

    def count_approx_distinct(self, relative_sd: float = 0.05) -> int:
        """``RDD.countApproxDistinct`` parity: per-partition HyperLogLog
        sketches merged on the driver (register-max is the shuffle-free
        combine).  ``relative_sd`` sets the register count like the
        reference maps it to HLL precision."""
        import math

        from asyncframework_tpu.utils.sketch import HyperLogLog

        p = int(math.ceil(2 * math.log2(1.04 / relative_sd)))
        if p > 18:
            raise ValueError(
                f"relative_sd={relative_sd} needs HLL precision p={p} > 18; "
                "the achievable floor is ~0.0021"
            )
        p = max(p, 4)

        def sketch(wid: int):
            def run(w=wid):
                h = HyperLogLog(p=p)
                xs = self._compute(w)
                if xs:
                    h.add(_hashable_u64(xs))
                return h

            return run

        per = self._run_sync(sketch)
        acc: Optional[object] = None
        for wid in self.partition_ids():
            acc = per[wid] if acc is None else acc.merge(per[wid])
        return int(round(acc.estimate())) if acc is not None else 0

    def take_sample(
        self, with_replacement: bool, num: int, seed: int = 42
    ) -> List[E]:
        """``RDD.takeSample`` parity: a fixed-size uniform sample collected
        to the driver."""
        if num < 0:
            raise ValueError("num must be >= 0")
        if num == 0:
            return []
        rs = np.random.default_rng(seed)
        allv = self.collect()
        if not allv:
            return []
        idx = rs.choice(len(allv), size=num, replace=with_replacement) \
            if (with_replacement or num <= len(allv)) \
            else rs.permutation(len(allv))
        return [allv[i] for i in np.atleast_1d(idx)[:num]]

    def fold(self, zero: E, op: Callable[[E, E], E]) -> E:
        """``RDD.fold`` parity: like reduce with a per-partition zero."""
        return self.aggregate(zero, op, op)

    def top(self, n: int, key: Optional[Callable[[E], Any]] = None) -> List[E]:
        """``RDD.top`` parity: n largest, descending (per-partition heads
        combined on the driver)."""
        import heapq

        k = key or (lambda x: x)
        per = self._run_sync(
            lambda wid: (
                lambda w=wid: heapq.nlargest(n, self._compute(w), key=k)
            )
        )
        allv = [x for wid in self.partition_ids() for x in per[wid]]
        return heapq.nlargest(n, allv, key=k)

    def take_ordered(
        self, n: int, key: Optional[Callable[[E], Any]] = None
    ) -> List[E]:
        """``RDD.takeOrdered`` parity: n smallest, ascending."""
        import heapq

        k = key or (lambda x: x)
        per = self._run_sync(
            lambda wid: (
                lambda w=wid: heapq.nsmallest(n, self._compute(w), key=k)
            )
        )
        allv = [x for wid in self.partition_ids() for x in per[wid]]
        return heapq.nsmallest(n, allv, key=k)

    def _lazy_elements(self) -> Callable[[], List[E]]:
        """Memoized deferred materialization of every element, computed
        DIRECTLY on the first caller's thread (not via scheduler jobs:
        transformations stay lazy like the rest of the file, and a nested
        job launched from inside a worker task could deadlock the pool)."""
        cell: Dict[str, List[E]] = {}
        lock = threading.Lock()

        def get() -> List[E]:
            with lock:
                if "v" not in cell:
                    cell["v"] = [
                        x
                        for wid in self.partition_ids()
                        for x in self._compute(wid)
                    ]
                return cell["v"]

        return get

    def subtract(self, other: "DistributedDataset[E]") -> "DistributedDataset[E]":
        """``RDD.subtract`` parity: elements of self not present in other
        (duplicates of surviving elements are preserved, like the
        reference's cogroup formulation).  Lazy: ``other`` materializes at
        first action, not at definition."""
        get_other = other._lazy_elements()
        return self.map_partitions(
            lambda xs: (lambda gone: [x for x in xs if x not in gone])(
                set(get_other())
            )
        )

    def intersection(
        self, other: "DistributedDataset[E]"
    ) -> "DistributedDataset[E]":
        """``RDD.intersection`` parity: distinct elements present in both."""
        get_other = other._lazy_elements()
        return self.distinct().map_partitions(
            lambda xs: (lambda have: [x for x in xs if x in have])(
                set(get_other())
            )
        )

    def cartesian(
        self, other: "DistributedDataset[U]"
    ) -> "DistributedDataset[Tuple[E, U]]":
        """``RDD.cartesian`` parity: partition (i) pairs with the WHOLE other
        dataset (the reference builds p*q partitions; worker-pinned
        partitions keep self's layout and broadcast other's rows)."""
        get_other = other._lazy_elements()
        return self.map_partitions(
            lambda xs: [(x, o) for x in xs for o in get_other()]
        )

    def barrier(
        self,
        ctx: AsyncContext,
        predicate: Callable[[WorkerState], bool],
    ) -> Tuple[List[int], "DistributedDataset[E]"]:
        """Partial barrier: select the cohort, empty out the rest.

        Parity: ``RDD.ASYNCbarrier`` -- non-selected partitions yield
        ``Iterator.empty`` (``rdd/RDD.scala:1066-1073``); the cohort is
        returned instead of written to the global ``RDD.WorkerList``.
        """
        cohort = partial_barrier(ctx, self.partition_ids(), predicate)
        in_cohort = set(cohort)

        def gate(wid: int) -> Callable[[], Iterable[E]]:
            def compute() -> Iterable[E]:
                return self._compute(wid) if wid in in_cohort else []

            return compute

        return cohort, DistributedDataset(
            self.scheduler, {wid: gate(wid) for wid in self._parts}
        )

    # ---------------------------------------------------------------- actions
    def collect(self) -> List[E]:
        per = self._run_sync(lambda wid: (lambda w=wid: self._compute(w)))
        out: List[E] = []
        for wid in self.partition_ids():
            out.extend(per[wid])
        return out

    def take(self, n: int) -> List[E]:
        """First ``n`` elements in partition order.

        ``RDD.take``-style incremental scan, collapsed to two rounds: probe
        the first partition alone (the common small-n case touches nothing
        else), then -- only if short -- compute every remaining partition in
        ONE parallel job instead of a sequential per-partition walk.
        """
        if n <= 0:
            return []
        ids = self.partition_ids()
        if not ids:
            return []
        first = self._run_job_dict(
            {ids[0]: (lambda w=ids[0]: self._compute(w))}
        )[ids[0]]
        out: List[E] = list(first[:n])
        if len(out) >= n or len(ids) == 1:
            return out
        rest = self._run_job_dict(
            {wid: (lambda w=wid: self._compute(w)) for wid in ids[1:]}
        )
        for wid in ids[1:]:
            out.extend(rest[wid][: n - len(out)])
            if len(out) >= n:
                break
        return out

    def first(self) -> E:
        got = self.take(1)
        if not got:
            raise ValueError("first() on an empty dataset")
        return got[0]

    def count(self) -> int:
        per = self._run_sync(lambda wid: (lambda w=wid: len(self._compute(w))))
        return sum(per.values())

    def reduce(self, op: Callable[[E, E], E]) -> E:
        """Local per-partition reduce, then driver-side merge in partition
        order (the reference's driver-mediated collective)."""
        per = self._run_sync(
            lambda wid: (lambda w=wid: _local_reduce(self._compute(w), op))
        )
        acc: Optional[E] = None
        for wid in self.partition_ids():
            got, nonempty = per[wid]
            if not nonempty:
                continue
            acc = got if acc is None else op(acc, got)
        if acc is None:
            raise ValueError("reduce on an empty dataset")
        return acc

    def aggregate(
        self,
        zero: U,
        seq_op: Callable[[U, E], U],
        comb_op: Callable[[U, U], U],
    ) -> U:
        import copy

        per = self._run_sync(
            lambda wid: (
                lambda w=wid: _local_aggregate(self._compute(w), zero, seq_op)
            )
        )
        acc = copy.deepcopy(zero)  # never mutate the caller's zero
        for wid in self.partition_ids():
            acc = comb_op(acc, per[wid])
        return acc

    def tree_aggregate(
        self,
        zero: U,
        seq_op: Callable[[U, E], U],
        comb_op: Callable[[U, U], U],
        depth: int = 2,
    ) -> U:
        """Multi-round combine (``treeAggregate`` parity).

        The reference inserts shuffle stages to halve the fan-in per round;
        here rounds are extra (tiny) jobs pair-combining partials on workers,
        keeping the driver's final fan-in bounded.  The TPU-native analog for
        device arrays is an XLA ``psum`` (``ops/collectives.py``) -- this is
        the host-payload path.
        """
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        per = self._run_sync(
            lambda wid: (
                lambda w=wid: _local_aggregate(self._compute(w), zero, seq_op)
            )
        )
        partials = [per[wid] for wid in self.partition_ids()]
        for _ in range(depth - 1):
            if len(partials) <= 2:
                break
            pairs = [
                (partials[i], partials[i + 1])
                for i in range(0, len(partials) - 1, 2)
            ]
            tail = [partials[-1]] if len(partials) % 2 else []
            combined = self._run_job_dict(
                {
                    i: (lambda p=pair: comb_op(p[0], p[1]))
                    for i, pair in enumerate(pairs)
                }
            )
            partials = [combined[i] for i in range(len(pairs))] + tail
        import copy

        acc = copy.deepcopy(zero)  # never mutate the caller's zero
        for p in partials:
            acc = comb_op(acc, p)
        return acc

    # ------------------------------------------------------------- async delta
    def async_reduce(
        self,
        op: Callable[[E, E], E],
        ctx: AsyncContext,
        cohort: Optional[List[int]] = None,
    ) -> Optional[JobWaiter]:
        """Non-blocking reduce streaming per-partition results into ``ctx``.

        Parity: ``RDD.ASYNCreduce`` (``rdd/RDD.scala:1087-1171``) -- stamp the
        submit clock, mark the cohort busy, submit without blocking; each
        finishing partition merges via ``ctx.merge_result`` (staleness =
        clock_now - submit_clock, clock += 1).  Empty cohort skips the run
        (``rdd/RDD.scala:1095-1097`` returns without submitting).
        """
        return self._async_action(lambda xs: _local_reduce(xs, op), ctx, cohort)

    def async_aggregate(
        self,
        zero: U,
        seq_op: Callable[[U, E], U],
        comb_op: Callable[[U, U], U],
        ctx: AsyncContext,
        cohort: Optional[List[int]] = None,
    ) -> Optional[JobWaiter]:
        """Non-blocking aggregate (``RDD.ASYNCaggregate`` parity); ``comb_op``
        is applied driver-side by the consumer of ``ctx`` (the updater)."""
        del comb_op  # driver-side merge belongs to the updater thread
        return self._async_action(
            lambda xs: (_local_aggregate(xs, zero, seq_op), True), ctx, cohort
        )

    def _async_action(
        self,
        local: Callable[[List[E]], Any],
        ctx: AsyncContext,
        cohort: Optional[List[int]],
    ) -> Optional[JobWaiter]:
        wids = self.partition_ids() if cohort is None else list(cohort)
        if not wids:
            return None  # empty-cohort skip
        submit_clock = ctx.get_current_time()
        ctx.set_last_time(submit_clock)
        ctx.mark_busy(wids)
        clock = self.scheduler.clock

        def make_task(wid: int) -> Callable[[], Any]:
            def run() -> Any:
                t0 = clock.now_ms()
                xs = self._compute(wid)
                out = local(xs)
                return out, len(xs), clock.now_ms() - t0

            return run

        def handler(wid: int, payload: Any) -> None:
            out, n, elapsed_ms = payload
            value, nonempty = out
            if not nonempty:
                ctx.mark_available(wid)  # empty partition: freed, no merge
                return
            ctx.merge_result(
                wid, value, submit_clock, elapsed_ms, batch_size=n
            )

        mode = self.scheduler.get_mode()
        self.scheduler.set_mode(ASYNC)
        try:
            waiter = self.scheduler.run_job(
                {wid: make_task(wid) for wid in wids}, handler
            )
        except BaseException:
            # the scheduler's first job blocks (warm-up) and re-raises task
            # failures synchronously -- release the cohort before propagating
            for w in wids:
                ctx.mark_available(w)
            raise
        finally:
            self.scheduler.set_mode(mode)
        # If the job aborts (a task exhausted retries), release the whole
        # cohort so the driver loop does not deadlock on availability; the
        # caller observes the error via ``waiter.failed``.  Workers that
        # already merged are available anyway (mark_available is idempotent).
        waiter.on_failure(
            lambda _exc: [ctx.mark_available(w) for w in wids]
        )
        return waiter


def _payload_to_host(e):
    """Recursively convert device arrays to host numpy for pickling
    (tuples/lists/dicts of arrays are common payload shapes).  Tuple
    subclasses (namedtuples) are rebuilt with their own type so a
    checkpoint round trip preserves element types."""
    import jax

    if isinstance(e, jax.Array):
        return np.asarray(e)
    if isinstance(e, tuple):
        converted = [_payload_to_host(x) for x in e]
        if type(e) is tuple:
            return tuple(converted)
        return type(e)(*converted)  # namedtuple and friends
    if isinstance(e, list):
        return [_payload_to_host(x) for x in e]
    if isinstance(e, dict):
        return {k: _payload_to_host(v) for k, v in e.items()}
    return e


def _checkpoint_loader(directory: str, wid: int):
    """Partition-reader closure; runs on the partition's worker thread."""
    import os

    path = os.path.join(directory, f"part-{wid:05d}.pkl")

    def load():
        with open(path, "rb") as f:
            return pickle.load(f)

    return load


def _hashable_u64(xs: List) -> np.ndarray:
    """Elements -> uint64 for sketching: numeric sequences take the
    vectorized path; everything else (tuples, strings, mixed) hashes per
    element through the stable portable hash (tuple support is what makes
    countApproxDistinct work on pair datasets)."""
    try:
        a = np.asarray(xs)
    except ValueError:  # ragged
        a = None
    if a is not None and a.ndim == 1 and a.dtype.kind in "iuf":
        return a
    from asyncframework_tpu.data.pairs import portable_hash

    return np.asarray(
        [portable_hash(x) & 0xFFFFFFFFFFFFFFFF for x in xs], np.uint64
    )


def _local_reduce(xs: List[E], op: Callable[[E, E], E]) -> Tuple[Any, bool]:
    """(value, nonempty): the reference's ``reducePartition`` returns an
    Option; empty partitions contribute nothing."""
    it = iter(xs)
    try:
        acc = next(it)
    except StopIteration:
        return None, False
    for x in it:
        acc = op(acc, x)
    return acc, True


def _local_aggregate(
    xs: List[E], zero: U, seq_op: Callable[[U, E], U]
) -> U:
    import copy

    acc = copy.deepcopy(zero)
    for x in xs:
        acc = seq_op(acc, x)
    return acc


class StatCounter:
    """Running (count, mean, variance, min, max) with a numerically stable
    merge (``org.apache.spark.util.StatCounter`` parity: Chan et al.'s
    parallel-moments update, the same algebra ``stats()`` relies on)."""

    def __init__(self):
        self.count = 0
        self.mean = 0.0
        self._m2 = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def merge_value(self, x: float) -> "StatCounter":
        delta = x - self.mean
        self.count += 1
        self.mean += delta / self.count
        self._m2 += delta * (x - self.mean)
        if x != x:
            # NaN poisons min/max like the moments (StatCounter parity:
            # Java's Math.min propagates NaN; Python's min() would
            # silently skip it and report an inconsistent clean range)
            self.min = float("nan")
            self.max = float("nan")
        else:
            self.min = min(self.min, x)
            self.max = max(self.max, x)
        return self

    def merge_stats(self, other: "StatCounter") -> "StatCounter":
        if other.count == 0:
            return self
        if self.count == 0:
            self.count = other.count
            self.mean = other.mean
            self._m2 = other._m2
            self.min = other.min
            self.max = other.max
            return self
        delta = other.mean - self.mean
        total = self.count + other.count
        self.mean += delta * other.count / total
        self._m2 += other._m2 + delta * delta * self.count * other.count / total
        self.count = total
        if other.min != other.min or self.min != self.min:
            self.min = float("nan")
            self.max = float("nan")
        else:
            self.min = min(self.min, other.min)
            self.max = max(self.max, other.max)
        return self

    @property
    def variance(self) -> float:
        """Population variance (StatCounter.variance semantics)."""
        return self._m2 / self.count if self.count else float("nan")

    @property
    def sample_variance(self) -> float:
        return (
            self._m2 / (self.count - 1)
            if self.count > 1
            else float("nan")
        )

    @property
    def stdev(self) -> float:
        return self.variance ** 0.5

    @property
    def sum(self) -> float:
        return self.mean * self.count

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"StatCounter(count={self.count}, mean={self.mean:.6g}, "
            f"stdev={self.stdev:.6g}, min={self.min:.6g}, max={self.max:.6g})"
        )
