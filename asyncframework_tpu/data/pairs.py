"""Pair (key, value) dataset operations: the other half of the RDD API.

Parity (studied, not copied): ``core/src/main/scala/org/apache/spark/rdd/
PairRDDFunctions.scala`` -- ``combineByKey`` (the base primitive),
``reduceByKey`` (~line 300), ``foldByKey``, ``groupByKey``, ``countByKey``,
``join``/``leftOuterJoin``/``rightOuterJoin``/``fullOuterJoin``, ``cogroup``,
``partitionBy``, ``keys``/``values``/``mapValues``/``flatMapValues``, plus
``OrderedRDDFunctions.sortByKey`` (range partitioner + per-partition sort).

TPU-first design: the reference shuffles through sorted spill files fetched
over the network because its partitions live in different JVMs.  Here
partitions are worker-pinned host/device payloads inside ONE process, and the
driver is already the reduction point for every collective (SURVEY.md
section 2.3: Spark's collectives are driver-mediated -- that is *why* ASYNC
exists).  The shuffle therefore decomposes into:

1. **map-side combine on workers** (a parallel job; the analog of Spark's
   map-side ``Aggregator``),
2. **driver routing** of the (already combined, so small) per-key entries to
   their hash/range target partition (the analog of the shuffle fetch, minus
   the network), and
3. **reduce-side merge on workers** (a second parallel job producing the
   output partitions).

Keys are hashed with a *portable* hash (Python's builtin is salted per
process, which would break any persisted partitioning), matching the spirit
of the reference's ``Partitioner.defaultPartitioner`` + Java hashCode.
"""

from __future__ import annotations

from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Optional,
    Tuple,
    TypeVar,
)

from asyncframework_tpu.storage.kvstore import string_hash_code

K = TypeVar("K")
V = TypeVar("V")
W = TypeVar("W")
C = TypeVar("C")


def partition_draws(seed: int, wid: int, n: int):
    """The shared per-partition uniform-draw recipe: deterministic in
    (seed, partition id) -- ``PartitionwiseSampledRDD`` parity.  Both
    ``DistributedDataset.sample`` and ``sample_by_key`` derive their
    Bernoulli draws from here so their seeding stays in lockstep."""
    import numpy as _np

    rs = _np.random.default_rng(
        _np.random.SeedSequence(entropy=seed, spawn_key=(wid,))
    )
    return rs.random(n)


def _append(c: list, v) -> list:
    c.append(v)
    return c


def _extend(a: list, b: list) -> list:
    a.extend(b)
    return a


def portable_hash(key: Any) -> int:
    """Process-stable hash (Python's ``hash`` is salted for str/bytes)."""
    if key is None:
        return 0
    if isinstance(key, bool):
        return int(key)
    if isinstance(key, int):
        return key
    if isinstance(key, str):
        return string_hash_code(key)
    if isinstance(key, bytes):
        return string_hash_code(key.decode("utf-8", "surrogateescape"))
    if isinstance(key, float):
        return hash(key)  # floats are not salted
    if isinstance(key, tuple):
        h = 0x345678
        for item in key:
            h = (h * 1000003) ^ portable_hash(item)
        return h
    raise TypeError(
        f"unhashable/unstable key type for partitioning: {type(key).__name__}"
    )


def hash_partition(key: Any, num_partitions: int) -> int:
    return portable_hash(key) % num_partitions


class PairOpsMixin:
    """Pair-op surface mixed into ``DistributedDataset``.

    Elements are assumed to be ``(key, value)`` tuples, like an
    ``RDD[(K, V)]`` picking up ``PairRDDFunctions`` implicitly.
    """

    # ------------------------------------------------------- simple projections
    def keys(self):
        return self.map(lambda kv: kv[0])

    def values(self):
        return self.map(lambda kv: kv[1])

    def map_values(self, f: Callable[[V], W]):
        """``mapValues`` parity: preserves partitioning (no shuffle)."""
        return self.map(lambda kv: (kv[0], f(kv[1])))

    def flat_map_values(self, f: Callable[[V], Iterable[W]]):
        return self.flat_map(lambda kv: [(kv[0], w) for w in f(kv[1])])

    # ---------------------------------------------------------------- shuffles
    def _resolve_p(self, num_partitions: Optional[int]) -> int:
        p = num_partitions or max(len(self._parts), 1)
        if p > self.scheduler.num_workers:
            raise ValueError(
                f"num_partitions={p} exceeds num_workers="
                f"{self.scheduler.num_workers}; partitions are worker-pinned"
            )
        return p

    def partition_by(
        self,
        num_partitions: Optional[int] = None,
        partition_func: Callable[[Any, int], int] = hash_partition,
    ):
        """``partitionBy`` parity: route each pair to its key's partition.

        The ROUTING buffer is memory-bounded (past
        ``async.shuffle.spill.bytes`` it spills to disk runs,
        data/spill.py), which halves peak residency during the route: the
        input lists and the full routed copy never coexist.  The OUTPUT
        partitions are in-memory payloads -- like every dataset in this
        architecture -- so partitioning N pairs still ends with N pairs
        resident; ops that shrink (combine_by_key) or stream per-partition
        (sort) get the full benefit of the bound."""
        from asyncframework_tpu.data.spill import (
            SpillingRouter,
            configured_spill_bytes,
        )

        p = self._resolve_p(num_partitions)
        per = self._run_sync(lambda wid: (lambda w=wid: self._compute(w)))
        with SpillingRouter(p, configured_spill_bytes(),
                            label="partition_by") as router:
            for wid in sorted(per):
                for kv in per[wid]:
                    router.add(partition_func(kv[0], p), kv)
            routed = {i: router.partition_list(i) for i in range(p)}
        return type(self).from_partitions(self.scheduler, routed)

    def combine_by_key(
        self,
        create_combiner: Callable[[V], C],
        merge_value: Callable[[C, V], C],
        merge_combiners: Callable[[C, C], C],
        num_partitions: Optional[int] = None,
    ):
        """``combineByKey`` parity -- the base of every by-key aggregation.

        Map-side combine runs on workers, the driver routes the (small)
        per-key combiners, reduce-side merge runs on workers again.
        """
        p = self._resolve_p(num_partitions)

        def local_combine(wid: int):
            def run(w=wid):
                acc: Dict[Any, Any] = {}
                for k, v in self._compute(w):
                    if k in acc:
                        acc[k] = merge_value(acc[k], v)
                    else:
                        acc[k] = create_combiner(v)
                return list(acc.items())

            return run

        from asyncframework_tpu.data.spill import (
            SpillingRouter,
            configured_spill_bytes,
        )

        combined = self._run_sync(local_combine)
        router = SpillingRouter(p, configured_spill_bytes(),
                                label="combine_by_key")
        for wid in sorted(combined):
            for k, c in combined[wid]:
                router.add(hash_partition(k, p), (k, c))

        def reduce_side(pid: int):
            def run(r=router, i=pid):
                # reduce-side merge streams this partition's entries out of
                # the spill runs + memory tail -- never the whole shuffle
                acc: Dict[Any, Any] = {}
                for k, c in r.partition(i):
                    acc[k] = merge_combiners(acc[k], c) if k in acc else c
                return list(acc.items())

            return run

        try:
            merged = self._run_job_dict(
                {pid: reduce_side(pid) for pid in range(p)}
            )
        finally:
            router.close()
        return type(self).from_partitions(
            self.scheduler, {pid: merged[pid] for pid in range(p)}
        )

    def reduce_by_key(
        self, op, num_partitions: Optional[int] = None,
        distinct_hint: Optional[int] = None,
    ):
        """``reduceByKey`` parity (map-side combine included, like the
        reference's default).

        ``op`` may be a callable (host path: arbitrary Python keys/values,
        driver-routed) or one of ``'sum'|'max'|'min'`` with array-typed
        partitions (``from_array_pairs``), which takes the ARRAY data
        plane.  The route is the measured winner per backend
        (``async.shuffle.data.plane``, default ``auto``):

        - accelerator backends -> the DEVICE shuffle: hash partitioning,
          one ``lax.all_to_all`` exchange, jitted segment reduces
          (ops/shuffle.py -- the SortShuffleManager-role data plane);
        - CPU backend -> the vectorized HOST shuffle (numpy
          bincount/sort+reduceat).  Rig measurements (ROUND5.md): on 10M
          pairs the host-vectorized path is ~10x the driver-routed dict
          path, while the device path's collective is EMULATED on CPU and
          loses to both -- so ``auto`` only takes the device route when a
          real accelerator backs it.
        """
        if isinstance(op, str):
            from asyncframework_tpu.conf import (
                SHUFFLE_DATA_PLANE,
                global_conf,
            )

            plane = str(global_conf().get(SHUFFLE_DATA_PLANE))
            if plane not in ("auto", "host", "device"):
                raise ValueError(
                    f"async.shuffle.data.plane={plane!r}: must be "
                    "'auto', 'host', or 'device'"
                )
            if plane == "auto":
                import jax

                plane = ("host" if jax.default_backend() == "cpu"
                         else "device")
            if plane == "host":
                return self._reduce_by_key_arrays("host", op)
            return self._reduce_by_key_arrays("device", op, distinct_hint)
        return self.combine_by_key(lambda v: v, op, op, num_partitions)

    def _reduce_by_key_arrays(self, plane: str, op: str, distinct_hint=None):
        from asyncframework_tpu.ops import shuffle as _shuffle

        blocks = self._run_sync(lambda wid: (lambda w=wid: self._compute(w)))
        parts = {}
        for wid, payload in blocks.items():
            payload = list(payload)
            kv = payload[0] if len(payload) == 1 else None
            if not (
                isinstance(kv, tuple) and len(kv) == 2
                and hasattr(kv[0], "shape") and hasattr(kv[1], "shape")
            ):
                raise ValueError(
                    "device reduce_by_key needs array-pair partitions "
                    "(build with from_array_pairs); got a generic payload -- "
                    "pass a callable op for the host path"
                )
            parts[wid] = kv
        if plane == "host":
            out = _shuffle.host_reduce_by_key(parts, op=op)
        else:
            out = _shuffle.device_reduce_by_key(
                parts, op=op, distinct_hint=distinct_hint
            )
        return type(self).from_partitions(
            self.scheduler, {pid: [kv] for pid, kv in out.items()}
        )

    def fold_by_key(
        self,
        zero: V,
        op: Callable[[V, V], V],
        num_partitions: Optional[int] = None,
    ):
        import copy

        return self.combine_by_key(
            lambda v: op(copy.deepcopy(zero), v), op, op, num_partitions
        )

    def group_by_key(self, num_partitions: Optional[int] = None):
        """``groupByKey`` parity: values are collected into lists (the
        reference documents the same no-map-side-combine memory caveat)."""
        return self.combine_by_key(
            lambda v: [v],
            _append,  # in-place: `c + [v]` would be O(m^2) per skewed key
            _extend,
            num_partitions,
        )

    def sample_by_key(self, fractions: Dict[Any, float], seed: int = 42):
        """``sampleByKey`` parity: per-key Bernoulli fractions, deterministic
        in (seed, partition) like :meth:`DistributedDataset.sample`; keys
        absent from ``fractions`` are dropped."""
        for k, f in fractions.items():
            if not 0.0 <= f <= 1.0:
                raise ValueError(f"fraction for key {k!r} must be in [0, 1]")

        def sampler(wid: int):
            def run(w=wid):
                xs = self._compute(w)
                draws = partition_draws(seed, w, len(xs))
                return [
                    kv for kv, u in zip(xs, draws)
                    if u < fractions.get(kv[0], 0.0)
                ]

            return run

        return type(self)(
            self.scheduler, {wid: sampler(wid) for wid in self._parts}
        )

    def count_by_key(self) -> Dict[Any, int]:
        """``countByKey`` action: driver-side dict of counts."""
        counts = self.map_values(lambda _v: 1).reduce_by_key(lambda a, b: a + b)
        return dict(counts.collect())

    # ------------------------------------------------------------------- joins
    def cogroup(self, other, num_partitions: Optional[int] = None):
        """``cogroup`` parity: (k, ([vs], [ws])) with both sides grouped."""
        p = self._resolve_p(num_partitions)
        left = self.group_by_key(p)
        right = other.group_by_key(p)
        lper = left._run_sync(lambda wid: (lambda w=wid: left._compute(w)))
        rper = right._run_sync(lambda wid: (lambda w=wid: right._compute(w)))

        def merge_partition(pid: int):
            def run(ls=lper.get(pid, []), rs=rper.get(pid, [])):
                acc: Dict[Any, Tuple[list, list]] = {}
                for k, vs in ls:
                    acc.setdefault(k, ([], []))[0].extend(vs)
                for k, ws in rs:
                    acc.setdefault(k, ([], []))[1].extend(ws)
                return list(acc.items())

            return run

        merged = self._run_job_dict(
            {pid: merge_partition(pid) for pid in range(p)}
        )
        return type(self).from_partitions(
            self.scheduler, {pid: merged[pid] for pid in range(p)}
        )

    def _join_with(self, other, num_partitions, keep_left, keep_right):
        co = self.cogroup(other, num_partitions)

        def expand(kv):
            k, (vs, ws) = kv
            if vs and ws:
                return [(k, (v, w)) for v in vs for w in ws]
            if vs and not ws and keep_left:
                return [(k, (v, None)) for v in vs]
            if ws and not vs and keep_right:
                return [(k, (None, w)) for w in ws]
            return []

        return co.flat_map(expand)

    def join(self, other, num_partitions: Optional[int] = None):
        """Inner ``join`` parity: (k, (v, w)) for every matching pair."""
        return self._join_with(other, num_partitions, False, False)

    def left_outer_join(self, other, num_partitions: Optional[int] = None):
        return self._join_with(other, num_partitions, True, False)

    def right_outer_join(self, other, num_partitions: Optional[int] = None):
        return self._join_with(other, num_partitions, False, True)

    def full_outer_join(self, other, num_partitions: Optional[int] = None):
        return self._join_with(other, num_partitions, True, True)

    # ----------------------------------------------------------------- sorting
    def sort_by_key(
        self,
        ascending: bool = True,
        num_partitions: Optional[int] = None,
    ):
        """``sortByKey`` parity: range-partition by sampled bounds, then sort
        within partitions -- partition order IS global order, like the
        reference's ``RangePartitioner`` + per-partition sort."""
        p = self._resolve_p(num_partitions)
        per = self._run_sync(lambda wid: (lambda w=wid: self._compute(w)))
        all_pairs = [kv for wid in sorted(per) for kv in per[wid]]
        if not all_pairs:
            return type(self).from_partitions(
                self.scheduler, {i: [] for i in range(p)}
            )
        keys = sorted(kv[0] for kv in all_pairs)
        # p-1 range bounds from evenly spaced order statistics
        bounds = [
            keys[(i + 1) * len(keys) // p] for i in range(p - 1)
        ]

        def target(k) -> int:
            import bisect

            t = bisect.bisect_right(bounds, k)
            return t if ascending else p - 1 - t

        from asyncframework_tpu.data.spill import (
            SpillingRouter,
            configured_spill_bytes,
        )

        router = SpillingRouter(p, configured_spill_bytes(),
                                label="sort_by_key")
        for kv in all_pairs:
            router.add(target(kv[0]), kv)

        def sort_partition(pid: int):
            def run(r=router, i=pid):
                return sorted(
                    r.partition(i), key=lambda kv: kv[0],
                    reverse=not ascending
                )

            return run

        try:
            merged = self._run_job_dict(
                {pid: sort_partition(pid) for pid in range(p)}
            )
        finally:
            router.close()
        return type(self).from_partitions(
            self.scheduler, {pid: merged[pid] for pid in range(p)}
        )
