"""Random dataset generators (RandomRDDs parity).

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/random/
RandomRDDs.scala`` -- uniform/normal/poisson/exponential/gamma/log-normal
scalar and vector generators partitioned across the cluster.

Design: one ``jax.random`` draw per generator (a single counter-based PRNG
key replaces the reference's RDD of per-partition seeds -- same
independence guarantee, no seed bookkeeping), then the host values are
partitioned into a :class:`DistributedDataset` so the full dataset op
surface (map/filter/reduce/pair ops) applies.  The engine-dataset layer is
host-resident by design (see ``data/dataset.py``); device-resident sharded
generation lives in ``ShardedDataset.generate_on_device``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from asyncframework_tpu.data.dataset import DistributedDataset


def _draw(sampler, scheduler, n: int, d: Optional[int], seed: int,
          num_partitions: Optional[int]):
    key = jax.random.PRNGKey(seed)
    shape = (n,) if d is None else (n, d)
    values = np.asarray(sampler(key, shape))
    data = [float(v) for v in values] if d is None else list(values)
    return DistributedDataset.from_list(
        scheduler, data, num_partitions=num_partitions
    )


def uniform_dataset(scheduler, n, num_partitions=None, seed=0):
    """U[0, 1) scalars (``RandomRDDs.uniformRDD``)."""
    return _draw(
        lambda k, s: jax.random.uniform(k, s, jnp.float32),
        scheduler, n, None, seed, num_partitions,
    )


def normal_dataset(scheduler, n, num_partitions=None, seed=0):
    """Standard normal scalars (``RandomRDDs.normalRDD``)."""
    return _draw(
        lambda k, s: jax.random.normal(k, s, jnp.float32),
        scheduler, n, None, seed, num_partitions,
    )


def poisson_dataset(scheduler, n, mean, num_partitions=None, seed=0):
    """Poisson(mean) scalars (``RandomRDDs.poissonRDD``)."""
    return _draw(
        lambda k, s: jax.random.poisson(k, mean, s).astype(jnp.float32),
        scheduler, n, None, seed, num_partitions,
    )


def exponential_dataset(scheduler, n, mean, num_partitions=None, seed=0):
    """Exponential(mean) scalars (``RandomRDDs.exponentialRDD``)."""
    return _draw(
        lambda k, s: jax.random.exponential(k, s) * mean,
        scheduler, n, None, seed, num_partitions,
    )


def gamma_dataset(scheduler, n, shape, scale, num_partitions=None, seed=0):
    """Gamma(shape, scale) scalars (``RandomRDDs.gammaRDD``)."""
    return _draw(
        lambda k, s: jax.random.gamma(k, shape, s) * scale,
        scheduler, n, None, seed, num_partitions,
    )


def log_normal_dataset(scheduler, n, mean, std, num_partitions=None, seed=0):
    """Log-normal scalars (``RandomRDDs.logNormalRDD``)."""
    return _draw(
        lambda k, s: jnp.exp(mean + std * jax.random.normal(k, s)),
        scheduler, n, None, seed, num_partitions,
    )


def uniform_vector_dataset(scheduler, n, d, num_partitions=None, seed=0):
    """U[0, 1) row vectors (``RandomRDDs.uniformVectorRDD``)."""
    return _draw(
        lambda k, s: jax.random.uniform(k, s, jnp.float32),
        scheduler, n, d, seed, num_partitions,
    )


def normal_vector_dataset(scheduler, n, d, num_partitions=None, seed=0):
    """Standard normal row vectors (``RandomRDDs.normalVectorRDD``)."""
    return _draw(
        lambda k, s: jax.random.normal(k, s, jnp.float32),
        scheduler, n, d, seed, num_partitions,
    )
