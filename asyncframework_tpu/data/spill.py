"""Bounded-memory shuffle routing with disk spill.

Parity (studied, not copied): ``core/.../shuffle/sort/SortShuffleManager.
scala:69`` spills sorted runs to disk when the shuffle's execution-memory
grant is exhausted, and ``memory/UnifiedMemoryManager.scala:47`` accounts
the bytes.  The TPU build's host shuffle (data/pairs.py) routes per-key
entries through the driver; before this module it held every routed group
in Python dicts with no bound -- a 10^8-pair shuffle OOMed the driver
silently.

Design: a :class:`SpillingRouter` buffers routed entries per target
partition, estimates their host bytes incrementally, and when the
configured bound (``async.shuffle.spill.bytes``) is exceeded writes the
whole buffer as one pickled RUN file and clears it.  Reading a partition
replays its slice of every run in write order, then the in-memory tail --
insertion order is preserved exactly as the unbounded dict preserved it,
so results are bit-identical with or without spilling.  Cumulative
counters (records, spills, bytes) feed the live UI's shuffle panel and
``DistributedDataset``-level assertions in tests.
"""

from __future__ import annotations

import os
import pickle
import sys
import tempfile
import threading
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: process-wide cumulative counters (UnifiedMemoryManager's accounting
#: role, trimmed to observability); read by metrics/live.py
_TOTALS_LOCK = threading.Lock()
_TOTALS = {
    "shuffles": 0,
    "records_routed": 0,
    "spill_count": 0,
    "bytes_spilled": 0,
    "bytes_in_memory_peak": 0,
}


def shuffle_totals() -> Dict[str, int]:
    with _TOTALS_LOCK:
        return dict(_TOTALS)


def reset_shuffle_totals() -> None:
    """Zero the process-wide counters (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    with _TOTALS_LOCK:
        for k in _TOTALS:
            _TOTALS[k] = 0


_reset_totals = reset_shuffle_totals  # historical test-suite alias


def _estimate_bytes(kv: Tuple[Any, Any]) -> int:
    """Cheap per-entry host-memory estimate: shallow sizes + container
    overhead.  Deliberately approximate -- the bound is a safety rail, not
    an allocator."""
    k, v = kv
    est = 64 + sys.getsizeof(k)
    est += v.nbytes if hasattr(v, "nbytes") else sys.getsizeof(v)
    return est


class SpillingRouter:
    """Driver-side routing buffer with a memory bound and disk runs.

    ``memory_bytes <= 0`` disables spilling (the pre-existing unbounded
    behavior).  Spill files live in a private temp dir and are removed by
    :meth:`close` (or interpreter exit via the tempdir finalizer).
    """

    def __init__(self, num_partitions: int, memory_bytes: int,
                 label: str = "shuffle"):
        self.p = num_partitions
        self.bound = int(memory_bytes)
        self.label = label
        self._buf: Dict[int, List[Tuple[Any, Any]]] = {
            i: [] for i in range(num_partitions)
        }
        self._est = 0
        self._est_peak = 0
        # each run = per-partition pickled segments + an offset index, so a
        # partition read seeks straight to its slice (a whole-dict pickle
        # would cost O(p x spilled bytes) deserialization across readers)
        self._runs: List[Tuple[str, Dict[int, Tuple[int, int]]]] = []
        self._tmp: Optional[tempfile.TemporaryDirectory] = None
        self.records = 0
        self.spill_count = 0
        self.bytes_spilled = 0
        with _TOTALS_LOCK:
            _TOTALS["shuffles"] += 1

    # ------------------------------------------------------------- writing
    def add(self, pid: int, kv: Tuple[Any, Any]) -> None:
        self._buf[pid].append(kv)
        self.records += 1
        self._est += _estimate_bytes(kv)
        if self._est > self._est_peak:
            self._est_peak = self._est
        if self.bound > 0 and self._est >= self.bound:
            self._spill()

    def _spill(self) -> None:
        if self._tmp is None:
            self._tmp = tempfile.TemporaryDirectory(
                prefix=f"asynctpu-{self.label}-"
            )
        path = os.path.join(
            self._tmp.name, f"run-{len(self._runs):04d}.pkl"
        )
        index: Dict[int, Tuple[int, int]] = {}
        off = 0
        with open(path, "wb") as f:
            for pid in range(self.p):
                if not self._buf[pid]:
                    continue
                blob = pickle.dumps(
                    self._buf[pid], protocol=pickle.HIGHEST_PROTOCOL
                )
                f.write(blob)
                index[pid] = (off, len(blob))
                off += len(blob)
        self._runs.append((path, index))
        self.spill_count += 1
        self.bytes_spilled += off
        self._buf = {i: [] for i in range(self.p)}
        self._est = 0

    # ------------------------------------------------------------- reading
    def partition(self, pid: int) -> Iterator[Tuple[Any, Any]]:
        """Entries routed to ``pid`` in original insertion order (runs in
        write order, then the in-memory tail).  Reads only this
        partition's segments -- seek + bounded read per run."""
        for path, index in self._runs:
            seg = index.get(pid)
            if seg is None:
                continue
            off, length = seg
            with open(path, "rb") as f:
                f.seek(off)
                yield from pickle.loads(f.read(length))
        yield from self._buf[pid]

    def partition_list(self, pid: int) -> List[Tuple[Any, Any]]:
        return list(self.partition(pid))

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        with _TOTALS_LOCK:
            _TOTALS["records_routed"] += self.records
            _TOTALS["spill_count"] += self.spill_count
            _TOTALS["bytes_spilled"] += self.bytes_spilled
            _TOTALS["bytes_in_memory_peak"] = max(
                _TOTALS["bytes_in_memory_peak"], self._est_peak
            )
        if self._tmp is not None:
            self._tmp.cleanup()
            self._tmp = None
        self._runs = []

    def __enter__(self) -> "SpillingRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def configured_spill_bytes() -> int:
    """The process-global shuffle memory bound (0 = unbounded)."""
    from asyncframework_tpu.conf import SHUFFLE_SPILL_BYTES, global_conf

    return int(global_conf().get(SHUFFLE_SPILL_BYTES))
