"""Device-resident sharded dataset.

Parity: the reference's per-experiment data plumbing --
``loadLibSVMFile(...).repartition(numPart)`` + ``zipWithIndex().cache()``
(``SparkASGDThread.scala:74-93``) and the per-partition global-index offsets
``partitionCumList`` (``SparkASAGAThread.scala:79-87``).

TPU mapping: rows are split into ``num_workers`` contiguous shards.  Each
worker's shard is placed once into its device's HBM (the ``cache()``); global
row index of local row ``j`` in shard ``p`` is ``cum[p] + j`` (zipWithIndex
parity without materializing indices).  When several logical workers share one
physical device (single-chip mode), shards still get separate HBM buffers --
the worker is the unit of asynchrony, the device is the unit of compute.

Sharding note: shards are balanced like ``repartition`` (sizes differ by at
most 1).  For the SPMD sync path use :func:`ShardedDataset.global_arrays`
with ``parallel.shard_batch`` instead -- that path shards the *global* arrays
over the mesh in one placement.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np


def balanced_sizes(n: int, num_workers: int) -> List[int]:
    """Contiguous balanced split, sizes differ by <=1 (repartition parity)."""
    if num_workers < 1 or num_workers > n:
        raise ValueError(f"num_workers={num_workers} invalid for n={n}")
    return [
        n // num_workers + (1 if i < n % num_workers else 0)
        for i in range(num_workers)
    ]


@dataclass
class Shard:
    worker_id: int
    X: jax.Array  # (n_p, d) on the worker's device
    y: jax.Array  # (n_p,)
    start: int    # global index of row 0 (partitionCumList parity)
    size: int

    @property
    def device(self):
        return self.X.device


class ShardedDataset:
    """Immutable row-sharded (X, y) resident on devices."""

    @classmethod
    def generate_on_device(
        cls,
        n: int,
        d: int,
        num_workers: int,
        devices: Optional[Sequence] = None,
        seed: int = 42,
        noise: float = 0.01,
        dtype=None,
    ) -> "ShardedDataset":
        """Synthesize a planted least-squares problem directly in HBM.

        Zero host->device traffic: each shard's rows are drawn by a jitted
        PRNG on its own device (essential when the host link is slow -- and
        the TPU generates gigabytes/s anyway).  ``_host_X/_host_y`` stay None;
        host-side accessors raise.

        ``dtype=jnp.bfloat16`` stores X in bf16 (half the HBM -- what lets
        mnist8m's 8.1M x 784 fit a single v5e chip); rows are DRAWN in bf16
        so no f32 copy of the shard ever materializes, and labels are
        computed from the bf16-rounded rows with f32 accumulation so the
        planted noise floor stays exactly ``noise**2``.  Labels and the
        planted model stay f32.
        """
        import functools

        import jax.numpy as jnp

        from asyncframework_tpu.ops.gradients import mm_f32

        dtype = jnp.dtype(dtype) if dtype is not None else jnp.float32
        obj = cls.__new__(cls)
        sizes = balanced_sizes(n, num_workers)
        obj.n, obj.d, obj.num_workers = n, d, num_workers
        devs = list(devices) if devices is not None else jax.devices()
        cum = np.concatenate([[0], np.cumsum(sizes)])
        obj.partition_cum = [int(c) for c in cum]

        @functools.partial(jax.jit, static_argnums=(2,))
        def gen_shard(key, w_true, size):
            kx, kn = jax.random.split(key)
            Xp = jax.random.normal(kx, (size, d), dtype) / jnp.sqrt(d).astype(
                dtype
            )
            yp = mm_f32(Xp, w_true) + noise * jax.random.normal(
                kn, (size,), jnp.float32
            )
            return Xp, yp

        # Domain-separate the data stream from the solvers' per-worker mask
        # chains (which are fold_in(PRNGKey(seed), wid)): sharing the seed
        # must not make sample masks a function of the bits that drew the data.
        root = jax.random.fold_in(jax.random.PRNGKey(seed), 0x44415441)  # "DATA"
        w_true = jax.random.normal(jax.random.fold_in(root, 2**30), (d,), jnp.float32)
        obj.shards = {}
        for w in range(num_workers):
            dev = devs[w % len(devs)]
            key = jax.device_put(jax.random.fold_in(root, w), dev)
            Xp, yp = gen_shard(key, jax.device_put(w_true, dev), sizes[w])
            obj.shards[w] = Shard(
                worker_id=w, X=Xp, y=yp,
                start=obj.partition_cum[w], size=sizes[w],
            )
        obj._host_X = None
        obj._host_y = None
        return obj

    def __init__(
        self,
        X: np.ndarray,
        y: np.ndarray,
        num_workers: int,
        devices: Optional[Sequence] = None,
        dtype=None,
    ):
        n = X.shape[0]
        if y.shape[0] != n:
            raise ValueError(f"X has {n} rows but y has {y.shape[0]}")
        sizes = balanced_sizes(n, num_workers)
        self.n = n
        self.d = X.shape[1]
        self.num_workers = num_workers
        devs = list(devices) if devices is not None else jax.devices()
        cum = np.concatenate([[0], np.cumsum(sizes)])
        self.partition_cum: List[int] = [int(c) for c in cum]
        self.shards: Dict[int, Shard] = {}
        for w in range(num_workers):
            lo, hi = self.partition_cum[w], self.partition_cum[w + 1]
            dev = devs[w % len(devs)]
            Xs = jax.device_put(X[lo:hi], dev)
            if dtype is not None:
                Xs = Xs.astype(dtype)  # cast on device: bf16 storage
            self.shards[w] = Shard(
                worker_id=w,
                X=Xs,
                y=jax.device_put(y[lo:hi], dev),
                start=lo,
                size=hi - lo,
            )
        self._host_X = X
        self._host_y = y

    # ------------------------------------------------------------------ views
    def shard(self, worker_id: int) -> Shard:
        return self.shards[worker_id]

    def partition_sizes(self) -> Dict[int, int]:
        """Parity: the drivers' ``partitonInfo`` balance check."""
        return {w: s.size for w, s in self.shards.items()}

    def global_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """Host copies, for the SPMD sync path / evaluation."""
        if self._host_X is None:
            raise ValueError(
                "dataset was generated on device; no host copy exists "
                "(use the per-shard device arrays instead)"
            )
        return self._host_X, self._host_y

    def __repr__(self) -> str:  # pragma: no cover
        return (
            f"ShardedDataset(n={self.n}, d={self.d}, "
            f"workers={self.num_workers})"
        )
