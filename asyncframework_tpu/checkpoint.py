"""Checkpoint / resume of training state: a first-class feature here.

The reference has **no** checkpoint/resume for its training loop -- model
snapshots go to an in-memory list only (``SparkASGDThread.scala:192-195``);
its engine-level checkpointing (``rdd/RDD.scala:1773`` ``ReliableCheckpointRDD``)
persists *datasets*, not solver state.  SURVEY.md section 5 calls out real
model checkpointing as a capability the TPU build must add.

What a solver checkpoint holds (everything needed for bit-faithful resume):
``w`` (the model), the accepted-update counter ``k``, the logical clock, every
worker's PRNG key chain, and -- for ASAGA -- the per-worker gradient-history
slices plus ``alpha_bar``.

Design:
- State is a nested dict whose leaves are arrays (numpy or jax; jax arrays are
  fetched to host on save) or plain scalars/strings.  Nesting is flattened to
  ``a/b/c`` path keys; everything -- flattened arrays plus a JSON manifest
  recording tree structure and leaf kinds -- goes into ONE ``.npz`` file, so
  restore rebuilds the exact structure.
- A checkpoint being a single file makes every write atomic, including
  same-step overwrite: serialize to ``.tmp-<step>-<pid>.npz`` then
  ``os.replace`` onto ``ckpt-<step>.npz`` (rename is atomic even over an
  existing file) -- a reader or a crash never observes a partial or missing
  checkpoint at any point.
- ``max_to_keep`` garbage-collects old steps after a successful save.

Integer dict keys (worker ids) survive a round trip: they are stored as
strings in the path encoding and re-created as ``int`` on restore when the
manifest marks the mapping as int-keyed.
"""

from __future__ import annotations

import json
import os
import re
from pathlib import Path
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

_CKPT_RE = re.compile(r"^ckpt-(\d+)\.npz$")
_TMP_RE = re.compile(r"^\.tmp-\d+-(\d+)\.npz$")
_SEP = "/"
_MANIFEST_KEY = "__manifest__"
_ARR_PREFIX = "arr:"  # namespaces array keys away from the manifest entry


def durable_replace(tmp_path, final_path) -> None:
    """Crash- AND power-loss-durable atomic rename: fsync the data file,
    ``os.replace`` it onto the final name, then fsync the parent directory
    so the rename itself is on disk.  Without the directory fsync a host
    power loss after a "completed" save can roll the directory entry back
    to the old (or no) file even though the data blocks were flushed --
    the classic rename-durability hole.  One definition so the PS
    checkpoint, the step-numbered manager, and the master's persistence
    engine cannot drift on the discipline."""
    fd = os.open(tmp_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp_path, final_path)
    dfd = os.open(os.path.dirname(os.path.abspath(final_path)), os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _flatten(prefix: str, node: Any, arrays: Dict[str, np.ndarray]) -> Any:
    """Flatten ``node`` into ``arrays``; return the manifest subtree."""
    if isinstance(node, Mapping):
        keys = list(node.keys())
        int_keyed = all(isinstance(k, (int, np.integer)) for k in keys) and keys
        sub = {}
        for k in keys:
            ks = str(int(k)) if int_keyed else str(k)
            if _SEP in ks:
                raise ValueError(f"checkpoint keys may not contain '{_SEP}': {k!r}")
            path = f"{prefix}{_SEP}{ks}" if prefix else ks
            sub[ks] = _flatten(path, node[k], arrays)
        return {"kind": "dict", "int_keys": bool(int_keyed), "children": sub}
    if isinstance(node, (list, tuple)):
        sub = []
        for i, v in enumerate(node):
            path = f"{prefix}{_SEP}{i}" if prefix else str(i)
            sub.append(_flatten(path, v, arrays))
        return {"kind": "tuple" if isinstance(node, tuple) else "list",
                "children": sub}
    if node is None:
        return {"kind": "none"}
    if isinstance(node, bool):
        return {"kind": "bool", "value": bool(node)}
    if isinstance(node, (int, np.integer)):
        return {"kind": "int", "value": int(node)}
    if isinstance(node, (float, np.floating)):
        return {"kind": "float", "value": float(node)}
    if isinstance(node, str):
        return {"kind": "str", "value": node}
    # Array leaf: numpy or jax (anything np.asarray can fetch to host).
    arrays[prefix] = np.asarray(node)
    return {"kind": "array", "path": prefix}


def _unflatten(entry: Dict[str, Any], arrays: Mapping[str, np.ndarray]) -> Any:
    kind = entry["kind"]
    if kind == "dict":
        out = {}
        for ks, sub in entry["children"].items():
            key = int(ks) if entry.get("int_keys") else ks
            out[key] = _unflatten(sub, arrays)
        return out
    if kind in ("list", "tuple"):
        vals = [_unflatten(sub, arrays) for sub in entry["children"]]
        return tuple(vals) if kind == "tuple" else vals
    if kind == "none":
        return None
    if kind in ("bool", "int", "float", "str"):
        return entry["value"]
    if kind == "array":
        return arrays[entry["path"]]
    raise ValueError(f"unknown manifest kind {kind!r}")


def save_checkpoint(path, state: Mapping[str, Any]) -> None:
    """Serialize ``state`` into the single file ``path`` (parent created).

    Not atomic on its own -- use :class:`CheckpointManager` for atomic
    step-numbered checkpoints."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    arrays: Dict[str, np.ndarray] = {}
    manifest = _flatten("", dict(state), arrays)
    payload = {_ARR_PREFIX + k: v for k, v in arrays.items()}
    payload[_MANIFEST_KEY] = np.frombuffer(
        json.dumps(manifest).encode(), dtype=np.uint8
    )
    # np.savez appends .npz when missing; write via an open handle to keep
    # the exact path
    with open(p, "wb") as f:
        np.savez(f, **payload)


def load_checkpoint(path) -> Dict[str, Any]:
    with np.load(Path(path)) as npz:
        manifest = json.loads(bytes(npz[_MANIFEST_KEY]).decode())
        arrays = {
            k[len(_ARR_PREFIX):]: npz[k]
            for k in npz.files
            if k.startswith(_ARR_PREFIX)
        }
    return _unflatten(manifest, arrays)


class CheckpointManager:
    """Step-numbered atomic checkpoints under one directory.

    ``save`` writes ``.tmp-<step>-<pid>.npz`` then atomically renames onto
    ``ckpt-<step>.npz`` (overwrite included -- there is a valid checkpoint at
    the step at every instant); ``restore`` loads a given (default: latest)
    step; old steps beyond ``max_to_keep`` are deleted after each save.
    """

    def __init__(self, directory, max_to_keep: int = 3):
        if max_to_keep < 1:
            raise ValueError("max_to_keep must be >= 1")
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep

    # ------------------------------------------------------------------ query
    def all_steps(self) -> List[int]:
        steps = []
        for child in self.directory.iterdir():
            m = _CKPT_RE.match(child.name)
            if m and child.is_file():
                steps.append(int(m.group(1)))
        return sorted(steps)

    def latest_step(self) -> Optional[int]:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_path(self, step: int) -> Path:
        return self.directory / f"ckpt-{step}.npz"

    # ------------------------------------------------------------------- save
    def save(self, step: int, state: Mapping[str, Any]) -> Path:
        if step < 0:
            raise ValueError("step must be >= 0")
        final = self.step_path(step)
        tmp = self.directory / f".tmp-{step}-{os.getpid()}.npz"
        save_checkpoint(tmp, state)
        # fsync data before the rename and the directory after it, so a power
        # loss can never leave a truncated ckpt-<step>.npz behind the atomic
        # name swap (same discipline as native/kvstore.cc kv_compact)
        durable_replace(tmp, final)
        self._gc()
        return final

    def restore(self, step: Optional[int] = None) -> Dict[str, Any]:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints under {self.directory}"
                )
        path = self.step_path(step)
        if not path.is_file():
            raise FileNotFoundError(f"no checkpoint at step {step}: {path}")
        return load_checkpoint(path)

    def restore_latest_or_none(self) -> Optional[Dict[str, Any]]:
        if self.latest_step() is None:
            return None
        return self.restore()

    # --------------------------------------------------------------------- gc
    def _gc(self) -> None:
        steps = self.all_steps()
        for step in steps[: max(0, len(steps) - self.max_to_keep)]:
            try:
                self.step_path(step).unlink()
            except FileNotFoundError:
                pass
        # sweep temp files from *crashed* writers only: a live pid may be a
        # concurrent writer mid-save whose file must not be yanked
        for child in self.directory.iterdir():
            m = _TMP_RE.match(child.name)
            if m is None:
                continue
            pid = int(m.group(1))
            if pid == os.getpid() or _pid_alive(pid):
                continue
            try:
                child.unlink()
            except FileNotFoundError:
                pass
