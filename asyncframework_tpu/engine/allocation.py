"""Dynamic executor allocation: scale slot capacity with task backlog.

Parity (studied, not copied): ``core/src/main/scala/org/apache/spark/
ExecutorAllocationManager.scala:82`` -- Spark requests extra executors when
tasks stay backlogged past ``schedulerBacklogTimeout`` and releases
executors idle past ``executorIdleTimeout``.

TPU mapping: the pod is a fixed resource, so "adding an executor" cannot
mean adding a chip -- it means adding a HOST THREAD (a sibling
``DeviceExecutor``) to a backlogged device slot.  That is precisely the
resource that runs out in this runtime: a slot's executor thread serializes
task bodies (host-side preprocessing, straggler sleeps, dispatch), so a
backlog of queued tasks on one slot is drained by a second thread sharing
the same device stream.  Scale-down retires idle siblings, never the
primary.

The policy mirrors the reference: a slot must stay backlogged for
``sustained_ticks`` consecutive checks before scale-up (the
schedulerBacklogTimeout analog), and a slot must be quiet for
``idle_timeout_s`` before a sibling is retired.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

from asyncframework_tpu.utils.clock import Clock, SystemClock


class ExecutorAllocationManager:
    """Periodic backlog scan over a :class:`JobScheduler`'s pool."""

    def __init__(
        self,
        scheduler,
        max_extra_per_slot: int = 1,
        backlog_threshold: int = 2,
        sustained_ticks: int = 2,
        idle_timeout_s: float = 1.0,
        check_interval_s: float = 0.05,
        clock: Optional[Clock] = None,
        on_scale=None,
    ):
        if backlog_threshold < 1:
            raise ValueError("backlog_threshold must be >= 1")
        self._sched = scheduler
        self.max_extra = max_extra_per_slot
        self.backlog_threshold = backlog_threshold
        self.sustained_ticks = sustained_ticks
        self.idle_timeout_s = idle_timeout_s
        self._interval = check_interval_s
        self._clock = clock or SystemClock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._on_scale = on_scale  # callback(worker_id, +1 | -1)
        self._backlog_streak: Dict[int, int] = {}
        self._idle_since_ms: Dict[int, float] = {}
        self._added = 0
        self._removed = 0
        self.last_error: Optional[BaseException] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------ policy
    def check_once(self) -> List[Tuple[int, int]]:
        """One scan; returns [(worker_id, delta)] scale events (testable
        without threads)."""
        pool = self._sched.pool
        events: List[Tuple[int, int]] = []
        now = self._clock.now_ms()
        for wid in pool.alive_ids():
            backlog = pool.slot_backlog(wid)
            if backlog >= self.backlog_threshold:
                self._idle_since_ms.pop(wid, None)
                streak = self._backlog_streak.get(wid, 0) + 1
                self._backlog_streak[wid] = streak
                if (
                    streak >= self.sustained_ticks
                    and pool.sibling_count(wid) < self.max_extra
                ):
                    pool.add_sibling(wid)
                    self._backlog_streak[wid] = 0
                    events.append((wid, +1))
            else:
                self._backlog_streak[wid] = 0
                if backlog == 0 and pool.sibling_count(wid) > 0:
                    since = self._idle_since_ms.setdefault(wid, now)
                    if now - since >= self.idle_timeout_s * 1e3:
                        if pool.remove_idle_sibling(wid):
                            events.append((wid, -1))
                        self._idle_since_ms.pop(wid, None)
                else:
                    self._idle_since_ms.pop(wid, None)
        if events:
            with self._lock:
                for _wid, delta in events:
                    if delta > 0:
                        self._added += 1
                    else:
                        self._removed += 1
            if self._on_scale is not None:
                for wid, delta in events:
                    self._on_scale(wid, delta)
        return events

    def counts(self) -> Tuple[int, int]:
        with self._lock:
            return self._added, self._removed

    # ---------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self._interval):
                try:
                    self.check_once()
                except Exception as e:
                    if self._sched.pool.closed:
                        return  # pool torn down mid-scan: normal exit
                    # a real policy/callback bug: record it, log it once,
                    # and stop scanning -- silently retrying every tick
                    # would leave allocation half-applied with misleading
                    # counts and no diagnostic
                    self.last_error = e
                    logging.getLogger(__name__).warning(
                        "dynamic allocation stopped after error: %r", e
                    )
                    return

        self._thread = threading.Thread(
            target=loop, name="executor-allocation", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
