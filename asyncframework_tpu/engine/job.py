"""Jobs, tasks, and the completion future.

Parity: ``core/.../scheduler/JobWaiter.scala:30`` -- per-task
``taskSucceeded(index, result)`` invoking the job's ``resultHandler`` and a
completion future resolved when all tasks finish; ``ActiveJob`` /
``ResultTask`` carry (job id, partition/worker id, function).

TPU mapping: a "task" is a host closure that launches a jitted computation on
one worker's device (plus any injected delay); the "cluster" it runs on is the
in-process :class:`ExecutorPool`.
"""

from __future__ import annotations

import itertools
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

_job_ids = itertools.count()


@dataclass
class TaskSpec:
    """One unit of work bound to a logical worker.

    ``speculative`` marks a duplicate copy launched by the speculation
    monitor: its success merges normally (first completion wins) but its
    *failure* is dropped -- the healthy primary is still running and must not
    be retried or counted against the job's attempt budget.  The flag also
    keeps the copy from comparing equal to the primary's in-flight entry.
    """

    job_id: int
    worker_id: int
    fn: Callable[[], Any]
    attempt: int = 0
    speculative: bool = False


class JobWaiter:
    """Completion future for a job; streams per-task results to a handler.

    ``result_handler(worker_id, result)`` runs on the completing executor's
    thread (parity: Spark's handler runs on the DAG event loop) -- handlers
    must therefore be thread-safe; in this framework the canonical handler is
    ``AsyncContext.merge_result`` which is.
    """

    def __init__(
        self,
        job_id: int,
        worker_ids: List[int],
        result_handler: Callable[[int, Any], None],
    ):
        self.job_id = job_id
        self._expected = set(worker_ids)
        self._claimed: set = set()   # first completion claims the worker slot
        self._handled: set = set()   # handler has fully run for the worker
        self._failed: Optional[BaseException] = None
        self._handler = result_handler
        self._lock = threading.Lock()
        self._done = threading.Event()
        self._failure_cbs: List[Callable[[BaseException], None]] = []
        if not self._expected:
            self._done.set()  # zero-task job is trivially complete

    def is_claimed(self, worker_id: int) -> bool:
        """True when some completion (primary or speculative) already claimed
        this worker's slot -- a late failure of the other copy is then moot."""
        with self._lock:
            return worker_id in self._claimed

    def task_succeeded(self, worker_id: int, result: Any) -> bool:
        """Returns True when this completion claimed the worker's slot
        (False = a duplicate; the other copy already won the race)."""
        with self._lock:
            if worker_id in self._claimed:
                return False  # duplicate (speculative copy lost the race)
            self._claimed.add(worker_id)
        # Handler runs outside the lock but BEFORE the worker counts toward
        # completion: await_result must never release while a claimed
        # result is still being merged.
        self._handler(worker_id, result)
        with self._lock:
            self._handled.add(worker_id)
            if self._handled >= self._expected:
                self._done.set()
        return True

    def job_failed(self, exc: BaseException) -> None:
        with self._lock:
            self._failed = exc
            self._done.set()
            cbs = list(self._failure_cbs)
        for cb in cbs:
            cb(exc)

    def on_failure(self, cb: Callable[[BaseException], None]) -> None:
        """Register a callback invoked (once) if the job aborts.

        Fires immediately when the job has already failed -- async submitters
        use this to release resources (e.g. un-busy a cohort) without polling.
        """
        with self._lock:
            if self._failed is None:
                self._failure_cbs.append(cb)
                return
            exc = self._failed
        cb(exc)

    def await_result(self, timeout: Optional[float] = None) -> None:
        """Block until every task has merged (mode-0 / first-iteration path).

        Raises the job's failure if any task exhausted its retries.
        """
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(f"job {self.job_id} did not complete in {timeout}s")
        if self._failed is not None:
            raise self._failed

    @property
    def completed(self) -> bool:
        return self._done.is_set() and self._failed is None

    @property
    def failed(self) -> Optional[BaseException]:
        return self._failed


@dataclass
class Job:
    """An active job: one task per cohort worker."""

    job_id: int
    tasks: Dict[int, TaskSpec]
    waiter: JobWaiter

    @staticmethod
    def create(
        worker_fns: Dict[int, Callable[[], Any]],
        result_handler: Callable[[int, Any], None],
    ) -> "Job":
        job_id = next(_job_ids)
        tasks = {
            wid: TaskSpec(job_id=job_id, worker_id=wid, fn=fn)
            for wid, fn in worker_fns.items()
        }
        waiter = JobWaiter(job_id, list(worker_fns), result_handler)
        return Job(job_id, tasks, waiter)
