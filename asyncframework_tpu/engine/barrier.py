"""Partial-barrier cohort selection.

Parity: ``RDD.ASYNCbarrier`` (``rdd/RDD.scala:1050-1077``): given a predicate
over per-worker state and the driver's state table, select the workers that
participate in the next round; workers with no table entry yet (cold start)
are always selected.  The reference materializes the selection as a global
``RDD.WorkerList`` consumed by ``mapPartitionsWithIndex``; here the cohort is
a returned value (no global mutable state) that the solver passes to
``JobScheduler.run_job``.
"""

from __future__ import annotations

import math
from typing import Callable, List, Sequence, Union

from asyncframework_tpu.context import AsyncContext, WorkerState


def partial_barrier(
    ctx: AsyncContext,
    workers: Union[int, Sequence[int]],
    predicate: Callable[[WorkerState], bool],
) -> List[int]:
    """Return the cohort: workers whose state passes ``predicate`` AND are
    available, plus workers never seen (no STAT entry).

    ``workers`` is either a worker count (ids ``0..n-1``) or an explicit id
    sequence (for datasets with non-contiguous partition ids).
    """
    ids = range(workers) if isinstance(workers, int) else workers
    cohort: List[int] = []
    states = ctx.states()
    for wid in ids:
        ws = states.get(wid)
        if ws is None:
            cohort.append(wid)
        elif predicate(ws) and ws.available:
            cohort.append(wid)
    return cohort


def bucket_predicate(ctx: AsyncContext, num_workers: int, bucket_ratio: float):
    """The drivers' predicate: enough of the fleet is available.

    Parity: ``SparkASGDThread.scala:282`` --
    ``state.getAvailableWorkers() >= floor(numPart * bucketRatio)``.
    """
    threshold = math.floor(num_workers * bucket_ratio)

    def pred(_ws: WorkerState) -> bool:
        return ctx.available_workers() >= threshold

    return pred
