"""Speculative execution: duplicate slow tasks, first finisher wins.

Parity: ``scheduler/TaskSetManager.checkSpeculatableTasks``
(``TaskSetManager.scala:975``): once at least ``quantile`` of a job's tasks
have finished, any task running longer than ``multiplier * median(finished
durations)`` (and at least ``min_time_ms``) gets a speculative copy launched
on a different executor; whichever copy finishes first supplies the result
and the other is ignored.

TPU mapping: the straggler is a host thread + device dispatch, not a bad
machine, so the "different executor" is a *spare* executor thread bound to
the same device slot (the device stream serializes compute, but the common
straggler causes here -- injected delay, a wedged host thread, host-side GC
-- are bypassed by the spare).  De-duplication happens in ``JobWaiter``:
a worker's second completion is dropped before the result handler runs.
"""

from __future__ import annotations

import statistics
import threading
from typing import Dict, List, Optional, Set, Tuple

from asyncframework_tpu.utils.clock import Clock, SystemClock


def find_speculatable(
    finished_ms: List[float],
    running_elapsed_ms: Dict[int, float],
    quantile: float = 0.75,
    multiplier: float = 1.5,
    min_time_ms: float = 100.0,
) -> List[int]:
    """Pure selection logic (unit-testable with no threads).

    ``finished_ms``: durations of this job's finished tasks.
    ``running_elapsed_ms``: worker id -> elapsed time of its running task.
    Returns worker ids whose running task qualifies for a speculative copy.
    """
    total = len(finished_ms) + len(running_elapsed_ms)
    if total == 0 or not finished_ms:
        return []
    if len(finished_ms) / total < quantile:
        return []
    threshold = max(multiplier * statistics.median(finished_ms), min_time_ms)
    return [wid for wid, el in running_elapsed_ms.items() if el > threshold]


class SpeculationMonitor:
    """Periodic scan over a scheduler's active jobs.

    The scheduler exposes ``speculation_snapshot()`` (per-job finished
    durations + running task elapsed times) and ``speculative_launch(job_id,
    worker_id)``; this monitor owns only the policy and the scan cadence.
    One speculative copy per (job, worker), like the reference.
    """

    def __init__(
        self,
        scheduler,
        quantile: float = 0.75,
        multiplier: float = 1.5,
        min_time_ms: float = 100.0,
        check_interval_s: float = 0.1,
        clock: Optional[Clock] = None,
        on_launch=None,
    ):
        self._sched = scheduler
        self.quantile = quantile
        self.multiplier = multiplier
        self.min_time_ms = min_time_ms
        self._interval = check_interval_s
        self._clock = clock or SystemClock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._speculated: Set[Tuple[int, int]] = set()
        self._lock = threading.Lock()
        self._on_launch = on_launch  # callback(job_id, worker_id) per copy

    def check_once(self) -> List[Tuple[int, int]]:
        """One scan; returns the (job_id, worker_id) copies launched."""
        launched: List[Tuple[int, int]] = []
        for job_id, (finished, running) in self._sched.speculation_snapshot().items():
            for wid in find_speculatable(
                finished, running, self.quantile, self.multiplier, self.min_time_ms
            ):
                with self._lock:
                    if (job_id, wid) in self._speculated:
                        continue
                    self._speculated.add((job_id, wid))
                if self._sched.speculative_launch(job_id, wid):
                    launched.append((job_id, wid))
                    if self._on_launch is not None:
                        try:
                            self._on_launch(job_id, wid)
                        except Exception:  # noqa: BLE001 - observer must not kill scan
                            pass
        return launched

    def speculated_count(self) -> int:
        with self._lock:
            return len(self._speculated)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="speculation-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.check_once()
