"""Executor liveness monitoring and failure detection.

Parity: ``core/.../HeartbeatReceiver.scala:59`` (driver-side liveness via
periodic executor heartbeats; silent executors are declared dead and their
tasks resubmitted) + standalone Master/Worker heartbeats.  Executors here
touch ``last_heartbeat_ms`` whenever their loop wakes; the monitor thread
compares against a timeout and notifies the scheduler (``on_executor_lost``),
which replaces the executor and resubmits in-flight tasks.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from asyncframework_tpu.engine.executor import ExecutorPool
from asyncframework_tpu.utils.clock import Clock, SystemClock


class HeartbeatMonitor:
    def __init__(
        self,
        pool: ExecutorPool,
        on_executor_lost: Callable[[int], None],
        timeout_ms: float = 5000.0,
        check_interval_s: float = 0.5,
        task_timeout_ms: Optional[float] = None,
        clock: Optional[Clock] = None,
        on_sibling_lost=None,
    ):
        """``timeout_ms`` applies to *idle* silence (a dead thread).  A worker
        legitimately goes silent while running a long task (first XLA compile
        is tens of seconds), so busy executors are only timed out when
        ``task_timeout_ms`` is set (hung-task detection, off by default --
        slow tasks are the *straggler* story, handled by cohort selection,
        not by killing workers)."""
        self._pool = pool
        self._on_lost = on_executor_lost
        # on_sibling_lost(wid, queued_tasks, running_task): a failed
        # dynamic-allocation sibling must NOT escalate to slot loss -- the
        # primary is healthy, and resubmitting ITS in-flight tasks would
        # inflate their attempts (spurious max-failures abort) and
        # duplicate running work.  Only the sibling's own tasks resubmit.
        self._on_sibling_lost = on_sibling_lost
        self._timeout_ms = timeout_ms
        self._task_timeout_ms = task_timeout_ms
        self._interval = check_interval_s
        self._clock = clock or SystemClock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="heartbeat-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def check_once(self) -> list:
        """One scan; returns the worker ids declared lost (test-friendly)."""
        if self._pool.closed:
            return []
        now = self._clock.now_ms()

        def is_bad(ex) -> bool:
            if ex.shutdown_requested:
                return False  # graceful stop, not a failure
            if not ex.alive:
                return True
            if ex.busy:
                return (
                    self._task_timeout_ms is not None
                    and now - ex.busy_since_ms > self._task_timeout_ms
                )
            return now - ex.last_heartbeat_ms > self._timeout_ms

        lost = []
        for wid, ex in list(self._pool.executors.items()):
            if is_bad(ex):
                lost.append(wid)
            # dynamic-allocation siblings carry tasks too: a dead or hung
            # sibling is dropped and ONLY ITS tasks resubmit -- the healthy
            # primary's in-flight work keeps its attempt counts.  Without a
            # resubmission handler the sibling's tasks would be silently
            # discarded (hung jobs), so fall back to escalating the whole
            # slot -- on_lost's resubmission covers them
            for sib in self._pool.siblings_of(wid):
                if is_bad(sib):
                    if self._on_sibling_lost is not None and wid not in lost:
                        queued, running = self._pool.drop_sibling(wid, sib)
                        self._on_sibling_lost(wid, queued, running)
                    else:
                        # no handler, OR the slot is already being
                        # escalated this scan: on_lost's resubmission
                        # covers the sibling's tasks -- relaunching them
                        # here too would double-execute and double-bump
                        # their attempts
                        self._pool.drop_sibling(wid, sib)
                        if wid not in lost:
                            lost.append(wid)
        for wid in lost:
            self._on_lost(wid)
        return lost

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self.check_once()
