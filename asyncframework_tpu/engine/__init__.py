from asyncframework_tpu.engine.job import Job, JobWaiter, TaskSpec
from asyncframework_tpu.engine.executor import DeviceExecutor, ExecutorPool, TaskMetrics
from asyncframework_tpu.engine.scheduler import JobScheduler
from asyncframework_tpu.engine.barrier import partial_barrier
from asyncframework_tpu.engine.straggler import DelayModel, build_cloud_stragglers
from asyncframework_tpu.engine.blacklist import BlacklistTracker
from asyncframework_tpu.engine.allocation import ExecutorAllocationManager
from asyncframework_tpu.engine.speculation import SpeculationMonitor, find_speculatable
from asyncframework_tpu.engine.recovery import (
    ReassignmentPlan,
    ShardRecovery,
    plan_reassignment,
)
from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor
from asyncframework_tpu.engine.accumulator import (
    Accumulator,
    CollectionAccumulator,
    DoubleAccumulator,
    LongAccumulator,
    MaxAccumulator,
)

__all__ = [
    "Accumulator",
    "LongAccumulator",
    "DoubleAccumulator",
    "CollectionAccumulator",
    "MaxAccumulator",
    "Job",
    "JobWaiter",
    "TaskSpec",
    "DeviceExecutor",
    "ExecutorPool",
    "TaskMetrics",
    "JobScheduler",
    "partial_barrier",
    "DelayModel",
    "build_cloud_stragglers",
    "BlacklistTracker",
    "SpeculationMonitor",
    "ExecutorAllocationManager",
    "find_speculatable",
    "ReassignmentPlan",
    "ShardRecovery",
    "plan_reassignment",
    "HeartbeatMonitor",
]
