"""Deterministic straggler / delay injection.

Parity: the ASYNC drivers' simulation of slow workers
(``SparkASGDThread.scala:121-138`` for cohort construction,
``:284-309`` for the injected sleeps):

- ``coeff > 0``: worker 0 sleeps ``coeff * avg_delay`` each round (a single
  deterministic straggler whose slowness scales with measured average task
  latency);
- ``coeff == -1`` ("cloud mode", long-tail): 25% of workers are stragglers --
  of those, 80% sleep ``U(1.5, 2.5) * avg_delay`` and the rest sleep
  ``U(2.5, 10) * avg_delay``; straggler worker ids follow the reference's
  ``c * 4`` spacing pattern;
- delays activate only after the calibration phase (first ``100 * num_workers``
  accepted updates measure ``avg_delay``).

Delta from the reference: the per-round multipliers draw from a seeded
``numpy`` Generator instead of an unseeded ``java.util.Random``, so runs are
reproducible; staleness on a real pod also arises naturally from compute-time
variance -- this module only *adds* controlled skew.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np


def build_cloud_stragglers(num_workers: int) -> Tuple[List[int], List[int]]:
    """Reference cohort construction (``SparkASGDThread.scala:126-138``):
    ``length = round(0.25 * n)`` stragglers; first ``length - round(0.8*length)``
    of the ``c*4`` id sequence are long-tail, the rest normal."""
    length = int(round(0.25 * num_workers))
    length_normal = int(round(0.8 * length))
    length_long_tail = length - length_normal
    long_tail = [c * 4 for c in range(0, length_long_tail)]
    normal = [c * 4 for c in range(length_long_tail, length)]
    return normal, long_tail


@dataclass
class DelayModel:
    """Computes the injected delay (ms) for a worker in one round."""

    coeff: float
    num_workers: int
    seed: int = 42
    avg_delay_ms: float = 0.0
    calibrated: bool = False
    _rng: np.random.Generator = field(default=None, repr=False)  # type: ignore
    _normal: List[int] = field(default_factory=list)
    _long_tail: List[int] = field(default_factory=list)

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)
        if self.cloud_mode:
            self._normal, self._long_tail = build_cloud_stragglers(self.num_workers)

    @property
    def cloud_mode(self) -> bool:
        return self.coeff == -1

    @property
    def enabled(self) -> bool:
        return self.coeff != 0

    def calibrate(self, avg_delay_ms: float) -> None:
        """Fix the average-delay scale after the measurement phase."""
        self.avg_delay_ms = avg_delay_ms
        self.calibrated = True

    def delay_ms(self, worker_id: int) -> float:
        """Delay to inject for this worker this round (0 before calibration)."""
        if not self.enabled or not self.calibrated:
            return 0.0
        if not self.cloud_mode:
            if worker_id == 0 and self.coeff > 0:
                return float(round(self.coeff * self.avg_delay_ms))
            return 0.0
        if worker_id in self._long_tail:
            c = self._rng.random() * 7.5 + 2.5
            return float(round(c * self.avg_delay_ms))
        if worker_id in self._normal:
            c = self._rng.random() + 1.5
            return float(round(c * self.avg_delay_ms))
        return 0.0
