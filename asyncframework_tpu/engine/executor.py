"""Device executors: the worker side of the engine.

Parity: ``executor/Executor.scala:53`` (``TaskRunner.run`` 290: run task,
report status) + ``executor/CoarseGrainedExecutorBackend.scala:40``
(``LaunchTask`` inbox) + per-task ``TaskMetrics``
(``executor/TaskMetrics.scala:45``) + executor heartbeats (``Executor.scala:814``).

TPU mapping: an executor is a daemon thread bound to one *logical worker*.
Each worker owns a jax device slot -- on an 8-device mesh that is one chip per
worker; on a single chip, workers share the device and the XLA stream
serializes their compute while the host threads still overlap dispatch,
transfers, and the driver loop (this mirrors the reference's ``local[8]``
mode, where 8 executor threads share one machine).

Failure semantics: a task closure raising is reported to the scheduler
(status FAILED -> retry/resubmit policy there); an executor can also be
``kill()``-ed to simulate worker loss -- its heartbeat stops and the
:class:`HeartbeatMonitor` (engine/heartbeat.py) declares it dead, triggering
task resubmission on a replacement. That is the Spark executor-loss /
``DistributedSuite`` story in one process.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from asyncframework_tpu.engine.job import TaskSpec
from asyncframework_tpu.utils.clock import Clock, SystemClock


@dataclass
class TaskMetrics:
    """Per-task observability record (TaskMetrics parity, trimmed to what a
    host-dispatched XLA task actually has)."""

    job_id: int
    worker_id: int
    attempt: int
    launch_ms: float
    finish_ms: float = 0.0
    run_ms: float = 0.0
    injected_delay_ms: float = 0.0
    succeeded: bool = False
    error: Optional[str] = None


class DeviceExecutor:
    """One worker: a daemon thread draining an inbox of :class:`TaskSpec`.

    ``status_update(executor, task, result, exc)`` is invoked on this thread
    when a task finishes (Spark's ``statusUpdate`` RPC, minus the RPC).
    """

    def __init__(
        self,
        worker_id: int,
        status_update: Callable[["DeviceExecutor", TaskSpec, Any, Optional[BaseException]], None],
        device=None,
        clock: Optional[Clock] = None,
    ):
        self.worker_id = worker_id
        self.device = device
        self._status_update = status_update
        self._clock = clock or SystemClock()
        self._inbox: "queue.Queue[Optional[TaskSpec]]" = queue.Queue()
        self._alive = True
        self._killed = False
        self.shutdown_requested = False
        self.busy = False
        self.current_task: Optional[TaskSpec] = None
        self.busy_since_ms = 0.0
        self.last_heartbeat_ms = self._clock.now_ms()
        self.metrics: List[TaskMetrics] = []
        self._thread = threading.Thread(
            target=self._run, name=f"executor-{worker_id}", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------------ API
    def launch_task(self, task: TaskSpec) -> None:
        if not self._alive:
            raise RuntimeError(f"executor {self.worker_id} is not alive")
        self._inbox.put(task)

    def kill(self) -> None:
        """Simulate worker loss: stop heartbeating and stop taking work."""
        self._killed = True
        self._alive = False
        self._inbox.put(None)

    def shutdown(self) -> None:
        """Graceful stop: NOT a failure -- the heartbeat monitor must not
        declare this executor lost (unlike :meth:`kill`)."""
        self.shutdown_requested = True
        self._alive = False
        self._inbox.put(None)

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout=timeout)

    @property
    def alive(self) -> bool:
        return self._alive

    def pending_tasks(self) -> int:
        return self._inbox.qsize()

    def idle(self) -> bool:
        """True iff no queued AND no dequeued-but-unfinished task.  Uses
        the queue's unfinished-task count (decremented only after the task
        completes), so the dequeue->busy window cannot misreport idle."""
        return self._inbox.unfinished_tasks == 0

    # ------------------------------------------------------------ main loop
    def _run(self) -> None:
        while True:
            try:
                task = self._inbox.get(timeout=0.1)
            except queue.Empty:
                if not self._alive:
                    return
                self.last_heartbeat_ms = self._clock.now_ms()
                continue
            if task is None or self._killed:
                if task is not None or not self._killed:
                    self._inbox.task_done()  # sentinel / killed-drop
                if (
                    task is None
                    and self.shutdown_requested
                    and not self._killed
                ):
                    # graceful retirement: a task enqueued concurrently
                    # with shutdown() may sit behind the sentinel -- drain
                    # and run it rather than strand its job forever
                    try:
                        task = self._inbox.get_nowait()
                    except queue.Empty:
                        return
                    if task is None:
                        self._inbox.task_done()
                        return
                else:
                    return
            self.last_heartbeat_ms = self._clock.now_ms()
            self.busy = True
            self.current_task = task
            self.busy_since_ms = self.last_heartbeat_ms
            m = TaskMetrics(
                job_id=task.job_id,
                worker_id=self.worker_id,
                attempt=task.attempt,
                launch_ms=self._clock.now_ms(),
            )
            try:
                result = task.fn()
                m.succeeded = True
                exc: Optional[BaseException] = None
            except BaseException as e:  # noqa: BLE001 - report, don't die
                result = None
                exc = e
                m.error = repr(e)
            m.finish_ms = self._clock.now_ms()
            m.run_ms = m.finish_ms - m.launch_ms
            self.metrics.append(m)
            self.busy = False
            self.current_task = None
            self._inbox.task_done()
            self.last_heartbeat_ms = self._clock.now_ms()
            if self._killed:
                return  # killed mid-task: never report (the monitor handles it)
            self._status_update(self, task, result, exc)


class ExecutorPool:
    """Creates and tracks executors; supports replacement after failure.

    Parity: the standalone ``Master``/``Worker`` pair's role of (re)launching
    executors (``deploy/master/Master.scala``), collapsed to in-process
    thread management -- the TPU build has no separate OS processes to manage,
    the pod is a fixed resource.
    """

    def __init__(
        self,
        num_workers: int,
        status_update,
        devices: Optional[List] = None,
        clock: Optional[Clock] = None,
    ):
        self.closed = False
        self._clock = clock or SystemClock()
        self._status_update = status_update
        if devices is not None and len(devices) > 0:
            device_of = lambda wid: devices[wid % len(devices)]  # noqa: E731
        else:
            device_of = lambda wid: None  # noqa: E731
        self._device_of = device_of
        self._lock = threading.Lock()
        self.executors: Dict[int, DeviceExecutor] = {
            wid: DeviceExecutor(wid, status_update, device_of(wid), self._clock)
            for wid in range(num_workers)
        }
        self._spares: List[DeviceExecutor] = []
        # long-lived extra executors per slot, added/removed by the
        # allocation manager (dynamic allocation); distinct from one-shot
        # speculation spares
        self._siblings: Dict[int, List[DeviceExecutor]] = {}
        # TaskMetrics of retired siblings: their tasks must stay accounted
        self._retired_metrics: List[TaskMetrics] = []

    def get(self, worker_id: int) -> DeviceExecutor:
        with self._lock:
            return self.executors[worker_id]

    # ------------------------------------------------- dynamic allocation
    def add_sibling(self, worker_id: int) -> DeviceExecutor:
        """Register a long-lived extra executor on a slot.  New launches go
        to the least-loaded of the slot's executors (``least_loaded``) --
        the in-process analog of dynamic executor allocation adding
        capacity where tasks back up."""
        with self._lock:
            if self.closed:
                raise RuntimeError("pool is shut down; cannot add sibling")
            ex = DeviceExecutor(
                worker_id, self._status_update,
                self._device_of(worker_id), self._clock,
            )
            self._siblings.setdefault(worker_id, []).append(ex)
            return ex

    def remove_idle_sibling(self, worker_id: int) -> bool:
        """Retire one idle sibling from the slot (scale-down); returns
        whether one was removed.  Busy siblings (running OR queued work)
        are never killed; the check and the removal happen under the pool
        lock, the same lock ``launch_on_slot`` holds while enqueuing, so a
        concurrently-launched task cannot land on a retiring sibling."""
        with self._lock:
            sibs = self._siblings.get(worker_id, [])
            for i, ex in enumerate(sibs):
                if ex.idle():
                    del sibs[i]
                    self._retired_metrics.extend(ex.metrics)
                    break
            else:
                return False
        ex.shutdown()
        return True

    def launch_on_slot(self, worker_id: int, task) -> None:
        """Pick the slot's least-loaded executor and enqueue the task in
        one pool-locked step, so sibling retirement (which takes the same
        lock) can never shut down the chosen executor between the pick and
        the enqueue."""
        with self._lock:
            self._least_loaded_locked(worker_id).launch_task(task)

    def sibling_count(self, worker_id: int) -> int:
        with self._lock:
            return len(self._siblings.get(worker_id, []))

    def slot_backlog(self, worker_id: int) -> int:
        """Queued-but-unstarted tasks across the slot's executors."""
        with self._lock:
            ex = self.executors.get(worker_id)
            total = ex.pending_tasks() if ex is not None and ex.alive else 0
            for s in self._siblings.get(worker_id, []):
                if s.alive:
                    total += s.pending_tasks()
            return total

    def siblings_of(self, worker_id: int) -> List[DeviceExecutor]:
        with self._lock:
            return list(self._siblings.get(worker_id, []))

    def drop_sibling(self, worker_id: int, ex: DeviceExecutor):
        """Remove a dead/hung sibling (failure path -- contrast the
        scale-down path ``remove_idle_sibling``); its metrics are retained
        and it is killed, not drained.  Returns ``(queued, running)``: the
        never-started tasks recovered from its inbox (relaunchable at the
        SAME attempt) and the task it was running when it died, if any
        (failed once -- relaunch bumps the attempt)."""
        with self._lock:
            sibs = self._siblings.get(worker_id, [])
            self._siblings[worker_id] = [s for s in sibs if s is not ex]
            self._retired_metrics.extend(ex.metrics)
        running = ex.current_task
        ex.kill()
        queued = []
        try:
            while True:
                t = ex._inbox.get_nowait()
                if t is not None:
                    queued.append(t)
        except queue.Empty:
            pass
        return queued, running

    def _least_loaded_locked(self, worker_id: int) -> DeviceExecutor:
        """The slot's executor with the lightest load (primary when tied --
        keeps single-executor behavior identical).  Load counts the queued
        inbox PLUS the currently-running task: a busy executor with an
        empty inbox must lose the tie to an idle sibling.  Internal: pick
        and enqueue must share one lock hold (``launch_on_slot``)."""
        def load_of(ex: DeviceExecutor) -> float:
            if not ex.alive:
                return float("inf")
            return ex.pending_tasks() + (1 if ex.busy else 0)

        best = self.executors[worker_id]
        load = load_of(best)
        for s in self._siblings.get(worker_id, []):
            if load_of(s) < load:
                best, load = s, load_of(s)
        return best

    # ----------------------------------------------------- speculative spares
    def spawn_spare(self, worker_id: int) -> DeviceExecutor:
        """Extra executor bound to the same device slot, for a speculative
        copy; not registered under the worker id (the primary keeps it)."""
        with self._lock:
            if self.closed:
                raise RuntimeError("pool is shut down; cannot spawn spare")
            ex = DeviceExecutor(
                worker_id, self._status_update, self._device_of(worker_id), self._clock
            )
            self._spares.append(ex)
            return ex

    def is_spare(self, ex: DeviceExecutor) -> bool:
        with self._lock:
            return any(s is ex for s in self._spares)

    def discard_spare(self, ex: DeviceExecutor) -> None:
        """One-shot spares are shut down and dropped after their task."""
        with self._lock:
            self._spares = [s for s in self._spares if s is not ex]
            self._retired_metrics.extend(ex.metrics)
        ex.shutdown()

    def replace(self, worker_id: int) -> DeviceExecutor:
        """Start a fresh executor for a dead worker (elastic recovery)."""
        with self._lock:
            if self.closed:
                raise RuntimeError("pool is shut down; cannot replace executor")
            old = self.executors.get(worker_id)
            if old is not None:
                self._retired_metrics.extend(old.metrics)
                if old.alive:
                    old.shutdown()
            ex = DeviceExecutor(
                worker_id, self._status_update, self._device_of(worker_id), self._clock
            )
            self.executors[worker_id] = ex
            return ex

    def kill(self, worker_id: int) -> None:
        with self._lock:
            self.executors[worker_id].kill()

    def alive_ids(self) -> List[int]:
        with self._lock:
            return [wid for wid, ex in self.executors.items() if ex.alive]

    def shutdown(self) -> None:
        with self._lock:
            self.closed = True
            for ex in self.executors.values():
                ex.shutdown()
            for ex in self._spares:
                self._retired_metrics.extend(ex.metrics)
                ex.shutdown()
            self._spares = []
            for sibs in self._siblings.values():
                for ex in sibs:
                    self._retired_metrics.extend(ex.metrics)
                    ex.shutdown()
            self._siblings = {}

    def all_metrics(self) -> List[TaskMetrics]:
        with self._lock:
            out: List[TaskMetrics] = []
            for ex in self.executors.values():
                out.extend(ex.metrics)
            for sibs in self._siblings.values():
                for ex in sibs:
                    out.extend(ex.metrics)
            for ex in self._spares:
                out.extend(ex.metrics)
            out.extend(self._retired_metrics)
            return out
