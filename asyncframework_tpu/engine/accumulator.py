"""Accumulators: write-only shared counters updated from task closures.

Parity: ``AccumulatorV2`` (``core/.../util/AccumulatorV2.scala``) --
``LongAccumulator`` / ``DoubleAccumulator`` / ``CollectionAccumulator``,
added to from tasks, read on the driver.  The reference ships per-task
accumulator deltas back in task results and merges on the DAG event loop;
here tasks run in executor threads of the same process, so an accumulator is
a lock-guarded cell the closure captures directly -- same API, and `add` is
thread-safe against concurrent tasks (the semantics Spark only guarantees
via its merge protocol).

Spark's caveat carries over deliberately: a task that is retried or
speculatively duplicated may double-count (only the reference's *internal*
metrics accumulators de-duplicate; user accumulators there double-count on
resubmission too).
"""

from __future__ import annotations

import threading
from typing import Any, Generic, List, TypeVar

T = TypeVar("T")


class Accumulator(Generic[T]):
    """Base: subclasses define ``_zero`` and ``_combine``."""

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._value: T = self._zero()

    def _zero(self) -> T:
        raise NotImplementedError

    def _combine(self, cur: T, update) -> T:
        raise NotImplementedError

    def add(self, update) -> None:
        with self._lock:
            self._value = self._combine(self._value, update)

    def merge(self, other: "Accumulator[T]") -> None:
        """Fold another accumulator in (multi-host: one per host, merged).

        The other's value is snapshotted BEFORE taking our lock: holding
        both would deadlock on self-merge and ABBA-deadlock on concurrent
        cross-merges.
        """
        snapshot = other.value
        with self._lock:
            self._value = self._combine(self._value, snapshot)

    def reset(self) -> None:
        with self._lock:
            self._value = self._zero()

    @property
    def value(self) -> T:
        with self._lock:
            return self._value

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}({self.name!r}, value={self.value!r})"


class LongAccumulator(Accumulator[int]):
    """Sum + count (so ``avg`` works), like the reference's LongAccumulator."""

    def __init__(self, name: str = ""):
        self._count = 0
        super().__init__(name)

    def _zero(self) -> int:
        return 0

    def _combine(self, cur: int, update) -> int:
        return cur + int(update)

    def add(self, update) -> None:
        with self._lock:
            self._value = self._combine(self._value, update)
            self._count += 1

    def merge(self, other: "LongAccumulator") -> None:
        # one acquisition of other's lock: (sum, count) must not tear
        with other._lock:
            v, c = other._value, other._count
        with self._lock:
            self._value += v
            self._count += c

    def reset(self) -> None:
        with self._lock:
            self._value = 0
            self._count = 0

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def avg(self) -> float:
        with self._lock:
            return self._value / self._count if self._count else 0.0


class DoubleAccumulator(Accumulator[float]):
    def _zero(self) -> float:
        return 0.0

    def _combine(self, cur: float, update) -> float:
        return cur + float(update)


class CollectionAccumulator(Accumulator[List[Any]]):
    def _zero(self) -> List[Any]:
        return []

    def _combine(self, cur: List[Any], update) -> List[Any]:
        if isinstance(update, list):
            return cur + update
        return cur + [update]

    def merge(self, other: "Accumulator[List[Any]]") -> None:
        snapshot = list(other.value)
        with self._lock:
            self._value = self._value + snapshot


class MaxAccumulator(Accumulator[float]):
    """Running maximum (handy for staleness/latency high-water marks)."""

    def _zero(self) -> float:
        return float("-inf")

    def _combine(self, cur: float, update) -> float:
        return max(cur, float(update))
