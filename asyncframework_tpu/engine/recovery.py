"""Elastic recovery: re-homing a dead worker's data shard.

Parity: the reference's recovery story is lineage -- a lost executor's cached
partitions are *recomputed* from their parent RDDs on surviving executors
(``DAGScheduler.scala:1326-1400`` resubmission, ``DistributedSuite``'s
"recover from node failures" cases).  The TPU build has no lineage because it
has no lazy transformation graph on the hot path; the equivalent capability
is explicit: a shard whose worker slot is declared dead is re-placed into a
surviving slot's device HBM (from the host copy when one exists -- the
"recompute from source" analog -- or by device-to-device copy of the live
buffer when the dataset was generated on device).

``plan_reassignment`` is the pure policy (balanced round-robin of dead slots
over survivors); ``ShardRecovery`` applies a plan to a ``ShardedDataset`` by
building per-worker *assignment views*: worker slots keep their identity, a
surviving worker simply computes extra shards' gradients in subsequent
rounds.  The solver layer stays oblivious -- it asks ``assignments(wid)`` for
the shard list a worker currently owns.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax

from asyncframework_tpu.data.sharded import Shard, ShardedDataset


@dataclass(frozen=True)
class ReassignmentPlan:
    """dead worker id -> adopting (live) worker id."""

    moves: Dict[int, int]


def plan_reassignment(
    all_workers: Sequence, dead: Sequence[int],
    load: Optional[Dict] = None,
) -> ReassignmentPlan:
    """Round-robin dead workers' shards over survivors, least-loaded first.

    Deterministic: survivors are visited in ascending id order, dead shards
    in ascending id order, so every host computes the same plan.

    ``load`` (optional) is the survivors' CURRENT shard count -- the
    multi-process supervisor re-plans incrementally as membership keeps
    changing, so a survivor that already adopted shards must weigh
    heavier than a fresh one.  Default (None) is the single-shot policy:
    every survivor owns exactly its own shard.  Survivor ids need not be
    worker ints -- the DCN supervisor plans over process tokens.
    """
    dead_set = set(dead)
    survivors = sorted(w for w in all_workers if w not in dead_set)
    if not survivors:
        raise RuntimeError("no surviving workers to adopt shards")
    if load is None:
        load = {w: 1 for w in survivors}  # own shard
    else:
        load = {w: int(load.get(w, 0)) for w in survivors}
    moves: Dict[int, int] = {}
    for d in sorted(dead_set):
        target = min(survivors, key=lambda w: (load[w], w))
        moves[d] = target
        load[target] += 1
    return ReassignmentPlan(moves)


class ShardRecovery:
    """Tracks which worker currently owns which shards; applies plans.

    After ``apply(plan)``, each adopted shard has been re-placed on its new
    owner's device (host re-upload when the dataset has a host copy, else
    device-to-device) and ``assignments(wid)`` lists every shard worker
    ``wid`` now computes per round.
    """

    def __init__(self, ds: ShardedDataset, devices: Sequence):
        self.ds = ds
        self.devices = list(devices)
        self._lock = threading.Lock()
        self._owner: Dict[int, int] = {w: w for w in range(ds.num_workers)}
        # shard_id -> device-resident Shard under its current owner
        self._placed: Dict[int, Shard] = {w: ds.shard(w) for w in range(ds.num_workers)}

    def _device_of(self, wid: int):
        return self.devices[wid % len(self.devices)]

    def apply(self, plan: ReassignmentPlan) -> None:
        for shard_id, new_owner in plan.moves.items():
            self.move_shard(shard_id, new_owner)

    def move_shard(self, shard_id: int, new_owner: int):
        """Re-place one shard on ``new_owner``'s device; returns the new view."""
        with self._lock:
            cur = self._placed[shard_id]
            target_dev = self._device_of(new_owner)
            # jax.device_put from a live device buffer is a device-to-device
            # (or host-bounce) copy; from the host copy it is a fresh upload.
            # Either way the result lives on the adopting worker's device.
            if hasattr(cur, "cols"):  # padded-ELL sparse shard
                from asyncframework_tpu.data.sparse import SparseShard

                moved = SparseShard(
                    worker_id=shard_id,
                    cols=jax.device_put(cur.cols, target_dev),
                    vals=jax.device_put(cur.vals, target_dev),
                    y=jax.device_put(cur.y, target_dev),
                    start=cur.start,
                    size=cur.size,
                )
            else:
                moved = Shard(
                    worker_id=shard_id,
                    X=jax.device_put(cur.X, target_dev),
                    y=jax.device_put(cur.y, target_dev),
                    start=cur.start,
                    size=cur.size,
                )
            self._placed[shard_id] = moved
            self._owner[shard_id] = new_owner
            return moved

    # ------------------------------------------------------------------ views
    def owner(self, shard_id: int) -> int:
        with self._lock:
            return self._owner[shard_id]

    def assignments(self, worker_id: int) -> List[Shard]:
        """Every shard this worker currently computes (own + adopted)."""
        with self._lock:
            return [
                self._placed[sid]
                for sid, own in sorted(self._owner.items())
                if own == worker_id
            ]

    def shard(self, shard_id: int) -> Shard:
        with self._lock:
            return self._placed[shard_id]
