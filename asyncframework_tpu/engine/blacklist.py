"""Worker blacklisting after repeated task failures.

Parity: ``scheduler/BlacklistTracker.scala:50`` -- executors accumulating task
failures inside a time window are excluded from scheduling until the
blacklist entry expires.

TPU mapping: a "worker" is a logical device slot driven by an executor
thread, and the hardware behind it is fixed (the pod is the cluster), so
blacklisting cannot move work to different *hardware*.  What it can do --
and what the reference's tracker really provides -- is (a) stop offering
tasks to a slot whose runtime state is poisoned (wedged XLA stream, leaked
buffers, a straggling host thread) until it is replaced, and (b) force the
replacement: the scheduler swaps in a fresh executor for a blacklisted slot
before the next launch.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional

from asyncframework_tpu.utils.clock import Clock, SystemClock


class BlacklistTracker:
    """Sliding-window failure counting with timed expiry.

    A worker with ``max_failures`` failures inside ``window_ms`` is
    blacklisted until ``timeout_ms`` after its most recent failure
    (``spark.blacklist.timeout`` semantics).  A success clears nothing --
    like the reference, only time heals a blacklisted worker -- but it also
    does not extend the window.
    """

    def __init__(
        self,
        max_failures: int = 2,
        timeout_ms: float = 60_000.0,
        window_ms: Optional[float] = None,
        clock: Optional[Clock] = None,
    ):
        if max_failures < 1:
            raise ValueError("max_failures must be >= 1")
        self.max_failures = max_failures
        self.timeout_ms = timeout_ms
        self.window_ms = window_ms if window_ms is not None else timeout_ms
        self._clock = clock or SystemClock()
        self._lock = threading.Lock()
        self._failures: Dict[int, Deque[float]] = {}

    def clear(self, worker_id: int) -> None:
        """Forget a worker's failures (called after its executor is replaced:
        the fresh executor starts with a clean slate)."""
        with self._lock:
            self._failures.pop(worker_id, None)

    def record_failure(self, worker_id: int) -> None:
        now = self._clock.now_ms()
        with self._lock:
            dq = self._failures.setdefault(worker_id, deque())
            dq.append(now)
            self._prune(dq, now)

    def _prune(self, dq: Deque[float], now: float) -> None:
        while dq and now - dq[0] > self.window_ms:
            dq.popleft()

    def is_blacklisted(self, worker_id: int) -> bool:
        now = self._clock.now_ms()
        with self._lock:
            dq = self._failures.get(worker_id)
            if not dq:
                return False
            self._prune(dq, now)
            if len(dq) < self.max_failures:
                return False
            return now - dq[-1] <= self.timeout_ms

    def blacklisted_workers(self) -> List[int]:
        with self._lock:
            ids = list(self._failures)
        return [wid for wid in ids if self.is_blacklisted(wid)]

    def failure_count(self, worker_id: int) -> int:
        now = self._clock.now_ms()
        with self._lock:
            dq = self._failures.get(worker_id)
            if not dq:
                return 0
            self._prune(dq, now)
            return len(dq)
