"""The job scheduler: non-blocking submission is the core ASYNC mechanism.

Parity (the heart of the reference delta):
- ``DAGScheduler.scala:139-145`` -- ``mode`` (0 sync / 1 async) and
  ``first_iter`` flags, set from user code via ``SparkContext.set_mode``
  (``SparkContext.scala:89-101``).
- ``DAGScheduler.scala:641-663`` -- ``runJob`` blocks on the waiter when
  ``mode==0 || first_iter``, and returns immediately after submission when
  ``mode==1``; per-task results flow through the result handler either way.
- Task retry on failure: ``TaskSetManager`` resubmits a failed task up to
  ``maxTaskFailures`` then aborts the job.

Design deltas: ``mode`` is per-scheduler state settable per submission (not a
process-global), and the first-iteration block is an explicit, documented
warm-up (it is what populates XLA's compile cache here, exactly analogous to
the reference warming its block/broadcast caches).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from asyncframework_tpu.engine.blacklist import BlacklistTracker
from asyncframework_tpu.engine.executor import DeviceExecutor, ExecutorPool
from asyncframework_tpu.engine.job import Job, JobWaiter, TaskSpec
from asyncframework_tpu.utils.clock import Clock, SystemClock

SYNC = 0
ASYNC = 1


class JobScheduler:
    """Submits per-worker tasks to an :class:`ExecutorPool`; owns retry policy.

    One scheduler per training context.  Thread-safe: submissions come from
    the driver thread; status updates arrive on executor threads.
    """

    def __init__(
        self,
        num_workers: int,
        devices: Optional[List] = None,
        max_task_failures: int = 4,
        clock: Optional[Clock] = None,
        pool: Optional[ExecutorPool] = None,
        blacklist: Optional[BlacklistTracker] = None,
    ):
        self.num_workers = num_workers
        self.max_task_failures = max_task_failures
        self._clock = clock or SystemClock()
        self._mode = SYNC
        self._first_iter = True
        self._lock = threading.Lock()
        self._active_jobs: Dict[int, Job] = {}
        # in-flight task registry for resubmission on executor death:
        # worker_id -> list of TaskSpec currently launched there
        self._inflight: Dict[int, List[TaskSpec]] = {}
        # speculation bookkeeping: launch stamps + finished durations per job
        self._launch_ms: Dict[Tuple[int, int], float] = {}
        self._finished_ms: Dict[int, List[float]] = {}
        self._spec_wins = 0  # speculative copies that beat their primary
        self.blacklist = blacklist
        self.pool = pool or ExecutorPool(
            num_workers, self._status_update, devices=devices, clock=self._clock
        )

    @property
    def clock(self) -> Clock:
        return self._clock

    # ------------------------------------------------------------------ mode
    def set_mode(self, mode: int) -> None:
        """Parity: ``SparkContext.set_mode`` -> ``dagScheduler.set_mode``."""
        if mode not in (SYNC, ASYNC):
            raise ValueError(f"mode must be {SYNC} or {ASYNC}, got {mode}")
        self._mode = mode

    def get_mode(self) -> int:
        return self._mode

    # ---------------------------------------------------------------- submit
    def run_job(
        self,
        worker_fns: Dict[int, Callable[[], Any]],
        result_handler: Callable[[int, Any], None],
        timeout: Optional[float] = None,
    ) -> JobWaiter:
        """Submit one task per cohort worker.

        Blocking iff ``mode==SYNC`` or this is the scheduler's first job
        (``DAGScheduler.scala:641-663`` semantics).  Returns the waiter either
        way so sync callers can inspect it and async callers can ignore it.
        """
        job = Job.create(worker_fns, result_handler)
        with self._lock:
            self._active_jobs[job.job_id] = job
        for wid, task in job.tasks.items():
            self._launch(wid, task)
        block = self._mode == SYNC or self._first_iter
        self._first_iter = False
        if block:
            job.waiter.await_result(timeout=timeout)
            with self._lock:
                self._active_jobs.pop(job.job_id, None)
        return job.waiter

    def _launch(self, worker_id: int, task: TaskSpec) -> None:
        with self._lock:
            ex = self.pool.executors[worker_id]
            if not ex.alive:
                ex = self.pool.replace(worker_id)
            elif (
                self.blacklist is not None
                and self.blacklist.is_blacklisted(worker_id)
            ):
                # blacklisted slot: swap in a fresh executor before offering
                # it more work (the TPU analog of scheduling elsewhere); the
                # swap heals the slot, so clear the entry -- without this,
                # every launch in the timeout window would churn executors
                ex = self.pool.replace(worker_id)
                self.blacklist.clear(worker_id)
            else:
                # healthy slot: route to its least-loaded executor (equals
                # the primary unless dynamic allocation added siblings).
                # Pick + enqueue happen atomically under the POOL lock so a
                # concurrent sibling retirement cannot shut the chosen
                # executor down in between (see ExecutorPool.launch_on_slot)
                ex = None
            self._inflight.setdefault(worker_id, []).append(task)
            self._launch_ms[(task.job_id, worker_id)] = self._clock.now_ms()
        if ex is not None:
            ex.launch_task(task)
        else:
            self.pool.launch_on_slot(worker_id, task)

    # -------------------------------------------------------- status updates
    def _status_update(
        self,
        executor: DeviceExecutor,
        task: TaskSpec,
        result: Any,
        exc: Optional[BaseException],
    ) -> None:
        """Runs on the executor thread (Spark's ``statusUpdate`` path)."""
        with self._lock:
            job = self._active_jobs.get(task.job_id)
            if not task.speculative:
                lst = self._inflight.get(task.worker_id, [])
                if task in lst:
                    lst.remove(task)
                start = self._launch_ms.pop((task.job_id, task.worker_id), None)
                # record only while the job is live: a losing primary landing
                # after completion must not resurrect the entry (leak)
                if start is not None and exc is None and job is not None:
                    self._finished_ms.setdefault(task.job_id, []).append(
                        self._clock.now_ms() - start
                    )
        if self.pool.is_spare(executor):
            self.pool.discard_spare(executor)  # one speculative copy, one task
        if task.speculative and exc is not None:
            return  # copy failed; the healthy primary is still running
        if exc is not None and self.blacklist is not None:
            self.blacklist.record_failure(task.worker_id)
        if job is None:
            return  # job already finished/aborted (e.g. sync caller gone)
        if exc is not None and job.waiter.is_claimed(task.worker_id):
            # primary failed after its speculative copy already delivered the
            # result: nothing to retry, and certainly nothing to abort
            return
        if exc is None:
            won = job.waiter.task_succeeded(task.worker_id, result)
            if task.speculative and won:
                # the copy beat the (straggling) primary -- the observable
                # payoff of TaskSetManager-style speculation
                with self._lock:
                    self._spec_wins += 1
            if job.waiter.completed:
                with self._lock:
                    self._active_jobs.pop(task.job_id, None)
                    self._finished_ms.pop(task.job_id, None)
        else:
            self._retry_or_abort(job, task, exc)

    def _retry_or_abort(self, job: Job, task: TaskSpec, exc: BaseException) -> None:
        if task.attempt + 1 >= self.max_task_failures:
            job.waiter.job_failed(
                RuntimeError(
                    f"task for worker {task.worker_id} in job {job.job_id} failed "
                    f"{task.attempt + 1} times; aborting job"
                )
            )
            with self._lock:
                self._active_jobs.pop(job.job_id, None)
                self._finished_ms.pop(job.job_id, None)
            return
        retry = TaskSpec(
            job_id=task.job_id,
            worker_id=task.worker_id,
            fn=task.fn,
            attempt=task.attempt + 1,
        )
        self._launch(task.worker_id, retry)

    # ------------------------------------------------------------ speculation
    def speculative_wins(self) -> int:
        """Speculative copies whose result claimed the slot (copy beat the
        primary) -- the observable payoff of speculation."""
        with self._lock:
            return self._spec_wins

    def speculation_snapshot(self) -> Dict[int, Tuple[List[float], Dict[int, float]]]:
        """Per active job: (finished task durations, running task elapsed).

        Consumed by :class:`~asyncframework_tpu.engine.speculation.SpeculationMonitor`.
        """
        now = self._clock.now_ms()
        with self._lock:
            out: Dict[int, Tuple[List[float], Dict[int, float]]] = {}
            for job_id in self._active_jobs:
                finished = list(self._finished_ms.get(job_id, []))
                running = {
                    wid: now - t
                    for (jid, wid), t in self._launch_ms.items()
                    if jid == job_id
                }
                out[job_id] = (finished, running)
            return out

    def speculative_launch(self, job_id: int, worker_id: int) -> bool:
        """Launch a copy of a running task on a spare executor (same device
        slot, fresh host thread).  First completion wins -- the
        :class:`JobWaiter` drops the loser.  Returns False when the task
        already finished (nothing to speculate)."""
        with self._lock:
            job = self._active_jobs.get(job_id)
            if job is None:
                return False
            orig = next(
                (t for t in self._inflight.get(worker_id, []) if t.job_id == job_id),
                None,
            )
            if orig is None:
                return False
        copy = TaskSpec(
            job_id=job_id, worker_id=worker_id, fn=orig.fn,
            attempt=orig.attempt, speculative=True,
        )
        spare = self.pool.spawn_spare(worker_id)
        spare.launch_task(copy)
        return True

    # ------------------------------------------------------- failure recovery
    def on_executor_lost(self, worker_id: int) -> None:
        """Resubmit every in-flight task of a dead worker on a replacement.

        Parity: ``DAGScheduler`` resubmitting tasks on executor loss; invoked
        by the heartbeat monitor (engine/heartbeat.py).
        """
        with self._lock:
            lost = self._inflight.pop(worker_id, [])
        self.pool.replace(worker_id)
        for task in lost:
            with self._lock:
                active = self._active_jobs.get(task.job_id)
            if active is not None and active.waiter.is_claimed(task.worker_id):
                with self._lock:
                    # nothing will relaunch or report this task: drop its
                    # launch stamp or speculation_snapshot sees a phantom
                    # forever-running task
                    self._launch_ms.pop((task.job_id, task.worker_id), None)
                continue  # a speculative copy already delivered this result
            retry = TaskSpec(
                job_id=task.job_id,
                worker_id=task.worker_id,
                fn=task.fn,
                attempt=task.attempt + 1,
            )
            if retry.attempt >= self.max_task_failures:
                with self._lock:
                    job = self._active_jobs.pop(task.job_id, None)
                    self._finished_ms.pop(task.job_id, None)
                if job is not None:
                    job.waiter.job_failed(
                        RuntimeError(
                            f"worker {worker_id} lost with task at max attempts"
                        )
                    )
            else:
                self._launch(worker_id, retry)

    def on_sibling_lost(self, worker_id: int, queued, running) -> None:
        """Resubmit a failed dynamic-allocation sibling's own tasks.

        ``queued`` never started: relaunch at the SAME attempt.  ``running``
        died mid-task: bump its attempt (one real failure), abort the job
        at ``max_task_failures`` exactly like the slot-loss path.  The
        healthy primary's in-flight tasks are untouched.
        """
        # drop the sibling's entries from the in-flight registry first
        # (identity match): _launch re-registers each relaunch, and a stale
        # duplicate would look forever-running to the speculation monitor
        # and get re-executed on a later primary loss
        with self._lock:
            gone = {id(t) for t in queued}
            if running is not None:
                gone.add(id(running))
            inflight = self._inflight.get(worker_id, [])
            self._inflight[worker_id] = [
                t for t in inflight if id(t) not in gone
            ]
        for task in queued:
            self._launch(worker_id, task)
        if running is None:
            return
        with self._lock:
            active = self._active_jobs.get(running.job_id)
        if active is not None and active.waiter.is_claimed(running.worker_id):
            with self._lock:
                self._launch_ms.pop(
                    (running.job_id, running.worker_id), None
                )
            return  # another copy already delivered this result
        retry = TaskSpec(
            job_id=running.job_id,
            worker_id=running.worker_id,
            fn=running.fn,
            attempt=running.attempt + 1,
        )
        if retry.attempt >= self.max_task_failures:
            with self._lock:
                job = self._active_jobs.pop(running.job_id, None)
                self._finished_ms.pop(running.job_id, None)
            if job is not None:
                job.waiter.job_failed(
                    RuntimeError(
                        f"sibling on slot {worker_id} lost with task at "
                        "max attempts"
                    )
                )
        else:
            self._launch(worker_id, retry)

    def shutdown(self) -> None:
        self.pool.shutdown()
