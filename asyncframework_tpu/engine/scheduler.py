"""The job scheduler: non-blocking submission is the core ASYNC mechanism.

Parity (the heart of the reference delta):
- ``DAGScheduler.scala:139-145`` -- ``mode`` (0 sync / 1 async) and
  ``first_iter`` flags, set from user code via ``SparkContext.set_mode``
  (``SparkContext.scala:89-101``).
- ``DAGScheduler.scala:641-663`` -- ``runJob`` blocks on the waiter when
  ``mode==0 || first_iter``, and returns immediately after submission when
  ``mode==1``; per-task results flow through the result handler either way.
- Task retry on failure: ``TaskSetManager`` resubmits a failed task up to
  ``maxTaskFailures`` then aborts the job.

Design deltas: ``mode`` is per-scheduler state settable per submission (not a
process-global), and the first-iteration block is an explicit, documented
warm-up (it is what populates XLA's compile cache here, exactly analogous to
the reference warming its block/broadcast caches).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from asyncframework_tpu.engine.executor import DeviceExecutor, ExecutorPool
from asyncframework_tpu.engine.job import Job, JobWaiter, TaskSpec
from asyncframework_tpu.utils.clock import Clock, SystemClock

SYNC = 0
ASYNC = 1


class JobScheduler:
    """Submits per-worker tasks to an :class:`ExecutorPool`; owns retry policy.

    One scheduler per training context.  Thread-safe: submissions come from
    the driver thread; status updates arrive on executor threads.
    """

    def __init__(
        self,
        num_workers: int,
        devices: Optional[List] = None,
        max_task_failures: int = 4,
        clock: Optional[Clock] = None,
        pool: Optional[ExecutorPool] = None,
    ):
        self.num_workers = num_workers
        self.max_task_failures = max_task_failures
        self._clock = clock or SystemClock()
        self._mode = SYNC
        self._first_iter = True
        self._lock = threading.Lock()
        self._active_jobs: Dict[int, Job] = {}
        # in-flight task registry for resubmission on executor death:
        # worker_id -> list of TaskSpec currently launched there
        self._inflight: Dict[int, List[TaskSpec]] = {}
        self.pool = pool or ExecutorPool(
            num_workers, self._status_update, devices=devices, clock=self._clock
        )

    @property
    def clock(self) -> Clock:
        return self._clock

    # ------------------------------------------------------------------ mode
    def set_mode(self, mode: int) -> None:
        """Parity: ``SparkContext.set_mode`` -> ``dagScheduler.set_mode``."""
        if mode not in (SYNC, ASYNC):
            raise ValueError(f"mode must be {SYNC} or {ASYNC}, got {mode}")
        self._mode = mode

    def get_mode(self) -> int:
        return self._mode

    # ---------------------------------------------------------------- submit
    def run_job(
        self,
        worker_fns: Dict[int, Callable[[], Any]],
        result_handler: Callable[[int, Any], None],
        timeout: Optional[float] = None,
    ) -> JobWaiter:
        """Submit one task per cohort worker.

        Blocking iff ``mode==SYNC`` or this is the scheduler's first job
        (``DAGScheduler.scala:641-663`` semantics).  Returns the waiter either
        way so sync callers can inspect it and async callers can ignore it.
        """
        job = Job.create(worker_fns, result_handler)
        with self._lock:
            self._active_jobs[job.job_id] = job
        for wid, task in job.tasks.items():
            self._launch(wid, task)
        block = self._mode == SYNC or self._first_iter
        self._first_iter = False
        if block:
            job.waiter.await_result(timeout=timeout)
            with self._lock:
                self._active_jobs.pop(job.job_id, None)
        return job.waiter

    def _launch(self, worker_id: int, task: TaskSpec) -> None:
        with self._lock:
            ex = self.pool.executors[worker_id]
            if not ex.alive:
                ex = self.pool.replace(worker_id)
            self._inflight.setdefault(worker_id, []).append(task)
        ex.launch_task(task)

    # -------------------------------------------------------- status updates
    def _status_update(
        self,
        executor: DeviceExecutor,
        task: TaskSpec,
        result: Any,
        exc: Optional[BaseException],
    ) -> None:
        """Runs on the executor thread (Spark's ``statusUpdate`` path)."""
        with self._lock:
            lst = self._inflight.get(task.worker_id, [])
            if task in lst:
                lst.remove(task)
            job = self._active_jobs.get(task.job_id)
        if job is None:
            return  # job already finished/aborted (e.g. sync caller gone)
        if exc is None:
            job.waiter.task_succeeded(task.worker_id, result)
            if job.waiter.completed:
                with self._lock:
                    self._active_jobs.pop(task.job_id, None)
        else:
            self._retry_or_abort(job, task, exc)

    def _retry_or_abort(self, job: Job, task: TaskSpec, exc: BaseException) -> None:
        if task.attempt + 1 >= self.max_task_failures:
            job.waiter.job_failed(
                RuntimeError(
                    f"task for worker {task.worker_id} in job {job.job_id} failed "
                    f"{task.attempt + 1} times; aborting job"
                )
            )
            with self._lock:
                self._active_jobs.pop(job.job_id, None)
            return
        retry = TaskSpec(
            job_id=task.job_id,
            worker_id=task.worker_id,
            fn=task.fn,
            attempt=task.attempt + 1,
        )
        self._launch(task.worker_id, retry)

    # ------------------------------------------------------- failure recovery
    def on_executor_lost(self, worker_id: int) -> None:
        """Resubmit every in-flight task of a dead worker on a replacement.

        Parity: ``DAGScheduler`` resubmitting tasks on executor loss; invoked
        by the heartbeat monitor (engine/heartbeat.py).
        """
        with self._lock:
            lost = self._inflight.pop(worker_id, [])
        self.pool.replace(worker_id)
        for task in lost:
            retry = TaskSpec(
                job_id=task.job_id,
                worker_id=task.worker_id,
                fn=task.fn,
                attempt=task.attempt + 1,
            )
            if retry.attempt >= self.max_task_failures:
                with self._lock:
                    job = self._active_jobs.pop(task.job_id, None)
                if job is not None:
                    job.waiter.job_failed(
                        RuntimeError(
                            f"worker {worker_id} lost with task at max attempts"
                        )
                    )
            else:
                self._launch(worker_id, retry)

    def shutdown(self) -> None:
        self.pool.shutdown()
