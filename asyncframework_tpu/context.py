"""Driver-side hub for asynchronous result streaming.

Reference parity (studied, not copied):
- ``AsyncContext``  ~ ``core/.../rdd/ASYNCcontext.scala:14-81`` -- blocking
  result queue, worker-state table, logical clock, consumer API.
- ``WorkerState``   ~ ``core/.../rdd/workerState.scala:14-87`` -- per-worker
  staleness / average task time / availability / task count, plus table-wide
  aggregates ``available_workers`` and ``max_staleness``.
- ``PartialResult`` ~ ``core/.../rdd/RDDPartialRes.scala:13-37`` -- immutable
  (result, staleness, batch size, worker id) record.

Design deltas from the reference (deliberate, TPU-first):
- The reference mutates an unsynchronized HashMap from the DAG-scheduler event
  loop while two driver threads read it (a benign race it tolerates).  Here the
  state table is guarded by a single lock and the logical clock is atomic;
  semantics are identical but defined.
- The "result" payload is opaque to this layer: it may be a host numpy array or
  a ``jax.Array`` still resident in device HBM (the updater decides when --
  and whether -- to bring it to host).  This is what makes the queue a
  device-to-host streaming channel rather than an RPC deserialization point.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, Iterator, Optional, TypeVar

T = TypeVar("T")


@dataclass(frozen=True)
class PartialResult(Generic[T]):
    """Immutable record for one worker's streamed partial result.

    Parity: ``RDDPartialRes`` -- (data, ts=staleness, recs=batch size, id).
    """

    data: T
    staleness: int
    batch_size: int
    worker_id: int

    # Reference getter names, kept for drop-in familiarity.
    def get_task_result(self) -> T:
        return self.data

    def get_staleness(self) -> int:
        return self.staleness

    def get_batch_size(self) -> int:
        return self.batch_size

    def get_worker_id(self) -> int:
        return self.worker_id


class WorkerState:
    """Mutable per-worker state: staleness, avg task time, availability.

    Parity: ``workerState.scala`` fields ``staleness`` / ``averageTaskTime`` /
    ``availability`` / ``numTasks`` and the table-scanning aggregates
    ``getAvailableWorkers`` / ``getMaxStaleness`` (which in the reference scan
    ``AC.STAT``; here they live on :class:`AsyncContext` where they belong,
    with back-compat delegating methods kept on the state object).
    """

    __slots__ = ("_ctx", "staleness", "average_task_time", "available", "num_tasks")

    def __init__(
        self,
        ctx: "AsyncContext",
        staleness: int = 0,
        average_task_time: float = 0.0,
        available: bool = False,
    ):
        self._ctx = ctx
        self.staleness = staleness
        self.average_task_time = average_task_time
        self.available = available
        self.num_tasks = 0

    def update_num_tasks(self, n: int) -> None:
        self.num_tasks += n

    # Aggregates delegate to the owning context (single source of truth).
    def get_available_workers(self) -> int:
        return self._ctx.available_workers()

    def get_max_staleness(self) -> int:
        return self._ctx.max_staleness()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"WorkerState(staleness={self.staleness}, "
            f"avg_ms={self.average_task_time:.2f}, available={self.available}, "
            f"num_tasks={self.num_tasks})"
        )


class AsyncContext(Generic[T]):
    """The driver-side hub shared by the submitter and updater threads.

    Producers (device-executor completion callbacks) ``put`` results; the
    consumer (updater thread) drains with :meth:`collect` /
    :meth:`collect_all`.  A logical clock counts merged gradients; staleness of
    a result is ``clock_at_completion - clock_at_submit``.

    Parity: ``ASYNCcontext.scala`` -- ``ResultList`` (LinkedBlockingQueue),
    ``STAT`` (HashMap[Int, workerState]), ``CurrentTime`` / ``add2currentTime``
    / ``getCurrentTime``, ``ASYNCcollect`` / ``ASYNCcollectAll`` / ``getSize``
    / ``hasNext``, ``setLastTime`` / ``isOld``.
    """

    def __init__(self) -> None:
        self._results: "queue.Queue[PartialResult[T]]" = queue.Queue()
        self._stat: Dict[int, WorkerState] = {}
        self._lock = threading.RLock()
        self._clock = 0
        self._last_time = -(2**31)
        self._record_stat = False

    # ------------------------------------------------------------------ clock
    def set_current_time(self, t: int) -> None:
        with self._lock:
            self._clock = t

    def add_to_current_time(self, dt: int = 1) -> None:
        with self._lock:
            self._clock += dt

    def get_current_time(self) -> int:
        with self._lock:
            return self._clock

    def set_last_time(self, t: int) -> None:
        with self._lock:
            self._last_time = t

    def is_old(self) -> bool:
        """True when no new gradient has arrived since the last submit stamp."""
        with self._lock:
            return self._clock == self._last_time

    def set_record_stat(self, b: bool) -> None:
        self._record_stat = b

    def get_record_stat(self) -> bool:
        return self._record_stat

    # ------------------------------------------------------------ result queue
    def put(self, result: PartialResult[T]) -> None:
        self._results.put(result)

    def collect(self, timeout: Optional[float] = None) -> T:
        """Blocking take of the next task result (payload only)."""
        return self._results.get(timeout=timeout).data

    def collect_all(self, timeout: Optional[float] = None) -> PartialResult[T]:
        """Blocking take of the next full :class:`PartialResult`."""
        return self._results.get(timeout=timeout)

    def size(self) -> int:
        return self._results.qsize()

    def has_next(self) -> bool:
        return not self._results.empty()

    # -------------------------------------------------------------- STAT table
    def get_state(self, worker_id: int) -> Optional[WorkerState]:
        with self._lock:
            return self._stat.get(worker_id)

    def get_or_create_state(self, worker_id: int) -> WorkerState:
        with self._lock:
            ws = self._stat.get(worker_id)
            if ws is None:
                ws = WorkerState(self)
                self._stat[worker_id] = ws
            return ws

    def set_state(self, worker_id: int, state: WorkerState) -> None:
        with self._lock:
            self._stat[worker_id] = state

    def states(self) -> Dict[int, WorkerState]:
        """Snapshot copy of the state table (safe to iterate)."""
        with self._lock:
            return dict(self._stat)

    def num_workers_tracked(self) -> int:
        with self._lock:
            return len(self._stat)

    def mark_busy(self, worker_ids) -> None:
        """Mark a cohort unavailable before dispatch.

        Parity: the pre-submit loop in ``RDD.ASYNCreduce``
        (``rdd/RDD.scala:1136-1142``) setting availability=false for every
        selected partition.
        """
        with self._lock:
            for wid in worker_ids:
                self.get_or_create_state(wid).available = False

    def merge_result(
        self,
        worker_id: int,
        data: T,
        submit_clock: int,
        elapsed_ms: float,
        batch_size: int,
    ) -> PartialResult[T]:
        """Record a finished task: push result, update STAT, bump the clock.

        Parity: the ``mergeResult`` closure in ``RDD.ASYNCreduce``
        (``rdd/RDD.scala:1144-1165``): staleness = clock_now - submit_clock;
        per-worker average task time = elapsed / (num_tasks + 1); worker
        becomes available; logical clock += 1.
        """
        with self._lock:
            staleness = self._clock - submit_clock
            ws = self.get_or_create_state(worker_id)
            # Mutate in place (never replace) so references held by other
            # threads observe the update -- a deliberate tightening of the
            # reference, which installs a fresh workerState object per merge.
            # Deliberate delta: average_task_time is a true running mean of
            # task latencies; the reference's fresh-object dance makes its
            # "average" just elapsed/2 after the first task
            # (rdd/RDD.scala:1150-1156 reads the previous state's numTasks,
            # which is always 1).
            ws.staleness = staleness
            ws.average_task_time = (
                ws.average_task_time * ws.num_tasks + elapsed_ms
            ) / (ws.num_tasks + 1)
            ws.available = True
            ws.num_tasks += 1
            res = PartialResult(data, staleness, batch_size, worker_id)
            self._clock += 1
        self._results.put(res)
        return res

    def mark_available(self, worker_id: int) -> None:
        """Empty-result path of ``mergeResult`` (worker freed, no clock bump)."""
        with self._lock:
            self.get_or_create_state(worker_id).available = True

    # -------------------------------------------------------------- aggregates
    def available_workers(self) -> int:
        """Parity: ``workerState.getAvailableWorkers`` scanning ``AC.STAT``."""
        with self._lock:
            return sum(1 for ws in self._stat.values() if ws.available)

    def max_staleness(self) -> int:
        """Parity: ``workerState.getMaxStaleness`` (returns -1 when empty)."""
        with self._lock:
            n = -1
            for ws in self._stat.values():
                if ws.staleness > n:
                    n = ws.staleness
            return n

    def drain(self) -> Iterator[PartialResult[T]]:
        """Non-blocking drain of everything currently queued."""
        while True:
            try:
                yield self._results.get_nowait()
            except queue.Empty:
                return
