"""Streaming regression: online linear/logistic models over DStreams.

Parity (studied, not copied): ``mllib/src/main/scala/org/apache/spark/
mllib/regression/StreamingLinearRegressionWithSGD.scala`` and
``classification/StreamingLogisticRegressionWithSGD.scala`` (both built on
``StreamingLinearAlgorithm.scala``) -- every micro-batch runs a few SGD
steps FROM the current weights (warm start), so the model tracks drift;
``predictOn`` uses the model as of each interval.

TPU mapping: each batch update is one jitted scan of SGD steps (the same
fused program :class:`~asyncframework_tpu.ml.optimization.GradientDescent`
compiles); there is no per-batch cluster job to schedule.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from asyncframework_tpu.ml.gradient import (
    LeastSquaresGradient,
    LogisticGradient,
)
from asyncframework_tpu.ml.models import LinearModel, LogisticRegressionModel
from asyncframework_tpu.ml.optimization import GradientDescent
from asyncframework_tpu.ml.updater import SimpleUpdater


def _bucket_pad(X: np.ndarray, y: np.ndarray):
    """Pad a micro-batch's rows to the next power of two (>= 16).

    Streams deliver variable-size batches; the compiled SGD scan caches per
    exact shape, so unbucketed sizes would recompile nearly every interval
    and grow the executable cache without bound.  Zero rows with zero
    labels contribute zero gradient; they do dilute the count
    normalization by at most 2x, a constant absorbed into step-size tuning
    (documented trade: bounded compile cache over exact per-batch scale).
    """
    n = X.shape[0]
    target = 16
    while target < n:
        target *= 2
    if target == n:
        return X, y
    pad = target - n
    return (
        np.pad(X, ((0, pad), (0, 0))),
        np.pad(y, (0, pad)),
    )


class _StreamingGLM:
    """Shared machinery: warm-started per-batch SGD (the
    ``StreamingLinearAlgorithm.trainOn`` loop)."""

    def __init__(
        self,
        gradient,
        step_size: float = 0.1,
        num_iterations: int = 5,
        mini_batch_fraction: float = 1.0,
        seed: int = 0,
    ):
        self._opt = GradientDescent(
            gradient=gradient,
            updater=SimpleUpdater(),
            step_size=step_size,
            num_iterations=num_iterations,
            mini_batch_fraction=mini_batch_fraction,
            seed=seed,
        )
        self.weights: Optional[np.ndarray] = None
        self._batches_seen = 0

    def set_initial_weights(self, w) -> "_StreamingGLM":
        self.weights = np.asarray(w, np.float32)
        return self

    def latest_weights(self) -> np.ndarray:
        if self.weights is None:
            raise ValueError("no data seen yet and no initial weights set")
        return self.weights

    def _update(self, batch) -> "_StreamingGLM":
        """One micro-batch: ``num_iterations`` SGD steps from the current
        weights (``trainOn`` parity: warm start, never a reset)."""
        X, y = batch
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32)
        if X.ndim != 2:
            # silent drop would point the user at the stream plumbing
            # instead of the shape bug
            raise ValueError(
                f"streaming batches must be (n, d) feature matrices; got "
                f"shape {X.shape}"
            )
        if X.shape[0] == 0:
            return self
        if self.weights is None:
            self.weights = np.zeros(X.shape[1], np.float32)
        X, y = _bucket_pad(X, y)
        # vary the sampling seed per batch, deterministically
        self._opt.seed = self._opt.seed + 1
        w, _losses = self._opt.optimize(X, y, w0=self.weights)
        self.weights = np.asarray(w, np.float32)
        self._batches_seen += 1
        return self

    def train_on(self, dstream) -> "_StreamingGLM":
        """Update from every interval's ``(X, y)`` batch (``trainOn``)."""
        dstream.foreach_batch(lambda _t, b: self._update(b))
        return self

    def predict_on(self, dstream):
        """Per-interval predictions with the model AS OF the interval
        (``predictOn``); batches are feature matrices.  Like the
        reference's ``StreamingLinearAlgorithm.predictOn``, the model must
        be initialized (trained or ``set_initial_weights``) at CALL time
        -- failing later would kill the stream's job-generator thread."""
        if self.weights is None:
            raise ValueError(
                "model not initialized: train_on a batch first or call "
                "set_initial_weights before predict_on"
            )
        return dstream.map_batch(
            lambda X: self._predict(np.asarray(X, np.float32))
        )


class StreamingLinearRegression(_StreamingGLM):
    """``StreamingLinearRegressionWithSGD`` analog."""

    def __init__(self, step_size: float = 0.1, num_iterations: int = 5,
                 mini_batch_fraction: float = 1.0, seed: int = 0):
        super().__init__(
            LeastSquaresGradient(), step_size, num_iterations,
            mini_batch_fraction, seed,
        )

    def latest_model(self) -> LinearModel:
        """``latestModel`` parity: the batch model object (persistable via
        ``ml.persistence``, prediction logic defined ONCE there)."""
        return LinearModel(
            weights=self.latest_weights(), intercept=0.0,
            loss_history=np.asarray([]), weight_history=[],
        )

    def _predict(self, X: np.ndarray) -> np.ndarray:
        return self.latest_model().predict(X)


class StreamingLogisticRegression(_StreamingGLM):
    """``StreamingLogisticRegressionWithSGD`` analog; predictions are
    class labels in {0, 1} (the reference thresholds at 0.5)."""

    def __init__(self, step_size: float = 0.5, num_iterations: int = 5,
                 mini_batch_fraction: float = 1.0, seed: int = 0):
        super().__init__(
            LogisticGradient(), step_size, num_iterations,
            mini_batch_fraction, seed,
        )

    def latest_model(self) -> LogisticRegressionModel:
        """``latestModel`` parity (see StreamingLinearRegression)."""
        return LogisticRegressionModel(
            weights=self.latest_weights(), intercept=0.0,
            loss_history=np.asarray([]), weight_history=[],
        )

    def predict_probability(self, X) -> np.ndarray:
        return np.asarray(
            self.latest_model().predict_proba(np.asarray(X, np.float32))
        )

    def _predict(self, X: np.ndarray) -> np.ndarray:
        return np.asarray(
            self.latest_model().predict(np.asarray(X, np.float32))
        ).astype(np.int32)
