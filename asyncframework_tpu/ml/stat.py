"""Column statistics and correlation.

Parity: MLlib ``stat/`` -- ``Statistics.colStats`` returning a
``MultivariateStatisticalSummary`` (mean, variance, count, numNonzeros,
max, min) and ``Statistics.corr`` (Pearson / Spearman).  One jitted pass
computes every summary moment; the same pass runs ``psum``-reduced over a
mesh axis for sharded data (the reference tree-aggregates per partition).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.parallel.mesh import resolve_shard_map


@dataclass(frozen=True)
class ColStats:
    """MultivariateStatisticalSummary parity (corrected sample variance)."""

    count: int
    mean: np.ndarray
    variance: np.ndarray
    num_nonzeros: np.ndarray
    max: np.ndarray
    min: np.ndarray


@jax.jit
def _moments(X):
    n = X.shape[0]
    s1 = X.sum(axis=0)
    s2 = (X * X).sum(axis=0)
    nnz = (X != 0).sum(axis=0)
    return n, s1, s2, nnz, X.max(axis=0), X.min(axis=0)


def col_stats(X, mesh: Optional[Mesh] = None, axis: str = "dp") -> ColStats:
    """Column summary of ``X`` (n, d); with ``mesh``, X is sharded on rows
    over ``axis`` and the moments are psum-combined over ICI."""
    X = jnp.asarray(X, jnp.float32)
    if mesh is None:
        n, s1, s2, nnz, mx, mn = _moments(X)
    else:
        @partial(
            resolve_shard_map(),
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=(P(), P(None), P(None), P(None), P(None), P(None)),
        )
        def dist(Xl):
            nl = Xl.shape[0]
            out = (
                jnp.asarray(nl, jnp.int32),
                Xl.sum(axis=0),
                (Xl * Xl).sum(axis=0),
                (Xl != 0).sum(axis=0),
                Xl.max(axis=0),
                Xl.min(axis=0),
            )
            n = jax.lax.psum(out[0], axis)
            s1 = jax.lax.psum(out[1], axis)
            s2 = jax.lax.psum(out[2], axis)
            nnz = jax.lax.psum(out[3], axis)
            mx = jax.lax.pmax(out[4], axis)
            mn = jax.lax.pmin(out[5], axis)
            return n, s1, s2, nnz, mx, mn

        n, s1, s2, nnz, mx, mn = dist(X)
    n = int(n)
    mean = np.asarray(s1) / n
    # corrected sample variance from the moments
    var = (np.asarray(s2) - n * mean**2) / max(n - 1, 1)
    return ColStats(
        count=n,
        mean=mean,
        variance=np.maximum(var, 0.0),
        num_nonzeros=np.asarray(nnz),
        max=np.asarray(mx),
        min=np.asarray(mn),
    )


@jax.jit
def _pearson(X):
    Xc = X - X.mean(axis=0)
    cov = Xc.T @ Xc
    sd = jnp.sqrt(jnp.diag(cov))
    denom = jnp.outer(sd, sd)
    return jnp.where(denom > 0, cov / denom, 0.0)


def _average_ranks(col: np.ndarray) -> np.ndarray:
    """Average ranks with tie handling (Spearman's requirement)."""
    order = np.argsort(col, kind="stable")
    ranks = np.empty(len(col), np.float64)
    sorted_vals = col[order]
    i = 0
    while i < len(col):
        j = i
        while j + 1 < len(col) and sorted_vals[j + 1] == sorted_vals[i]:
            j += 1
        ranks[order[i:j + 1]] = (i + j) / 2.0 + 1.0
        i = j + 1
    return ranks


def corr(X, method: str = "pearson") -> np.ndarray:
    """(d, d) correlation matrix of the columns of ``X``.

    Pearson runs fully on device (one centered gram matrix); Spearman ranks
    on the host (tie-averaged ranks are data-dependent control flow) and
    then reuses the device Pearson on the ranks, mirroring how the
    reference computes Spearman as Pearson-of-ranks.
    """
    if method == "pearson":
        return np.asarray(_pearson(jnp.asarray(X, jnp.float32)))
    if method == "spearman":
        Xh = np.asarray(X)
        R = np.column_stack(
            [_average_ranks(Xh[:, j]) for j in range(Xh.shape[1])]
        )
        return np.asarray(_pearson(jnp.asarray(R, jnp.float32)))
    raise ValueError(f"unknown correlation method {method!r}")


@dataclass(frozen=True)
class ChiSqTestResult:
    """Parity: ``mllib/.../stat/test/ChiSqTest.scala`` result fields."""

    statistic: float
    degrees_of_freedom: int
    p_value: float
    method: str = "pearson"


def _chi2_sf(x: float, df: int) -> float:
    """Chi-square survival function = regularized UPPER incomplete gamma
    (gammaincc directly: ``1 - gammainc`` would lose every significant
    digit once p drops below float32 epsilon)."""
    from jax.scipy.special import gammaincc

    if x <= 0:
        return 1.0
    return float(gammaincc(df / 2.0, x / 2.0))


def chi_sq_test(observed, expected=None) -> ChiSqTestResult:
    """Pearson goodness-of-fit test of an observed frequency vector against
    ``expected`` (uniform when omitted), like ``Statistics.chiSqTest(vec)``."""
    obs = jnp.asarray(observed, jnp.float32)
    if obs.ndim != 1:
        raise ValueError("observed must be 1-d; use chi_sq_test_matrix")
    n = obs.shape[0]
    if expected is None:
        exp = jnp.full(n, jnp.sum(obs) / n)
    else:
        exp = jnp.asarray(expected, jnp.float32)
        # scale expected to the observed total (reference semantics)
        exp = exp * (jnp.sum(obs) / jnp.sum(exp))
    if bool(jnp.any(exp <= 0)):
        # the reference's ChiSqTest raises on non-positive expected
        # frequencies; silent inf/nan would poison downstream comparisons
        raise ValueError("chi_sq_test: expected frequencies must be > 0")
    stat = float(jnp.sum((obs - exp) ** 2 / exp))
    df = int(n - 1)
    return ChiSqTestResult(stat, df, _chi2_sf(stat, df))


def chi_sq_test_matrix(counts) -> ChiSqTestResult:
    """Pearson independence test on a contingency matrix, like
    ``Statistics.chiSqTest(Matrix)``: expected = outer(row, col) / total."""
    m = jnp.asarray(counts, jnp.float32)
    if m.ndim != 2:
        raise ValueError("counts must be a 2-d contingency matrix")
    total = jnp.sum(m)
    exp = jnp.outer(jnp.sum(m, axis=1), jnp.sum(m, axis=0)) / total
    if bool(jnp.any(exp <= 0)):
        raise ValueError(
            "chi_sq_test_matrix: every row and column must have a "
            "positive total (empty rows/columns make the test undefined)"
        )
    stat = float(jnp.sum((m - exp) ** 2 / exp))
    df = int((m.shape[0] - 1) * (m.shape[1] - 1))
    return ChiSqTestResult(stat, df, _chi2_sf(stat, df))


@dataclass(frozen=True)
class KSTestResult:
    """``Statistics.kolmogorovSmirnovTest`` result fields."""

    statistic: float
    p_value: float
    null_hypothesis: str = "sample follows the theoretical distribution"


def ks_test(sample, cdf="norm", *params) -> KSTestResult:
    """One-sample two-sided Kolmogorov-Smirnov test.

    Parity: ``mllib/.../stat/test/KolmogorovSmirnovTest.scala`` -- D is the
    max deviation between the empirical CDF and the theoretical one
    ('norm' with optional (mean, std), or any callable CDF); the p-value
    uses the asymptotic Kolmogorov series like the reference's commons-math.
    """
    x = np.sort(np.asarray(sample, np.float64))
    n = len(x)
    if n == 0:
        raise ValueError("empty sample")
    if callable(cdf):
        f = np.asarray(cdf(x), np.float64)
    elif cdf == "norm":
        mu = params[0] if len(params) > 0 else 0.0
        sd = params[1] if len(params) > 1 else 1.0
        # float64 on host: the statistic is a max of CDF deviations, and
        # float32 CDF rounding would cap its accuracy around 1e-7
        import math

        erf = np.frompyfunc(math.erf, 1, 1)
        z = (x - mu) / (sd * math.sqrt(2.0))
        f = 0.5 * (1.0 + erf(z).astype(np.float64))
    else:
        raise ValueError("cdf must be 'norm' or a callable")
    ecdf_hi = np.arange(1, n + 1) / n
    ecdf_lo = np.arange(0, n) / n
    d = float(max(np.max(ecdf_hi - f), np.max(f - ecdf_lo)))
    # asymptotic Kolmogorov distribution: Q(sqrt(n) d)
    t = (np.sqrt(n) + 0.12 + 0.11 / np.sqrt(n)) * d
    s = 0.0
    for j in range(1, 101):
        s += 2.0 * (-1.0) ** (j - 1) * np.exp(-2.0 * j * j * t * t)
    return KSTestResult(statistic=d, p_value=float(min(max(s, 0.0), 1.0)))


class KernelDensity:
    """Gaussian kernel density estimation.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/stat/
    KernelDensity.scala`` -- density at each query point is the mean of
    normal kernels centered at the samples.  The reference aggregates the
    (n_samples x n_points) kernel grid with a fold over the RDD; here the
    grid is ONE broadcasted device op (samples on rows, query points on
    columns) reduced along the sample axis.
    """

    def __init__(self, bandwidth: float = 1.0):
        if bandwidth <= 0:
            raise ValueError("bandwidth must be > 0")
        self.bandwidth = float(bandwidth)
        self._samples = None

    def set_sample(self, samples) -> "KernelDensity":
        self._samples = jnp.asarray(np.asarray(samples), jnp.float32).ravel()
        return self

    def estimate(self, points) -> np.ndarray:
        if self._samples is None:
            raise ValueError("call set_sample first")
        pts = jnp.asarray(np.asarray(points), jnp.float32).ravel()
        return np.asarray(
            _kde_estimate(self._samples, pts, self.bandwidth)
        )


@jax.jit
def _kde_estimate(samples, points, h):
    z = (points[None, :] - samples[:, None]) / h
    k = jnp.exp(-0.5 * z * z) / (h * jnp.sqrt(2.0 * jnp.pi))
    return k.mean(axis=0)
