"""Isotonic regression by pool-adjacent-violators.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/regression/
IsotonicRegression.scala`` -- weighted PAVA producing a monotone step
function; prediction interpolates linearly between boundaries like the
reference's ``predict`` (JavaDoc'd linear interpolation).

Host-side by design: PAVA is an inherently sequential pointer-merge over
sorted data (the reference parallelizes only the per-partition pre-pass);
fitting n points is O(n) after the sort.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass
class IsotonicRegressionModel:
    boundaries: np.ndarray   # ascending feature values
    predictions: np.ndarray  # monotone fitted values at the boundaries
    increasing: bool

    def predict(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        b, p = self.boundaries, self.predictions
        out = np.interp(x, b, p)  # clamps at the ends, like the reference
        return out


class IsotonicRegression:
    def __init__(self, increasing: bool = True):
        self.increasing = increasing

    def fit(self, x, y, weights=None) -> IsotonicRegressionModel:
        x = np.asarray(x, np.float64)
        y = np.asarray(y, np.float64)
        w = np.ones_like(y) if weights is None else np.asarray(
            weights, np.float64
        )
        if np.any(w <= 0):
            raise ValueError("weights must be positive")
        order = np.argsort(x, kind="stable")
        xs, ys, ws = x[order], y[order], w[order]
        # pool tied x first (weighted mean), like Spark/sklearn -- PAVA over
        # raw ties would emit duplicate boundaries with different values,
        # which is not a function of x
        ux, starts = np.unique(xs, return_index=True)
        bounds = np.append(starts, len(xs))
        pooled_w = np.asarray(
            [ws[a:b].sum() for a, b in zip(bounds[:-1], bounds[1:])]
        )
        pooled_y = np.asarray([
            (ys[a:b] * ws[a:b]).sum() / wsum
            for a, b, wsum in zip(bounds[:-1], bounds[1:], pooled_w)
        ])
        xs, ys, ws = ux, pooled_y, pooled_w
        if not self.increasing:
            ys = -ys
        # weighted PAVA over blocks (value, weight, count)
        vals: list = []
        wts: list = []
        cnts: list = []
        for yi, wi in zip(ys, ws):
            vals.append(yi)
            wts.append(wi)
            cnts.append(1)
            while len(vals) > 1 and vals[-2] >= vals[-1]:
                wv = wts[-2] + wts[-1]
                vals[-2] = (vals[-2] * wts[-2] + vals[-1] * wts[-1]) / wv
                wts[-2] = wv
                cnts[-2] += cnts[-1]
                vals.pop()
                wts.pop()
                cnts.pop()
        # compress to boundaries: first/last x of each constant block
        b: list = []
        p: list = []
        i = 0
        for v, c in zip(
            (vals if self.increasing else [-v for v in vals]), cnts
        ):
            b.append(xs[i])
            p.append(v)
            if c > 1:
                b.append(xs[i + c - 1])
                p.append(v)
            i += c
        return IsotonicRegressionModel(
            boundaries=np.asarray(b), predictions=np.asarray(p),
            increasing=self.increasing,
        )
