"""ML library layer (L6): optimization primitives and models.

Parity: the slice of MLlib the reference's experiments stand on
(``mllib/.../optimization/`` -- ``GradientDescent.scala``, ``LBFGS.scala``,
``Gradient.scala``, ``Updater.scala`` -- plus the model wrappers in
``mllib/.../regression/`` and ``mllib/.../classification/`` and KMeans
clustering), re-designed as jitted SPMD programs over a device mesh instead
of per-iteration cluster jobs.
"""

from asyncframework_tpu.ml.gradient import (
    Gradient,
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from asyncframework_tpu.ml.updater import (
    L1Updater,
    SimpleUpdater,
    SquaredL2Updater,
    Updater,
)
from asyncframework_tpu.ml.optimization import LBFGS, GradientDescent
from asyncframework_tpu.ml.models import (
    LinearModel,
    LinearRegression,
    LinearSVM,
    LogisticRegression,
    RidgeRegression,
    Lasso,
    SoftmaxRegression,
    SoftmaxRegressionModel,
)
from asyncframework_tpu.ml.clustering import (
    BisectingKMeans,
    KMeans,
    KMeansModel,
    PowerIterationClustering,
    StreamingKMeans,
)
from asyncframework_tpu.ml.recommendation import ALS, ALSModel
from asyncframework_tpu.ml.feature import (
    IDF,
    ChiSqSelector,
    ChiSqSelectorModel,
    ElementwiseProduct,
    HashingTF,
    IDFModel,
    MinMaxScaler,
    Normalizer,
    StandardScaler,
)
from asyncframework_tpu.ml.stat import (
    ChiSqTestResult,
    ColStats,
    KernelDensity,
    KSTestResult,
    chi_sq_test,
    chi_sq_test_matrix,
    ks_test,
    col_stats,
    corr,
)

from asyncframework_tpu.ml.bayes import NaiveBayes, NaiveBayesModel
from asyncframework_tpu.ml.decomposition import PCA, PCAModel, svd
from asyncframework_tpu.ml.linalg_distributed import (
    BlockMatrix,
    CoordinateMatrix,
    IndexedRowMatrix,
    RowMatrix,
)
from asyncframework_tpu.ml.evaluation import (
    BinaryClassificationMetrics,
    MulticlassMetrics,
    MultilabelMetrics,
    RankingMetrics,
    RegressionMetrics,
)
from asyncframework_tpu.ml.tree import DecisionTree, DecisionTreeModel
from asyncframework_tpu.ml.boosting import (
    GradientBoostedTrees,
    GradientBoostedTreesModel,
)
from asyncframework_tpu.ml.forest import RandomForest, RandomForestModel
from asyncframework_tpu.ml.mixture import GaussianMixture, GaussianMixtureModel
from asyncframework_tpu.ml.fpm import (
    AssociationRules,
    FPGrowth,
    FPGrowthModel,
    FreqSequence,
    PrefixSpan,
    Rule,
)
from asyncframework_tpu.ml.isotonic import IsotonicRegression, IsotonicRegressionModel
from asyncframework_tpu.ml.lda import LDA, LDAModel
from asyncframework_tpu.ml.pipeline import (
    CrossValidator,
    CrossValidatorModel,
    Pipeline,
    PipelineModel,
    accuracy_scorer,
    r2_scorer,
    train_test_split,
)
from asyncframework_tpu.ml.streaming_models import (
    StreamingLinearRegression,
    StreamingLogisticRegression,
)
from asyncframework_tpu.ml.word2vec import Word2Vec, Word2VecModel
from asyncframework_tpu.ml.persistence import (
    load_model,
    save_as_libsvm_file,
    save_model,
)

__all__ = [
    "ALS",
    "ALSModel",
    "StandardScaler",
    "MinMaxScaler",
    "Normalizer",
    "ColStats",
    "col_stats",
    "corr",
    "Gradient",
    "LeastSquaresGradient",
    "LogisticGradient",
    "HingeGradient",
    "Updater",
    "SimpleUpdater",
    "SquaredL2Updater",
    "L1Updater",
    "GradientDescent",
    "LBFGS",
    "LinearModel",
    "LinearRegression",
    "LogisticRegression",
    "RidgeRegression",
    "Lasso",
    "IsotonicRegression",
    "IsotonicRegressionModel",
    "ks_test",
    "KSTestResult",
    "SoftmaxRegression",
    "SoftmaxRegressionModel",
    "LinearSVM",
    "KMeans",
    "KMeansModel",
    "PowerIterationClustering",
    "Word2Vec",
    "Word2VecModel",
    "NaiveBayes",
    "NaiveBayesModel",
    "PCA",
    "PCAModel",
    "svd",
    "BinaryClassificationMetrics",
    "RegressionMetrics",
    "MulticlassMetrics",
    "DecisionTree",
    "DecisionTreeModel",
    "GradientBoostedTrees",
    "GradientBoostedTreesModel",
    "RandomForest",
    "RandomForestModel",
    "GaussianMixture",
    "GaussianMixtureModel",
    "FPGrowth",
    "FPGrowthModel",
    "Rule",
    "LDA",
    "LDAModel",
    "Pipeline",
    "PipelineModel",
    "CrossValidator",
    "CrossValidatorModel",
    "train_test_split",
    "accuracy_scorer",
    "r2_scorer",
    "save_model",
    "load_model",
    "save_as_libsvm_file",
    "HashingTF",
    "IDF",
    "IDFModel",
    "ChiSqTestResult",
    "chi_sq_test",
    "chi_sq_test_matrix",
    "RowMatrix",
    "IndexedRowMatrix",
    "CoordinateMatrix",
    "BlockMatrix",
    "BisectingKMeans",
    "StreamingKMeans",
    "PrefixSpan",
    "FreqSequence",
    "AssociationRules",
    "KernelDensity",
    "ChiSqSelector",
    "ChiSqSelectorModel",
    "ElementwiseProduct",
    "RankingMetrics",
    "MultilabelMetrics",
    "StreamingLinearRegression",
    "StreamingLogisticRegression",
]
