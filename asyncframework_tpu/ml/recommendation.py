"""ALS matrix factorization (recommendation).

Parity: MLlib's ALS (``mllib/.../recommendation/ALS.scala`` family) -- the
reference solves per-user/per-item normal equations over sparse rating
blocks shuffled between executors.

TPU re-design: the normal equations are BATCHED dense linear algebra --
exactly what the MXU wants.  Ratings are a dense (users x items) matrix plus
an observation mask (unobserved entries contribute nothing); one ALS
half-step solves ALL users simultaneously:

    A_u = V^T diag(mask_u) V + reg * n_u * I      (vmapped einsum)
    b_u = V^T (mask_u * r_u)
    U   = batched_cholesky_solve(A, b)

and symmetrically for items.  No shuffles, no per-key grouping -- one
einsum + one batched solve per side per iteration, the whole fit under one
``lax.fori_loop`` jit.  The regularization follows MLlib's default
ALS-WR scaling (reg scaled by each row's observation count).

Dense-mask sizing: a 100k x 100k rating matrix is 40 GB and would NOT fit;
this formulation targets the dense/moderate regime (up to ~10k x 10k per
device).  Blocked/sharded ALS over a mesh follows the same math with the
item axis sharded; see ``parallel/mesh.py``.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclass
class ALSModel:
    user_factors: np.ndarray  # (n_users, rank)
    item_factors: np.ndarray  # (n_items, rank)
    rank: int

    def predict(self, users, items) -> np.ndarray:
        u = np.asarray(self.user_factors)[np.asarray(users)]
        v = np.asarray(self.item_factors)[np.asarray(items)]
        return np.sum(u * v, axis=-1)

    def predict_all(self) -> np.ndarray:
        return np.asarray(self.user_factors) @ np.asarray(self.item_factors).T

    def rmse(self, R, mask) -> float:
        R = np.asarray(R, np.float32)
        mask = np.asarray(mask, np.float32)
        pred = self.predict_all()
        err = (pred - R) * mask
        denom = max(float(mask.sum()), 1.0)
        return float(np.sqrt((err**2).sum() / denom))


def _half_step(F_other, R, mask, reg):
    """Solve one side's factors given the other side's.

    ``F_other``: (m, k) fixed factors; ``R``: (n, m) ratings (this side's
    rows); ``mask``: (n, m).  Returns (n, k).
    """
    k = F_other.shape[1]
    # A_i = F^T diag(mask_i) F  -> (n, k, k) in one einsum
    A = jnp.einsum("im,mk,ml->ikl", mask, F_other, F_other)
    counts = mask.sum(axis=1)
    # ALS-WR: reg scaled by each row's observation count (MLlib default)
    eye = jnp.eye(k, dtype=F_other.dtype)
    A = A + (reg * jnp.maximum(counts, 1.0))[:, None, None] * eye
    b = (mask * R) @ F_other  # (n, k)
    # batched SPD solve via Cholesky
    L = jax.lax.linalg.cholesky(A)
    y = jax.lax.linalg.triangular_solve(
        L, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        L, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


def _half_step_implicit(F_other, R, alpha, reg):
    """Implicit-feedback half step (Hu-Koren; ``ALS.trainImplicit`` parity).

    Confidence ``c = 1 + alpha * r`` on observed interactions, preference
    ``p = (r > 0)``; normal equations ``(F^T F + F^T diag(c-1) F + reg I)
    x_i = F^T (c_i * p_i)``.  The shared ``F^T F`` gram is one MXU matmul;
    the per-row correction is one einsum over the (sparse-in-spirit)
    confidence deltas.
    """
    k = F_other.shape[1]
    G = F_other.T @ F_other  # shared gram
    # c - 1 = alpha * |r| (MLlib uses the magnitude so negative "dislike"
    # ratings still mean high confidence; raw alpha*r would make A
    # indefinite and the batched Cholesky silently NaN)
    Cm1 = alpha * jnp.abs(R)
    A = G[None] + jnp.einsum("im,mk,ml->ikl", Cm1, F_other, F_other)
    A = A + reg * jnp.eye(k, dtype=F_other.dtype)[None]
    P = (R > 0).astype(F_other.dtype)
    b = ((1.0 + Cm1) * P) @ F_other
    L = jax.lax.linalg.cholesky(A)
    y = jax.lax.linalg.triangular_solve(
        L, b[..., None], left_side=True, lower=True
    )
    x = jax.lax.linalg.triangular_solve(
        L, y, left_side=True, lower=True, transpose_a=True
    )
    return x[..., 0]


class ALS:
    def __init__(
        self,
        rank: int = 10,
        reg: float = 0.1,
        num_iterations: int = 10,
        seed: int = 42,
        implicit_prefs: bool = False,
        alpha: float = 1.0,
    ):
        """``implicit_prefs=True`` switches to the Hu-Koren confidence
        formulation (``mllib ALS.trainImplicit``; alpha defaults to the
        reference's 1.0)."""
        if rank < 1:
            raise ValueError("rank must be >= 1")
        self.rank = rank
        self.reg = reg
        self.num_iterations = num_iterations
        self.seed = seed
        self.implicit_prefs = implicit_prefs
        self.alpha = alpha

    def fit(self, R, mask: Optional[np.ndarray] = None) -> ALSModel:
        """Factor ``R`` (n_users, n_items) given an observation ``mask``
        (1 = observed; default: nonzero entries are observed)."""
        R = jnp.asarray(R, jnp.float32)
        if self.implicit_prefs and mask is not None:
            raise ValueError(
                "mask is an explicit-feedback concept; implicit mode "
                "derives confidence from the interaction counts themselves"
            )
        if mask is None:
            mask = (R != 0).astype(jnp.float32)
        else:
            mask = jnp.asarray(mask, jnp.float32)
        if mask.shape != R.shape:
            raise ValueError("mask shape must match ratings shape")
        n_users, n_items = R.shape
        key = jax.random.PRNGKey(self.seed)
        ku, kv = jax.random.split(key)
        scale = 1.0 / np.sqrt(self.rank)
        U0 = jax.random.normal(ku, (n_users, self.rank), jnp.float32) * scale
        V0 = jax.random.normal(kv, (n_items, self.rank), jnp.float32) * scale

        @partial(jax.jit, static_argnums=())
        def run(U, V):
            def body(_i, uv):
                U, V = uv
                if self.implicit_prefs:
                    U = _half_step_implicit(V, R, self.alpha, self.reg)
                    V = _half_step_implicit(U, R.T, self.alpha, self.reg)
                else:
                    U = _half_step(V, R, mask, self.reg)
                    V = _half_step(U, R.T, mask.T, self.reg)
                return U, V

            return jax.lax.fori_loop(0, self.num_iterations, body, (U, V))

        U, V = run(U0, V0)
        return ALSModel(
            user_factors=np.asarray(U),
            item_factors=np.asarray(V),
            rank=self.rank,
        )
