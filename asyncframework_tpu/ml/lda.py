"""Latent Dirichlet Allocation by batch variational EM.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/clustering/LDA.scala``
(``EMLDAOptimizer`` / ``OnlineLDAOptimizer``) -- topic distributions over a
vocabulary, document-topic mixtures, Dirichlet priors alpha (doc-topic) and
eta (topic-word).

TPU mapping: the whole variational E-step is batched over documents -- the
fixed-point iteration for every document's gamma runs as (D, K) x (K, V)
matmuls on the MXU (the reference's per-document loop becomes two GEMMs per
iteration), and the M-step's sufficient statistics are one more GEMM.  The
term-count matrix is dense (D, V): the tested regime is vocab up to ~tens of
thousands, exactly the reference's experiments' scale.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _exp_elog_dirichlet(x):
    """exp(E[log theta]) for Dirichlet rows: digamma(x) - digamma(sum x)."""
    from jax.scipy.special import digamma

    return jnp.exp(digamma(x) - digamma(jnp.sum(x, axis=1, keepdims=True)))


@partial(jax.jit, static_argnums=(3,))
def _e_step(X, exp_elog_beta, alpha, n_iter):
    """Batched variational fixed point: returns (gamma (D,K), sstats (K,V))."""
    D = X.shape[0]
    K = exp_elog_beta.shape[0]
    gamma0 = jnp.full((D, K), 1.0, jnp.float32)

    def body(_, gamma):
        elog_t = _exp_elog_dirichlet(gamma)          # (D, K)
        phinorm = elog_t @ exp_elog_beta + 1e-30     # (D, V)
        return alpha + elog_t * ((X / phinorm) @ exp_elog_beta.T)

    gamma = jax.lax.fori_loop(0, n_iter, body, gamma0)
    elog_t = _exp_elog_dirichlet(gamma)
    phinorm = elog_t @ exp_elog_beta + 1e-30
    sstats = elog_t.T @ (X / phinorm) * exp_elog_beta
    return gamma, sstats


@dataclass
class LDAModel:
    topics: np.ndarray        # (K, V) normalized topic-word distributions
    doc_topics: np.ndarray    # (D, K) normalized training doc mixtures
    alpha: float
    log_perplexity_history: np.ndarray

    @property
    def k(self) -> int:
        return self.topics.shape[0]

    def describe_topics(self, max_terms: int = 10):
        """[(term indices, weights)] per topic, weight-descending
        (``LDAModel.describeTopics`` parity)."""
        out = []
        for k in range(self.k):
            order = np.argsort(-self.topics[k])[:max_terms]
            out.append((order, self.topics[k][order]))
        return out

    def transform(self, X, n_iter: int = 50) -> np.ndarray:
        """Infer doc-topic mixtures for new documents."""
        lam = jnp.asarray(self.topics, jnp.float32) + 1e-12
        exp_elog_beta = lam / lam.sum(axis=1, keepdims=True)
        gamma, _ = _e_step(
            jnp.asarray(X, jnp.float32), exp_elog_beta,
            jnp.float32(self.alpha), n_iter,
        )
        g = np.asarray(gamma)
        return g / g.sum(axis=1, keepdims=True)


class LDA:
    """``new LDA().setK(k).run(corpus)`` analog (batch variational EM)."""

    def __init__(
        self,
        k: int,
        max_iterations: int = 50,
        doc_concentration: float = None,
        topic_concentration: float = 1.01,
        e_step_iters: int = 30,
        seed: int = 0,
    ):
        if k < 2:
            raise ValueError("k must be >= 2")
        if max_iterations < 1:
            raise ValueError("max_iterations must be >= 1")
        self.k = k
        self.max_iterations = max_iterations
        # reference defaults: alpha = 50/k + 1 (EM); keep the spirit, smaller
        self.alpha = doc_concentration if doc_concentration is not None \
            else 1.0 / k
        self.eta = topic_concentration
        self.e_iters = e_step_iters
        self.seed = seed

    def fit(self, X) -> LDAModel:
        """X: (D, V) term-count matrix (dense; counts, not tf-idf)."""
        Xd = jnp.asarray(X, jnp.float32)
        D, V = Xd.shape
        rs = np.random.default_rng(self.seed)
        lam = jnp.asarray(
            rs.gamma(100.0, 0.01, size=(self.k, V)).astype(np.float32)
        )
        total_tokens = float(jnp.sum(Xd))
        hist = []
        gamma = None
        for _ in range(self.max_iterations):
            exp_elog_beta = _exp_elog_dirichlet(lam)
            gamma, sstats = _e_step(
                Xd, exp_elog_beta, jnp.float32(self.alpha), self.e_iters
            )
            lam = self.eta + sstats  # M-step
            # variational bound proxy: per-token log likelihood
            beta = lam / lam.sum(axis=1, keepdims=True)
            theta = gamma / gamma.sum(axis=1, keepdims=True)
            ll = jnp.sum(Xd * jnp.log(theta @ beta + 1e-30))
            hist.append(-float(ll) / total_tokens)
        beta = np.asarray(lam / lam.sum(axis=1, keepdims=True))
        g = np.asarray(gamma)
        return LDAModel(
            topics=beta,
            doc_topics=g / g.sum(axis=1, keepdims=True),
            alpha=self.alpha,
            log_perplexity_history=np.asarray(hist),
        )
