"""Random forests: bagged histogram trees.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/tree/RandomForest.scala``
-- bootstrap-sampled training sets, per-tree feature subsampling
(``featureSubsetStrategy``), majority vote (classification) / mean
(regression).

TPU mapping: each member is this framework's histogram
:class:`~asyncframework_tpu.ml.tree.DecisionTree` (device scatter-add
levels); bagging reuses the same binned design, so a forest is T sequential
device-accelerated tree fits.  (The reference trains groups of trees in one
pass over the data; with the per-level aggregation already a single device
op, per-tree passes are the simpler schedule at this scale.)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from asyncframework_tpu.ml.tree import DecisionTree, DecisionTreeModel


@dataclass
class RandomForestModel:
    trees: List[DecisionTreeModel]
    task: str
    num_classes: int

    def predict(self, X) -> np.ndarray:
        X = np.asarray(X, np.float32)
        votes = [t.predict(X) for t in self.trees]
        stack = np.stack(votes)
        if self.task == "regression":
            return stack.mean(axis=0)
        # majority vote via per-row bincount
        counts = np.zeros((X.shape[0], self.num_classes), np.int32)
        rows = np.arange(X.shape[0])
        for v in votes:
            counts[rows, v.astype(np.int64)] += 1
        return counts.argmax(axis=1)


class RandomForest:
    """``RandomForest.trainClassifier / trainRegressor`` analog."""

    def __init__(
        self,
        task: str = "classification",
        num_trees: int = 10,
        max_depth: int = 5,
        max_bins: int = 32,
        feature_subset_strategy: str = "auto",
        seed: int = 0,
        num_classes: Optional[int] = None,
    ):
        if num_trees < 1:
            raise ValueError("num_trees must be >= 1")
        self.task = task
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.strategy = feature_subset_strategy
        self.seed = seed
        self.num_classes = num_classes

    def _subset_size(self, F: int) -> int:
        # featureSubsetStrategy defaults: sqrt for classification,
        # one-third for regression ("auto" in the reference)
        if self.strategy == "all":
            return F
        if self.strategy == "sqrt":
            return max(1, int(np.sqrt(F)))
        if self.strategy == "onethird":
            return max(1, F // 3)
        if self.strategy == "auto":
            return (
                max(1, int(np.sqrt(F)))
                if self.task == "classification"
                else max(1, F // 3)
            )
        raise ValueError("feature_subset_strategy: auto/all/sqrt/onethird")

    def fit(self, X, y) -> RandomForestModel:
        X = np.asarray(X, np.float32)
        y = np.asarray(y)
        n, F = X.shape
        m = self._subset_size(F)
        rs = np.random.default_rng(self.seed)
        if self.task == "classification":
            C = self.num_classes or int(y.max()) + 1
        else:
            C = 0
        trees: List[DecisionTreeModel] = []
        for t_idx in range(self.num_trees):
            rows = rs.integers(0, n, n)           # bootstrap sample
            tree = DecisionTree(
                task=self.task,
                max_depth=self.max_depth,
                max_bins=self.max_bins,
                num_classes=C or None,
                feature_subset=m if m < F else None,  # per-NODE sampling
                seed=self.seed + 1000 * t_idx,
            ).fit(X[rows], y[rows])
            trees.append(tree)
        return RandomForestModel(trees=trees, task=self.task, num_classes=C)
