"""PCA and SVD via distributed gram matrices.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/feature/PCA.scala``
and ``mllib/.../linalg/distributed/RowMatrix.scala:493`` (``computeSVD``) --
the reference computes the d x d gram/covariance with a treeAggregate over
row blocks, then eigendecomposes on the driver (its "local" mode; ARPACK
only for huge d).

TPU mapping: the gram matrix is ONE matmul per shard on the MXU, psum-merged
over the mesh's data axis (the treeAggregate as an ICI collective); the
d x d eigendecomposition runs with ``jnp.linalg.eigh`` (d <= a few thousand,
exactly the reference's local regime).  U is recovered row-sharded as
``A V / s``, another MXU matmul.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.parallel.mesh import resolve_shard_map


def _gram_and_mean(X, mesh: Optional[Mesh], axis: str):
    """(n, X^T X, column sums), psum-combined over the mesh when given."""
    X = jnp.asarray(X, jnp.float32)

    if mesh is None:
        return X.shape[0], X.T @ X, X.sum(axis=0)

    @partial(
        resolve_shard_map(),
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=(P(), P(None, None), P(None)),
    )
    def dist(Xl):
        n = jax.lax.psum(jnp.asarray(Xl.shape[0], jnp.int32), axis)
        g = jax.lax.psum(Xl.T @ Xl, axis)
        s = jax.lax.psum(Xl.sum(axis=0), axis)
        return n, g, s

    n, g, s = dist(X)
    return int(n), g, s


@dataclass
class PCAModel:
    components: np.ndarray          # (k, d) principal axes, rows
    explained_variance: np.ndarray  # (k,)
    mean: np.ndarray                # (d,)

    def transform(self, X) -> jax.Array:
        X = jnp.asarray(X, jnp.float32)
        return (X - jnp.asarray(self.mean)) @ jnp.asarray(self.components).T


class PCA:
    """``new PCA(k).fit(data)`` analog; covariance eigendecomposition."""

    def __init__(self, k: int):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k

    def fit(self, X, mesh: Optional[Mesh] = None, axis: str = "dp") -> PCAModel:
        n, gram, colsum = _gram_and_mean(X, mesh, axis)
        d = gram.shape[0]
        if self.k > d:
            raise ValueError(f"k={self.k} > d={d}")
        mean = colsum / n
        # covariance from the gram matrix: (X^T X - n mu mu^T) / (n - 1)
        cov = (gram - n * jnp.outer(mean, mean)) / max(n - 1, 1)
        evals, evecs = jnp.linalg.eigh(cov)  # ascending
        order = jnp.argsort(-evals)[: self.k]
        comps = evecs[:, order].T
        # sign convention: largest-|.| coordinate of each axis positive
        # (deterministic across backends; eigh's signs are arbitrary)
        idx = jnp.argmax(jnp.abs(comps), axis=1)
        signs = jnp.sign(comps[jnp.arange(self.k), idx])
        comps = comps * signs[:, None]
        return PCAModel(
            components=np.asarray(comps),
            explained_variance=np.asarray(evals[order]),
            mean=np.asarray(mean),
        )


def svd(
    X,
    k: int,
    mesh: Optional[Mesh] = None,
    axis: str = "dp",
    compute_u: bool = True,
    rcond: float = 1e-3,
) -> Tuple[Optional[jax.Array], np.ndarray, np.ndarray]:
    """Truncated SVD ``A ~ U diag(s) V^T`` via the gram matrix.

    ``RowMatrix.computeSVD`` parity: eigendecompose ``A^T A = V S^2 V^T``,
    keep the top-k with ``s > rcond * s_max``, recover ``U = A V S^{-1}``
    (row-sharded, one matmul).  Returns (U or None, s (k',), V (d, k')).

    ``rcond`` defaults to 1e-3: squaring through the f32 gram floors
    recoverable singular values at ~sqrt(eps_f32) * s_max ~= 3e-4 * s_max
    (the reference's double-precision gram can cut tighter; document over
    pretend).
    """
    n, gram, _ = _gram_and_mean(X, mesh, axis)
    d = gram.shape[0]
    if not 1 <= k <= d:
        raise ValueError(f"k must be in [1, {d}], got {k}")
    evals, evecs = jnp.linalg.eigh(gram)
    order = jnp.argsort(-evals)[:k]
    s2 = jnp.maximum(evals[order], 0.0)
    s = jnp.sqrt(s2)
    keep = np.asarray(s > rcond * (s[0] if k else 1.0)).nonzero()[0]
    s = np.asarray(s)[keep]
    V = evecs[:, order][:, jnp.asarray(keep)]
    # deterministic sign convention, matched in U through the product
    idx = jnp.argmax(jnp.abs(V), axis=0)
    signs = jnp.sign(V[idx, jnp.arange(V.shape[1])])
    V = V * signs[None, :]
    U = None
    if compute_u:
        A = jnp.asarray(X, jnp.float32)
        U = (A @ V) / jnp.asarray(s)[None, :]
    return U, s, np.asarray(V)
