"""Parameter updaters (step + regularization).

Parity: ``mllib/.../optimization/Updater.scala`` -- ``SimpleUpdater`` (:41),
``L1Updater`` soft-thresholding (:70), ``SquaredL2Updater`` (:140).  Exact
semantics preserved: the per-iteration learning rate is
``step_size / sqrt(iter)`` with ``iter`` 1-indexed, applied to the *average*
gradient; the returned regularization value is computed on the *new* weights.
All methods are pure and jax-traceable.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


class Updater:
    def apply(
        self,
        w: jax.Array,
        avg_grad: jax.Array,
        step_size: float,
        it: jax.Array,
        reg_param: float,
    ) -> Tuple[jax.Array, jax.Array]:
        """Returns ``(w_new, reg_val)``; ``it`` is the 1-indexed iteration."""
        raise NotImplementedError

    @staticmethod
    def _lr(step_size, it):
        return step_size / jnp.sqrt(it)


class SimpleUpdater(Updater):
    def apply(self, w, avg_grad, step_size, it, reg_param):
        w2 = w - self._lr(step_size, it) * avg_grad
        return w2, jnp.asarray(0.0, w.dtype)


class SquaredL2Updater(Updater):
    """w' = w (1 - lr * reg) - lr * grad;  reg_val = reg/2 ||w'||^2."""

    def apply(self, w, avg_grad, step_size, it, reg_param):
        lr = self._lr(step_size, it)
        w2 = w * (1.0 - lr * reg_param) - lr * avg_grad
        return w2, 0.5 * reg_param * jnp.sum(w2 * w2)


class L1Updater(Updater):
    """Soft-threshold at ``lr * reg``;  reg_val = reg ||w'||_1."""

    def apply(self, w, avg_grad, step_size, it, reg_param):
        lr = self._lr(step_size, it)
        raw = w - lr * avg_grad
        shrink = lr * reg_param
        w2 = jnp.sign(raw) * jnp.maximum(jnp.abs(raw) - shrink, 0.0)
        return w2, reg_param * jnp.sum(jnp.abs(w2))
