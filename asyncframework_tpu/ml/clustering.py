"""KMeans clustering as SPMD Lloyd iterations.

Parity: ``mllib/.../clustering/KMeans.scala`` -- k-means++-style seeding,
Lloyd assignment/update loop, ``computeCost`` (sum of squared distances).
The reference runs one cluster job per iteration with per-partition center
sums combined at the driver; here one jitted ``shard_map`` step computes the
per-device (k, d) center sums + (k,) counts and ``psum``s them over ICI --
the assignment argmin and the segment sums are batched one-hot matmuls that
tile onto the MXU (no per-row host loop anywhere).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.parallel.mesh import make_mesh, pad_and_shard


class KMeansModel:
    def __init__(self, centers: np.ndarray, cost: float, iterations: int):
        self.centers = centers
        self.cost = cost  # computeCost parity: sum of squared distances
        self.iterations = iterations

    @property
    def k(self) -> int:
        return self.centers.shape[0]

    def predict(self, X: np.ndarray) -> np.ndarray:
        d2 = (
            (X * X).sum(1)[:, None]
            - 2.0 * X @ self.centers.T
            + (self.centers * self.centers).sum(1)[None, :]
        )
        return np.argmin(d2, axis=1)


class KMeans:
    def __init__(
        self,
        k: int,
        max_iterations: int = 20,
        tol: float = 1e-4,
        seed: int = 42,
        init: str = "k-means++",
    ):
        if init not in ("k-means++", "random"):
            raise ValueError(f"unknown init {init!r}")
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.init = init

    # ------------------------------------------------------------------ init
    def _seed_centers(self, X: np.ndarray) -> np.ndarray:
        """k-means++ seeding on a host subsample (the reference's k-means||
        parallel seeding exists to avoid scanning a giant RDD k times; a
        bounded subsample achieves the same O(1)-pass property here)."""
        rs = np.random.default_rng(self.seed)
        sub = X[rs.choice(X.shape[0], min(X.shape[0], 50_000), replace=False)]
        if self.init == "random":
            idx = rs.choice(sub.shape[0], self.k, replace=False)
            return sub[idx].astype(np.float32)
        centers = [sub[rs.integers(sub.shape[0])]]
        d2 = ((sub - centers[0]) ** 2).sum(1)
        for _ in range(1, self.k):
            p = d2 / d2.sum() if d2.sum() > 0 else None
            centers.append(sub[rs.choice(sub.shape[0], p=p)])
            d2 = np.minimum(d2, ((sub - centers[-1]) ** 2).sum(1))
        return np.stack(centers).astype(np.float32)

    # ------------------------------------------------------------------ fit
    def fit(self, X: np.ndarray, mesh: Optional[Mesh] = None) -> KMeansModel:
        X = np.asarray(X, np.float32)
        mesh = mesh or make_mesh()
        Xs, vs, n = pad_and_shard(mesh, X)
        k = self.k

        @jax.jit
        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P("dp", None), P("dp"), P(None, None)),
            out_specs=(P(None, None), P(None), P()),
        )
        def lloyd_step(Xl, vl, centers):
            d2 = (
                (Xl * Xl).sum(1)[:, None]
                - 2.0 * Xl @ centers.T
                + (centers * centers).sum(1)[None, :]
            )
            assign = jnp.argmin(d2, axis=1)
            onehot = jax.nn.one_hot(assign, k, dtype=Xl.dtype) * vl[:, None]
            sums = onehot.T @ Xl                      # (k, d)
            counts = onehot.sum(0)                    # (k,)
            cost = jnp.sum(jnp.min(d2, axis=1) * vl)
            sums, counts, cost = jax.lax.psum((sums, counts, cost), "dp")
            return sums, counts, cost

        centers = jnp.asarray(self._seed_centers(X[:n]))
        it = 0
        for it in range(1, self.max_iterations + 1):
            sums, counts, _cost_prev = lloyd_step(Xs, vs, centers)
            counts = jnp.maximum(counts, 1e-9)[:, None]
            new_centers = sums / counts
            # empty clusters keep their previous center (MLlib behavior)
            new_centers = jnp.where(counts > 0.5, new_centers, centers)
            shift = float(jnp.sum((new_centers - centers) ** 2))
            centers = new_centers
            if shift < self.tol * self.tol:
                break
        # cost of the RETURNED centers (computeCost parity): one extra
        # assignment pass -- the in-loop cost is w.r.t. pre-update centers
        _s, _c, cost_arr = lloyd_step(Xs, vs, centers)
        return KMeansModel(np.asarray(centers), float(cost_arr), it)


class PowerIterationClustering:
    """Clustering by power iteration on the normalized affinity matrix.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/clustering/
    PowerIterationClustering.scala`` (Lin & Cohen) -- iterate
    ``v <- W v / |W v|_1`` on the row-normalized affinities, then k-means
    the resulting 1-d embedding.

    TPU mapping: the reference runs each iteration as a GraphX
    aggregateMessages job; here the affinity is a dense (n, n) matrix and
    every iteration is one MXU matvec (dense regime note as in
    ``graph/algorithms.py``: n up to ~2^14).
    """

    def __init__(self, k: int, max_iterations: int = 30, seed: int = 0):
        if k < 2:
            raise ValueError("k must be >= 2")
        self.k = k
        self.max_iterations = max_iterations
        self.seed = seed

    def fit_predict(self, affinity) -> np.ndarray:
        W = jnp.asarray(affinity, jnp.float32)
        if W.ndim != 2 or W.shape[0] != W.shape[1]:
            raise ValueError("affinity must be square (n, n)")
        if bool(jnp.any(W < 0)):
            raise ValueError("affinities must be nonnegative")
        n = W.shape[0]
        deg = jnp.maximum(W.sum(axis=1, keepdims=True), 1e-12)
        Wn = W / deg  # row-normalized

        # init: degree-proportional vector (the reference's default)
        v0 = (deg[:, 0] / jnp.sum(deg)).astype(jnp.float32)
        v = _pic_iterate(Wn, v0, self.max_iterations)
        emb = np.asarray(v)[:, None]
        km = KMeans(self.k, seed=self.seed).fit(emb)
        return np.asarray(km.predict(emb))


@jax.jit
def _pic_iterate(Wn, v, iters):
    """Power iteration with the Lin & Cohen acceleration stopping rule.

    Wn rides as a jit ARGUMENT (a captured closure would bake the (n, n)
    matrix into the executable as a constant and retrace per call).

    Early stop is essential, not cosmetic: Wn is row-stochastic, so the
    iteration's fixed point is the uniform dominant eigenvector -- the
    cluster signal lives in the TRANSIENT.  Stop when the change of the
    step-delta stabilizes (|delta_t - delta_{t-1}| < 1e-5/n, the
    reference's epsilon), i.e. when locally-converged structure has
    emerged but before it washes out.
    """
    n = v.shape[0]
    eps = jnp.float32(1e-5) / n

    def cond(carry):
        _v, _prev, i, done = carry
        return jnp.logical_and(i < iters, jnp.logical_not(done))

    def body(carry):
        v, prev_delta, i, _done = carry
        nv = Wn @ v
        nv = nv / jnp.sum(jnp.abs(nv))
        delta = jnp.sum(jnp.abs(nv - v))
        return nv, delta, i + 1, jnp.abs(delta - prev_delta) < eps

    v, _, _, _ = jax.lax.while_loop(
        cond, body, (v, jnp.float32(jnp.inf), jnp.int32(0), jnp.bool_(False))
    )
    return v
