"""KMeans clustering as SPMD Lloyd iterations.

Parity: ``mllib/.../clustering/KMeans.scala`` -- k-means++-style seeding,
Lloyd assignment/update loop, ``computeCost`` (sum of squared distances).
The reference runs one cluster job per iteration with per-partition center
sums combined at the driver; here one jitted ``shard_map`` step computes the
per-device (k, d) center sums + (k,) counts and ``psum``s them over ICI --
the assignment argmin and the segment sums are batched one-hot matmuls that
tile onto the MXU (no per-row host loop anywhere).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.parallel.mesh import (
    make_mesh,
    pad_and_shard,
    resolve_shard_map,
)


def _pairwise_d2(X, centers):
    """Squared euclidean distances (n, k) via the expanded-norm matmul form
    -- the ONE assignment kernel shared by Lloyd iterations, streaming
    updates, and prediction (works on numpy and jax arrays alike)."""
    return (
        (X * X).sum(1)[:, None]
        - 2.0 * X @ centers.T
        + (centers * centers).sum(1)[None, :]
    )


class KMeansModel:
    def __init__(self, centers: np.ndarray, cost: float, iterations: int):
        self.centers = centers
        self.cost = cost  # computeCost parity: sum of squared distances
        self.iterations = iterations

    @property
    def k(self) -> int:
        return self.centers.shape[0]

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.argmin(_pairwise_d2(X, self.centers), axis=1)


class KMeans:
    def __init__(
        self,
        k: int,
        max_iterations: int = 20,
        tol: float = 1e-4,
        seed: int = 42,
        init: str = "k-means++",
    ):
        if init not in ("k-means++", "random"):
            raise ValueError(f"unknown init {init!r}")
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.init = init

    # ------------------------------------------------------------------ init
    def _seed_centers(self, X: np.ndarray) -> np.ndarray:
        """k-means++ seeding on a host subsample (the reference's k-means||
        parallel seeding exists to avoid scanning a giant RDD k times; a
        bounded subsample achieves the same O(1)-pass property here)."""
        rs = np.random.default_rng(self.seed)
        sub = X[rs.choice(X.shape[0], min(X.shape[0], 50_000), replace=False)]
        if self.init == "random":
            idx = rs.choice(sub.shape[0], self.k, replace=False)
            return sub[idx].astype(np.float32)
        centers = [sub[rs.integers(sub.shape[0])]]
        d2 = ((sub - centers[0]) ** 2).sum(1)
        for _ in range(1, self.k):
            p = d2 / d2.sum() if d2.sum() > 0 else None
            centers.append(sub[rs.choice(sub.shape[0], p=p)])
            d2 = np.minimum(d2, ((sub - centers[-1]) ** 2).sum(1))
        return np.stack(centers).astype(np.float32)

    # ------------------------------------------------------------------ fit
    def fit(self, X: np.ndarray, mesh: Optional[Mesh] = None) -> KMeansModel:
        X = np.asarray(X, np.float32)
        mesh = mesh or make_mesh()
        Xs, vs, n = pad_and_shard(mesh, X)
        k = self.k

        @jax.jit
        @partial(
            resolve_shard_map(),
            mesh=mesh,
            in_specs=(P("dp", None), P("dp"), P(None, None)),
            out_specs=(P(None, None), P(None), P()),
        )
        def lloyd_step(Xl, vl, centers):
            d2 = _pairwise_d2(Xl, centers)
            assign = jnp.argmin(d2, axis=1)
            onehot = jax.nn.one_hot(assign, k, dtype=Xl.dtype) * vl[:, None]
            sums = onehot.T @ Xl                      # (k, d)
            counts = onehot.sum(0)                    # (k,)
            cost = jnp.sum(jnp.min(d2, axis=1) * vl)
            sums, counts, cost = jax.lax.psum((sums, counts, cost), "dp")
            return sums, counts, cost

        centers = jnp.asarray(self._seed_centers(X[:n]))
        it = 0
        for it in range(1, self.max_iterations + 1):
            sums, counts, _cost_prev = lloyd_step(Xs, vs, centers)
            counts = jnp.maximum(counts, 1e-9)[:, None]
            new_centers = sums / counts
            # empty clusters keep their previous center (MLlib behavior)
            new_centers = jnp.where(counts > 0.5, new_centers, centers)
            shift = float(jnp.sum((new_centers - centers) ** 2))
            centers = new_centers
            if shift < self.tol * self.tol:
                break
        # cost of the RETURNED centers (computeCost parity): one extra
        # assignment pass -- the in-loop cost is w.r.t. pre-update centers
        _s, _c, cost_arr = lloyd_step(Xs, vs, centers)
        return KMeansModel(np.asarray(centers), float(cost_arr), it)


class BisectingKMeans:
    """Divisive hierarchical k-means.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/clustering/
    BisectingKMeans.scala`` -- start from one root cluster, repeatedly
    2-means-split the largest divisible cluster until ``k`` leaves exist
    (the reference splits level-by-level; largest-first yields the same
    leaf set for the common balanced case and a strictly better cost
    greedy otherwise).  ``min_divisible_cluster_size`` gates which
    clusters may split, as in the reference.

    TPU mapping: every split is a 2-means Lloyd loop on the member rows --
    the same one-hot-matmul assignment kernel as :class:`KMeans`, batched
    on device; the hierarchy bookkeeping (tiny) stays on host.
    """

    def __init__(
        self,
        k: int = 4,
        max_iterations: int = 20,
        min_divisible_cluster_size: int = 1,
        seed: int = 42,
    ):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self.max_iterations = max_iterations
        self.min_divisible = max(int(min_divisible_cluster_size), 1)
        self.seed = seed

    def fit(self, X: np.ndarray) -> KMeansModel:
        X = np.asarray(X, np.float32)
        n = X.shape[0]
        # leaves: list of (member row indices, center, sse cost)
        root_center = X.mean(axis=0)
        root_cost = float(((X - root_center) ** 2).sum())
        # leaf: (member row indices, center, sse cost, divisible flag)
        leaves = [(np.arange(n), root_center, root_cost, True)]
        it = 0
        while len(leaves) < self.k:
            # split the largest divisible leaf (>= 2 rows, >= min size)
            order = sorted(
                range(len(leaves)),
                key=lambda i: len(leaves[i][0]),
                reverse=True,
            )
            target = next(
                (
                    i for i in order
                    if leaves[i][3]
                    and len(leaves[i][0]) >= max(2, self.min_divisible)
                ),
                None,
            )
            if target is None:
                break  # nothing divisible; fewer than k leaves (reference
                # behavior: the tree just stops growing)
            idx, _, _, _ = leaves.pop(target)
            sub = X[idx]
            km = KMeans(
                2,
                max_iterations=self.max_iterations,
                seed=self.seed + it,
            ).fit(sub)
            assign = km.predict(sub)
            it += 1
            if len(np.unique(assign)) < 2:
                # degenerate split (duplicate rows): keep the leaf, mark it
                # indivisible, and move on to the next candidate
                leaves.append((idx, km.centers[0], km.cost, False))
                continue
            for c in (0, 1):
                members = idx[assign == c]
                center = km.centers[c]
                cost = float(((X[members] - center) ** 2).sum())
                leaves.append((members, center, cost, True))
        centers = np.stack([c for (_i, c, _s, _d) in leaves]).astype(
            np.float32
        )
        return KMeansModel(
            centers, cost=float(sum(s for (_i, _c, s, _d) in leaves)),
            iterations=it,
        )


class StreamingKMeans:
    """Online k-means with exponential forgetfulness.

    Parity: ``mllib/src/main/stream/.../clustering/StreamingKMeans.scala``
    update rule -- per batch:

        c' = (c * n * a + sum_batch) / (n * a + m),   n' = n * a + m

    with decay ``a`` applied per batch (``time_unit="batches"``) or as
    ``a^m`` (``time_unit="points"``); ``set_half_life`` derives ``a`` from
    a half-life.  Dying clusters (the reference's zero-weight check) are
    re-seeded by splitting the heaviest cluster.

    Each batch's (per-center sum, count) is one one-hot matmul on device.
    """

    def __init__(
        self,
        k: int,
        decay_factor: float = 1.0,
        time_unit: str = "batches",
        seed: int = 42,
    ):
        if time_unit not in ("batches", "points"):
            raise ValueError("time_unit must be 'batches' or 'points'")
        if not 0.0 < decay_factor <= 1.0:
            raise ValueError("decay_factor must be in (0, 1]")
        self.k = k
        self.decay = decay_factor
        self.time_unit = time_unit
        self.seed = seed
        self.centers: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None

    def set_half_life(self, half_life: float, time_unit: str) -> "StreamingKMeans":
        if time_unit not in ("batches", "points"):
            raise ValueError("time_unit must be 'batches' or 'points'")
        self.decay = float(np.exp(np.log(0.5) / half_life))
        self.time_unit = time_unit
        return self

    def set_initial_centers(self, centers, weights=None) -> "StreamingKMeans":
        self.centers = np.asarray(centers, np.float32)
        self.weights = (
            np.asarray(weights, np.float64)
            if weights is not None
            else np.zeros(self.centers.shape[0], np.float64)
        )
        return self

    def set_random_centers(self, d: int, weight: float = 0.0) -> "StreamingKMeans":
        rs = np.random.default_rng(self.seed)
        self.centers = rs.normal(size=(self.k, d)).astype(np.float32)
        self.weights = np.full(self.k, weight, np.float64)
        return self

    def update(self, batch) -> "StreamingKMeans":
        batch = np.asarray(batch, np.float32)
        if batch.ndim != 2 or batch.shape[0] == 0:
            return self
        if self.centers is None:
            self.set_random_centers(batch.shape[1])
        sums, counts = _assign_sums(
            jnp.asarray(batch), jnp.asarray(self.centers)
        )
        sums = np.asarray(sums, np.float64)
        counts = np.asarray(counts, np.float64)
        m = batch.shape[0]
        a = self.decay if self.time_unit == "batches" else self.decay ** m
        discounted = self.weights * a
        new_w = discounted + counts
        safe = np.maximum(new_w, 1e-12)
        updated = (
            (self.centers * discounted[:, None] + sums) / safe[:, None]
        ).astype(np.float32)
        # only move centers that actually received points this batch: the
        # reference updates from pointStats entries only, so a zero-weight
        # user-supplied center with no points stays where it was put
        self.centers = np.where((counts > 0)[:, None], updated, self.centers)
        self.weights = new_w
        # re-seed dying clusters: split the heaviest; relative threshold
        # matches StreamingKMeans.scala (minWeight < 1e-8 * maxWeight)
        dead = self.weights < 1e-8 * self.weights.max()
        if dead.any() and (~dead).any():
            heavy = int(np.argmax(self.weights))
            for j in np.nonzero(dead)[0]:
                jitter = 1e-4 * np.abs(self.centers[heavy]).max()
                self.centers[j] = self.centers[heavy] + jitter
                self.centers[heavy] = self.centers[heavy] - jitter
                self.weights[j] = self.weights[heavy] / 2
                self.weights[heavy] /= 2
        return self

    def latest_model(self) -> KMeansModel:
        if self.centers is None:
            raise ValueError("no data seen yet")
        return KMeansModel(self.centers.copy(), cost=float("nan"),
                           iterations=0)

    def predict(self, X) -> np.ndarray:
        return self.latest_model().predict(np.asarray(X, np.float32))

    # -------------------------------------------------- DStream integration
    def train_on(self, dstream) -> "StreamingKMeans":
        """Update the model from every batch of a DStream
        (``StreamingKMeans.trainOn`` parity).  Registers an output op; the
        stream's clock drives updates."""
        dstream.foreach_batch(lambda _t, b: self.update(np.asarray(b)))
        return self

    def predict_on(self, dstream):
        """Per-interval cluster assignments (``predictOn`` parity): a new
        DStream of label arrays using the model AS OF each interval."""
        return dstream.map_batch(
            lambda b: self.predict(np.asarray(b, np.float32))
        )


@jax.jit
def _assign_sums(batch, centers):
    """Per-center (sum of assigned rows, count): one-hot matmul kernel."""
    d2 = _pairwise_d2(batch, centers)
    onehot = jax.nn.one_hot(jnp.argmin(d2, axis=1), centers.shape[0],
                            dtype=batch.dtype)
    return onehot.T @ batch, onehot.sum(0)


class PowerIterationClustering:
    """Clustering by power iteration on the normalized affinity matrix.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/clustering/
    PowerIterationClustering.scala`` (Lin & Cohen) -- iterate
    ``v <- W v / |W v|_1`` on the row-normalized affinities, then k-means
    the resulting 1-d embedding.

    TPU mapping: the reference runs each iteration as a GraphX
    aggregateMessages job; here the affinity is a dense (n, n) matrix and
    every iteration is one MXU matvec (dense regime note as in
    ``graph/algorithms.py``: n up to ~2^14).
    """

    def __init__(self, k: int, max_iterations: int = 30, seed: int = 0):
        if k < 2:
            raise ValueError("k must be >= 2")
        self.k = k
        self.max_iterations = max_iterations
        self.seed = seed

    def fit_predict(self, affinity) -> np.ndarray:
        W = jnp.asarray(affinity, jnp.float32)
        if W.ndim != 2 or W.shape[0] != W.shape[1]:
            raise ValueError("affinity must be square (n, n)")
        if bool(jnp.any(W < 0)):
            raise ValueError("affinities must be nonnegative")
        n = W.shape[0]
        deg = jnp.maximum(W.sum(axis=1, keepdims=True), 1e-12)
        Wn = W / deg  # row-normalized

        # init: degree-proportional vector (the reference's default)
        v0 = (deg[:, 0] / jnp.sum(deg)).astype(jnp.float32)
        v = _pic_iterate(Wn, v0, self.max_iterations)
        emb = np.asarray(v)[:, None]
        km = KMeans(self.k, seed=self.seed).fit(emb)
        return np.asarray(km.predict(emb))


@jax.jit
def _pic_iterate(Wn, v, iters):
    """Power iteration with the Lin & Cohen acceleration stopping rule.

    Wn rides as a jit ARGUMENT (a captured closure would bake the (n, n)
    matrix into the executable as a constant and retrace per call).

    Early stop is essential, not cosmetic: Wn is row-stochastic, so the
    iteration's fixed point is the uniform dominant eigenvector -- the
    cluster signal lives in the TRANSIENT.  Stop when the change of the
    step-delta stabilizes (|delta_t - delta_{t-1}| < 1e-5/n, the
    reference's epsilon), i.e. when locally-converged structure has
    emerged but before it washes out.
    """
    n = v.shape[0]
    eps = jnp.float32(1e-5) / n

    def cond(carry):
        _v, _prev, i, done = carry
        return jnp.logical_and(i < iters, jnp.logical_not(done))

    def body(carry):
        v, prev_delta, i, _done = carry
        nv = Wn @ v
        nv = nv / jnp.sum(jnp.abs(nv))
        delta = jnp.sum(jnp.abs(nv - v))
        return nv, delta, i + 1, jnp.abs(delta - prev_delta) < eps

    v, _, _, _ = jax.lax.while_loop(
        cond, body, (v, jnp.float32(jnp.inf), jnp.int32(0), jnp.bool_(False))
    )
    return v
