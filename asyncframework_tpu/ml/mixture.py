"""Gaussian mixture models by EM.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/clustering/
GaussianMixture.scala`` -- EM with full covariances; the reference's E-step
is a map over points with a driver-side ``ExpectationSum`` aggregation.

TPU mapping: one EM iteration is a fixed pipeline of matmuls --
log-likelihood matrix (n, k) via batched quadratic forms, responsibilities
by a row softmax, and the M-step's weighted moments as two matmuls -- all
MXU work under one jit.  Cholesky factorizations of the k (d, d)
covariances run batched on device.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnums=())
def _log_gaussians(X, means, chols):
    """(n, k) log N(x | mu_j, Sigma_j) via batched Cholesky solves."""
    d = X.shape[1]
    diff = X[:, None, :] - means[None, :, :]            # (n, k, d)
    # solve L z = diff for each component: vmap over k
    z = jax.vmap(
        lambda L, v: jax.scipy.linalg.solve_triangular(L, v.T, lower=True),
        in_axes=(0, 1),
    )(chols, diff)                                       # (k, d, n)
    maha = jnp.sum(z * z, axis=1).T                      # (n, k)
    logdet = 2.0 * jnp.sum(
        jnp.log(jnp.diagonal(chols, axis1=1, axis2=2)), axis=1
    )
    return -0.5 * (maha + logdet + d * jnp.log(2.0 * jnp.pi))


@jax.jit
def _em_step(X, weights, means, chols):
    logp = _log_gaussians(X, means, chols) + jnp.log(weights)[None, :]
    norm = jax.scipy.special.logsumexp(logp, axis=1, keepdims=True)
    resp = jnp.exp(logp - norm)                          # (n, k)
    nk = resp.sum(axis=0)                                # (k,)
    new_means = (resp.T @ X) / nk[:, None]
    # covariances: E[xx^T] - mu mu^T with responsibility weights
    def cov_j(r, mu):
        xc = X - mu[None, :]
        return (xc * r[:, None]).T @ xc
    covs = jax.vmap(cov_j, in_axes=(1, 0))(resp, new_means) / nk[:, None, None]
    ll = jnp.sum(norm)
    return nk / X.shape[0], new_means, covs, ll


@dataclass
class GaussianMixtureModel:
    weights: np.ndarray      # (k,)
    means: np.ndarray        # (k, d)
    covariances: np.ndarray  # (k, d, d)
    log_likelihood: float

    def predict_proba(self, X) -> np.ndarray:
        X = jnp.asarray(X, jnp.float32)
        chols = jnp.linalg.cholesky(jnp.asarray(self.covariances))
        logp = _log_gaussians(X, jnp.asarray(self.means), chols)
        logp = logp + jnp.log(jnp.asarray(self.weights))[None, :]
        norm = jax.scipy.special.logsumexp(logp, axis=1, keepdims=True)
        return np.asarray(jnp.exp(logp - norm))

    def predict(self, X) -> np.ndarray:
        return np.asarray(np.argmax(self.predict_proba(X), axis=1))


class GaussianMixture:
    """``new GaussianMixture().setK(k).run(data)`` analog."""

    def __init__(
        self,
        k: int,
        max_iterations: int = 100,
        tol: float = 1e-3,
        seed: int = 0,
        reg: float = 1e-6,
    ):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.reg = reg  # diagonal jitter keeping covariances SPD

    def fit(self, X) -> GaussianMixtureModel:
        Xd = jnp.asarray(X, jnp.float32)
        n, d = Xd.shape
        # init means with a short k-means run (k-means++ seeding): EM from
        # random points routinely lands in visibly worse optima
        from asyncframework_tpu.ml.clustering import KMeans

        km = KMeans(self.k, max_iterations=10, seed=self.seed).fit(
            np.asarray(Xd)
        )
        means = jnp.asarray(km.centers, jnp.float32)
        global_cov = jnp.cov(Xd.T).reshape(d, d).astype(jnp.float32)
        covs = jnp.tile(global_cov[None], (self.k, 1, 1))
        weights = jnp.full(self.k, 1.0 / self.k, jnp.float32)
        eye = jnp.eye(d, dtype=jnp.float32)

        prev_ll = -np.inf
        ll = prev_ll
        for _ in range(self.max_iterations):
            chols = jnp.linalg.cholesky(covs + self.reg * eye[None])
            weights, means, covs, ll_dev = _em_step(Xd, weights, means, chols)
            ll = float(ll_dev)
            if abs(ll - prev_ll) < self.tol * max(abs(ll), 1.0):
                break
            prev_ll = ll
        return GaussianMixtureModel(
            weights=np.asarray(weights),
            means=np.asarray(means),
            covariances=np.asarray(covs + self.reg * eye[None]),
            log_likelihood=ll,
        )
