"""Model persistence: save/load for every model family.

Parity: each MLlib model implements ``Saveable``/``Loader`` (e.g.
``mllib/.../classification/NaiveBayes.scala`` save/load, tree models via
``tree/model/treeEnsembleModels.scala``) -- models round-trip through a
storage path with a format tag and validation on load.

Format here: one ``.npz`` per model (array fields as arrays, scalars/str as
0-d arrays, nested lists of models flattened with indexed keys) plus a
``__class__`` tag checked on load.  Array-only on purpose -- the same
no-code-execution trust posture as the checkpoint and WAL formats.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any, Dict, List, Union

import numpy as np

from asyncframework_tpu.ml.bayes import NaiveBayesModel
from asyncframework_tpu.ml.boosting import GradientBoostedTreesModel
from asyncframework_tpu.ml.clustering import KMeansModel
from asyncframework_tpu.ml.decomposition import PCAModel
from asyncframework_tpu.ml.forest import RandomForestModel
from asyncframework_tpu.ml.isotonic import IsotonicRegressionModel
from asyncframework_tpu.ml.lda import LDAModel
from asyncframework_tpu.ml.mixture import GaussianMixtureModel
from asyncframework_tpu.ml.models import (
    LinearModel,
    LogisticRegressionModel,
    SoftmaxRegressionModel,
    SVMModel,
)
from asyncframework_tpu.ml.pipeline import PipelineModel
from asyncframework_tpu.ml.recommendation import ALSModel
from asyncframework_tpu.ml.tree import DecisionTreeModel
from asyncframework_tpu.ml.word2vec import Word2VecModel
from asyncframework_tpu.graph.algorithms import SVDPlusPlusModel


def _tree_payload(t: DecisionTreeModel, prefix: str) -> Dict[str, np.ndarray]:
    return {
        f"{prefix}feature": t.feature,
        f"{prefix}threshold": t.threshold,
        f"{prefix}prediction": t.prediction,
        f"{prefix}depth": np.int64(t.depth),
        f"{prefix}task": np.str_(t.task),
    }


def _tree_restore(z, prefix: str) -> DecisionTreeModel:
    return DecisionTreeModel(
        feature=np.asarray(z[f"{prefix}feature"]),
        threshold=np.asarray(z[f"{prefix}threshold"]),
        prediction=np.asarray(z[f"{prefix}prediction"]),
        depth=int(z[f"{prefix}depth"]),
        task=str(z[f"{prefix}task"]),
    )


def _model_payload(model: Any) -> Dict[str, Any]:
    payload: Dict[str, Any] = {"__class__": np.str_(type(model).__name__)}

    if isinstance(model, PipelineModel):
        payload["n_tf"] = np.int64(len(model.transformers))
        for i, t in enumerate(model.transformers):
            payload.update(_transformer_payload(t, f"tf{i}_"))
        for k, v in _model_payload(model.model).items():
            payload[f"inner_{k}"] = v
    elif isinstance(model, DecisionTreeModel):
        payload.update(_tree_payload(model, "t_"))
    elif isinstance(model, (RandomForestModel, GradientBoostedTreesModel)):
        payload["n_trees"] = np.int64(len(model.trees))
        payload["task"] = np.str_(model.task)
        for i, t in enumerate(model.trees):
            payload.update(_tree_payload(t, f"tree{i}_"))
        if isinstance(model, RandomForestModel):
            payload["num_classes"] = np.int64(model.num_classes)
        else:
            payload["learning_rate"] = np.float64(model.learning_rate)
            payload["init_value"] = np.float64(model.init_value)
    elif isinstance(model, NaiveBayesModel):
        payload["model_type"] = np.str_(model.model_type)
        payload["log_pi"] = np.asarray(model.log_pi)
        if model.model_type == "gaussian":
            mean, var = model._gauss
            payload["mean"] = np.asarray(mean)
            payload["var"] = np.asarray(var)
        else:
            payload["log_theta"] = np.asarray(model.log_theta)
    elif isinstance(model, IsotonicRegressionModel):
        payload["boundaries"] = model.boundaries
        payload["predictions"] = model.predictions
        payload["increasing"] = np.bool_(model.increasing)
    elif isinstance(model, KMeansModel):
        payload["centers"] = np.asarray(model.centers)
        payload["cost"] = np.float64(model.cost)
        payload["iterations"] = np.int64(model.iterations)
    elif isinstance(model, PCAModel):
        payload["components"] = model.components
        payload["explained_variance"] = model.explained_variance
        payload["mean"] = model.mean
    elif isinstance(model, GaussianMixtureModel):
        payload["weights"] = model.weights
        payload["means"] = model.means
        payload["covariances"] = model.covariances
        payload["log_likelihood"] = np.float64(model.log_likelihood)
    elif isinstance(model, LDAModel):
        payload["topics"] = model.topics
        payload["doc_topics"] = model.doc_topics
        payload["alpha"] = np.float64(model.alpha)
        payload["hist"] = model.log_perplexity_history
    elif isinstance(model, ALSModel):
        payload["user_factors"] = model.user_factors
        payload["item_factors"] = model.item_factors
        payload["rank"] = np.int64(model.rank)
    elif isinstance(model, Word2VecModel):
        payload["vocab"] = np.asarray(model.vocab, dtype=np.str_)
        payload["vectors"] = np.asarray(model.vectors)
    elif isinstance(model, SVDPlusPlusModel):
        payload["user_vectors"] = np.asarray(model.user_vectors)
        payload["item_vectors"] = np.asarray(model.item_vectors)
        payload["user_bias"] = np.asarray(model.user_bias)
        payload["item_bias"] = np.asarray(model.item_bias)
        payload["mean"] = np.float64(model.mean)
    elif isinstance(model, SoftmaxRegressionModel):
        payload["W"] = model.W
        payload["b"] = model.b
        payload["loss_history"] = model.loss_history
    elif isinstance(model, LinearModel):  # covers logistic/SVM subclasses
        payload["weights"] = np.asarray(model.weights)
        payload["intercept"] = np.float64(model.intercept)
        payload["loss_history"] = np.asarray(model.loss_history)
        # the Warray-parity trajectory round-trips as indexed pairs
        payload["n_wh"] = np.int64(len(model.weight_history))
        for i, (t, w) in enumerate(model.weight_history):
            payload[f"wh_t_{i}"] = np.float64(t)
            payload[f"wh_w_{i}"] = np.asarray(w)
    else:
        raise TypeError(f"no persistence for {type(model).__name__}")
    return payload


def save_model(model: Any, path: Union[str, Path]) -> Path:
    """Persist a model to ``path`` (``.npz`` appended when absent)."""
    path = Path(path)
    if path.suffix != ".npz":
        path = path.with_suffix(path.suffix + ".npz")
    payload = _model_payload(model)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "wb") as f:  # direct handle: no double-buffered archive
        np.savez(f, **payload)
    return path


_TRANSFORMER_FIELDS = {
    "StandardScaler": ("mean_", "std_", "with_mean", "with_std"),
    "MinMaxScaler": ("min_", "max_", "lo", "hi"),
    "Normalizer": ("p",),
    "IDFModel": ("idf",),
}


def _transformer_payload(t: Any, prefix: str) -> Dict[str, Any]:
    name = type(t).__name__
    if name not in _TRANSFORMER_FIELDS:
        raise TypeError(f"no persistence for pipeline stage {name}")
    out: Dict[str, Any] = {f"{prefix}__class__": np.str_(name)}
    for field in _TRANSFORMER_FIELDS[name]:
        out[f"{prefix}{field}"] = np.asarray(getattr(t, field))
    return out


def _transformer_restore(z, prefix: str) -> Any:
    from asyncframework_tpu.ml.feature import (
        IDF,
        IDFModel,
        MinMaxScaler,
        Normalizer,
        StandardScaler,
    )

    name = str(z[f"{prefix}__class__"])
    if name == "StandardScaler":
        t = StandardScaler(
            with_mean=bool(z[f"{prefix}with_mean"]),
            with_std=bool(z[f"{prefix}with_std"]),
        )
        t.mean_ = np.asarray(z[f"{prefix}mean_"])
        t.std_ = np.asarray(z[f"{prefix}std_"])
        return t
    if name == "MinMaxScaler":
        t = MinMaxScaler(lo=float(z[f"{prefix}lo"]), hi=float(z[f"{prefix}hi"]))
        t.min_ = np.asarray(z[f"{prefix}min_"])
        t.max_ = np.asarray(z[f"{prefix}max_"])
        return t
    if name == "Normalizer":
        return Normalizer(p=float(z[f"{prefix}p"]))
    if name == "IDFModel":
        import jax.numpy as jnp

        return IDFModel(jnp.asarray(z[f"{prefix}idf"]))
    raise ValueError(f"unknown transformer tag {name}")


def load_model(path: Union[str, Path]) -> Any:
    path = Path(path)
    if not path.exists() and path.suffix != ".npz":
        path = path.with_suffix(path.suffix + ".npz")
    with np.load(path, allow_pickle=False) as z:
        return _model_restore({k: z[k] for k in z.files})


def _model_restore(z: Dict[str, Any]) -> Any:
    cls = str(z["__class__"])
    if cls == "PipelineModel":
        tfs = [
            _transformer_restore(z, f"tf{i}_")
            for i in range(int(z["n_tf"]))
        ]
        inner = {
            k[len("inner_"):]: v
            for k, v in z.items() if k.startswith("inner_")
        }
        return PipelineModel(
            transformers=tfs, model=_model_restore(inner)
        )
    if cls == "DecisionTreeModel":
        return _tree_restore(z, "t_")
    if cls in ("RandomForestModel", "GradientBoostedTreesModel"):
        trees = [
            _tree_restore(z, f"tree{i}_")
            for i in range(int(z["n_trees"]))
        ]
        if cls == "RandomForestModel":
            return RandomForestModel(
                trees=trees, task=str(z["task"]),
                num_classes=int(z["num_classes"]),
            )
        return GradientBoostedTreesModel(
            trees=trees, task=str(z["task"]),
            learning_rate=float(z["learning_rate"]),
            init_value=float(z["init_value"]),
        )
    if cls == "NaiveBayesModel":
        mtype = str(z["model_type"])
        if mtype == "gaussian":
            return NaiveBayesModel(
                np.asarray(z["log_pi"]), None, "gaussian",
                (np.asarray(z["mean"]), np.asarray(z["var"])),
            )
        return NaiveBayesModel(
            np.asarray(z["log_pi"]), np.asarray(z["log_theta"]), mtype
        )
    if cls == "IsotonicRegressionModel":
        return IsotonicRegressionModel(
            boundaries=np.asarray(z["boundaries"]),
            predictions=np.asarray(z["predictions"]),
            increasing=bool(z["increasing"]),
        )
    if cls == "KMeansModel":
        return KMeansModel(
            centers=np.asarray(z["centers"]), cost=float(z["cost"]),
            iterations=int(z["iterations"]),
        )
    if cls == "PCAModel":
        return PCAModel(
            components=np.asarray(z["components"]),
            explained_variance=np.asarray(z["explained_variance"]),
            mean=np.asarray(z["mean"]),
        )
    if cls == "GaussianMixtureModel":
        return GaussianMixtureModel(
            weights=np.asarray(z["weights"]),
            means=np.asarray(z["means"]),
            covariances=np.asarray(z["covariances"]),
            log_likelihood=float(z["log_likelihood"]),
        )
    if cls == "LDAModel":
        return LDAModel(
            topics=np.asarray(z["topics"]),
            doc_topics=np.asarray(z["doc_topics"]),
            alpha=float(z["alpha"]),
            log_perplexity_history=np.asarray(z["hist"]),
        )
    if cls == "ALSModel":
        return ALSModel(
            user_factors=np.asarray(z["user_factors"]),
            item_factors=np.asarray(z["item_factors"]),
            rank=int(z["rank"]),
        )
    if cls == "Word2VecModel":
        return Word2VecModel(
            vocab=[str(w) for w in z["vocab"]],
            vectors=np.asarray(z["vectors"]),
        )
    if cls == "SVDPlusPlusModel":
        return SVDPlusPlusModel(
            user_vectors=np.asarray(z["user_vectors"]),
            item_vectors=np.asarray(z["item_vectors"]),
            user_bias=np.asarray(z["user_bias"]),
            item_bias=np.asarray(z["item_bias"]),
            mean=float(z["mean"]),
        )
    if cls == "SoftmaxRegressionModel":
        return SoftmaxRegressionModel(
            W=np.asarray(z["W"]), b=np.asarray(z["b"]),
            loss_history=np.asarray(z["loss_history"]),
        )
    if cls in ("LinearModel", "LogisticRegressionModel", "SVMModel"):
        klass = {
            "LinearModel": LinearModel,
            "LogisticRegressionModel": LogisticRegressionModel,
            "SVMModel": SVMModel,
        }[cls]
        wh = [
            (float(z[f"wh_t_{i}"]), np.asarray(z[f"wh_w_{i}"]))
            for i in range(int(z["n_wh"])) if f"wh_t_{i}" in z
        ] if "n_wh" in z else []
        return klass(
            weights=np.asarray(z["weights"]),
            intercept=float(z["intercept"]),
            loss_history=np.asarray(z["loss_history"]),
            weight_history=wh,
        )
    raise ValueError(f"unknown model class tag {cls!r}")


def save_as_libsvm_file(
    X: np.ndarray, y: np.ndarray, path: Union[str, Path]
) -> Path:
    """``MLUtils.saveAsLibSVMFile`` parity (1-based indices, zeros skipped)."""
    X = np.asarray(X)
    y = np.asarray(y)
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            row = X[i]
            nz = np.nonzero(row)[0]
            feats = " ".join(f"{j + 1}:{row[j]:.9g}" for j in nz)
            f.write(f"{y[i]:.9g} {feats}\n".rstrip() + "\n")
    return path
