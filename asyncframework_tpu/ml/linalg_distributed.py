"""Distributed matrices: RowMatrix, IndexedRowMatrix, CoordinateMatrix,
BlockMatrix.

Parity (studied, not copied): ``mllib/src/main/scala/org/apache/spark/mllib/
linalg/distributed/RowMatrix.scala`` (computeGramianMatrix ~line 112,
computeSVD :493, computeCovariance, computeColumnSummaryStatistics,
columnSimilarities, tallSkinnyQR ~line 684), ``IndexedRowMatrix.scala``,
``CoordinateMatrix.scala``, ``BlockMatrix.scala`` (blocked multiply/add).

TPU mapping instead of RDD-of-rows:

- ``RowMatrix`` rows live batch-sharded over a mesh's ``dp`` axis; every
  aggregate (gram, covariance, column stats) is one per-device MXU matmul
  psum-merged over ICI -- the treeAggregate as a collective.
- ``tallSkinnyQR`` is a real two-stage TSQR: per-device local QR inside
  ``shard_map``, then one (P*d, d) QR of the stacked R factors -- the same
  communication-avoiding structure the reference builds out of
  treeAggregate, but with the local factorizations batched on device.
- ``columnSimilarities`` is exact (one gram matmul).  The reference's DIMSUM
  sampling exists because its gram is a shuffle over sparse rows; on the MXU
  the dense gram is the cheap path for the d <= a few-thousand regime this
  library targets.
- ``BlockMatrix`` keeps a (row-blocks x col-blocks) grid of device-resident
  dense blocks placed round-robin; multiply is the classic blocked SUMMA
  loop, each product one MXU matmul.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.ml.decomposition import _gram_and_mean, svd as _svd
from asyncframework_tpu.parallel.mesh import resolve_shard_map
from asyncframework_tpu.ml.stat import ColStats, col_stats


class RowMatrix:
    """A row-oriented distributed matrix; rows sharded over ``mesh``'s
    ``axis`` when one is given (otherwise single-device)."""

    def __init__(self, X, mesh: Optional[Mesh] = None, axis: str = "dp"):
        self.X = jnp.asarray(X, jnp.float32)
        if self.X.ndim != 2:
            raise ValueError("RowMatrix requires a 2-d array")
        self.mesh = mesh
        self.axis = axis

    # ------------------------------------------------------------ shape
    def num_rows(self) -> int:
        return int(self.X.shape[0])

    def num_cols(self) -> int:
        return int(self.X.shape[1])

    # ------------------------------------------------------ aggregates
    def compute_gramian(self) -> jax.Array:
        """A^T A, psum-combined over the mesh (computeGramianMatrix)."""
        _n, gram, _s = _gram_and_mean(self.X, self.mesh, self.axis)
        return gram

    def compute_column_summary_statistics(self) -> ColStats:
        return col_stats(self.X, self.mesh, self.axis)

    def compute_covariance(self) -> jax.Array:
        n, gram, colsum = _gram_and_mean(self.X, self.mesh, self.axis)
        mean = colsum / n
        return (gram - n * jnp.outer(mean, mean)) / max(n - 1, 1)

    def compute_svd(
        self, k: int, compute_u: bool = True, rcond: float = 1e-3
    ):
        """Truncated SVD via the gram matrix (RowMatrix.computeSVD:493)."""
        return _svd(
            self.X, k, self.mesh, self.axis, compute_u=compute_u, rcond=rcond
        )

    def compute_principal_components(self, k: int) -> np.ndarray:
        from asyncframework_tpu.ml.decomposition import PCA

        return PCA(k).fit(self.X, self.mesh, self.axis).components

    # ------------------------------------------------------------ products
    def multiply(self, B) -> "RowMatrix":
        """A @ B with B (d, m) replicated; result stays row-sharded."""
        B = jnp.asarray(B, jnp.float32)
        if self.mesh is None:
            return RowMatrix(self.X @ B)

        @partial(
            resolve_shard_map(),
            mesh=self.mesh,
            in_specs=(P(self.axis, None), P(None, None)),
            out_specs=P(self.axis, None),
        )
        def mm(Xl, Bl):
            return Xl @ Bl

        return RowMatrix(mm(self.X, B), self.mesh, self.axis)

    def column_similarities(self) -> jax.Array:
        """Cosine similarity between columns; exact upper-triangular
        (i < j) matrix, zero elsewhere (columnSimilarities parity)."""
        gram = self.compute_gramian()
        norms = jnp.sqrt(jnp.maximum(jnp.diag(gram), 1e-30))
        sims = gram / jnp.outer(norms, norms)
        d = sims.shape[0]
        return sims * jnp.triu(jnp.ones((d, d), sims.dtype), k=1)

    def tall_skinny_qr(self) -> Tuple["RowMatrix", jax.Array]:
        """TSQR: A = Q R with Q row-sharded, R (d, d) upper-triangular.

        Stage 1: each device QR-factors its local row block (batched on
        device).  Stage 2: the P stacked R factors get one small (P*d, d)
        QR.  Q = Q_local @ Q2_p -- one more local matmul.  Signs are
        normalized to a positive R diagonal so the factorization is
        deterministic across mesh sizes.
        """
        d = self.num_cols()
        if self.num_rows() < d:
            raise ValueError("tallSkinnyQR requires n >= d")
        if self.mesh is None:
            q, r = jnp.linalg.qr(self.X)
            sign = jnp.sign(jnp.where(jnp.diag(r) == 0, 1.0, jnp.diag(r)))
            return RowMatrix(q * sign[None, :]), r * sign[:, None]
        nper = self.X.shape[0] // self.mesh.shape[self.axis]
        if nper < d:
            # fewer local rows than columns: local QR would be rank-starved;
            # fall back to the single-pass factorization on gathered rows
            q, r = jnp.linalg.qr(self.X)
            sign = jnp.sign(jnp.where(jnp.diag(r) == 0, 1.0, jnp.diag(r)))
            return RowMatrix(q * sign[None, :]), r * sign[:, None]

        @partial(
            resolve_shard_map(),
            mesh=self.mesh,
            in_specs=P(self.axis, None),
            out_specs=(P(self.axis, None), P(self.axis, None)),
        )
        def local_qr(Xl):
            q, r = jnp.linalg.qr(Xl)
            return q, r

        Q1, Rs = local_qr(self.X)             # (n, d), (P*d, d)
        Q2, R = jnp.linalg.qr(Rs)             # (P*d, d), (d, d)
        sign = jnp.sign(jnp.where(jnp.diag(R) == 0, 1.0, jnp.diag(R)))
        R = R * sign[:, None]
        Q2 = Q2 * sign[None, :]

        @partial(
            resolve_shard_map(),
            mesh=self.mesh,
            in_specs=(P(self.axis, None), P(self.axis, None)),
            out_specs=P(self.axis, None),
        )
        def combine(Q1l, Q2l):
            return Q1l @ Q2l

        return RowMatrix(combine(Q1, Q2), self.mesh, self.axis), R


class IndexedRowMatrix:
    """Rows tagged with long indices (IndexedRowMatrix.scala parity)."""

    def __init__(self, indices, X, mesh: Optional[Mesh] = None,
                 axis: str = "dp"):
        self.indices = np.asarray(indices, np.int64)
        self.X = jnp.asarray(X, jnp.float32)
        if self.indices.shape[0] != self.X.shape[0]:
            raise ValueError("one index per row required")
        self.mesh = mesh
        self.axis = axis

    def num_rows(self) -> int:
        return int(self.indices.max()) + 1 if self.indices.size else 0

    def num_cols(self) -> int:
        return int(self.X.shape[1])

    def to_row_matrix(self) -> RowMatrix:
        return RowMatrix(self.X, self.mesh, self.axis)

    def to_coordinate_matrix(self) -> "CoordinateMatrix":
        Xh = np.asarray(self.X)
        r, c = np.nonzero(Xh)
        return CoordinateMatrix(
            self.indices[r], c.astype(np.int64), Xh[r, c],
            shape=(self.num_rows(), self.num_cols()),
        )

    def compute_svd(self, k: int, compute_u: bool = True):
        return self.to_row_matrix().compute_svd(k, compute_u=compute_u)

    def multiply(self, B) -> "IndexedRowMatrix":
        return IndexedRowMatrix(
            self.indices, self.to_row_matrix().multiply(B).X,
            self.mesh, self.axis,
        )


class CoordinateMatrix:
    """COO-format distributed matrix (CoordinateMatrix.scala parity)."""

    def __init__(self, rows, cols, values, shape: Tuple[int, int]):
        self.rows = np.asarray(rows, np.int64)
        self.cols = np.asarray(cols, np.int64)
        self.values = np.asarray(values, np.float32)
        if not (self.rows.shape == self.cols.shape == self.values.shape):
            raise ValueError("rows/cols/values must align")
        self.shape = (int(shape[0]), int(shape[1]))

    def num_rows(self) -> int:
        return self.shape[0]

    def num_cols(self) -> int:
        return self.shape[1]

    def transpose(self) -> "CoordinateMatrix":
        return CoordinateMatrix(
            self.cols, self.rows, self.values, (self.shape[1], self.shape[0])
        )

    def to_local(self) -> jax.Array:
        """Densify on device: one scatter-add (duplicate entries sum, the
        reference's toBlockMatrix behavior)."""
        dense = jnp.zeros(self.shape, jnp.float32)
        return dense.at[
            jnp.asarray(self.rows), jnp.asarray(self.cols)
        ].add(jnp.asarray(self.values))

    def to_row_matrix(self, mesh: Optional[Mesh] = None,
                      axis: str = "dp") -> RowMatrix:
        return RowMatrix(self.to_local(), mesh, axis)

    def to_indexed_row_matrix(self) -> IndexedRowMatrix:
        dense = self.to_local()
        return IndexedRowMatrix(np.arange(self.shape[0]), dense)

    def to_block_matrix(self, block_size: int = 1024) -> "BlockMatrix":
        return BlockMatrix.from_dense(
            self.to_local(), block_size=block_size
        )


class BlockMatrix:
    """Grid of dense blocks, each resident on a device (round-robin).

    ``multiply`` is the blocked SUMMA loop: C[i,j] = sum_k A[i,k] B[k,j],
    every term one MXU matmul (BlockMatrix.scala multiply parity -- the
    reference's simulateMultiply/shuffle plan collapses to device placement
    here).
    """

    def __init__(
        self,
        blocks: Dict[Tuple[int, int], jax.Array],
        shape: Tuple[int, int],
        block_size: int,
    ):
        self.blocks = blocks
        self.shape = (int(shape[0]), int(shape[1]))
        self.block_size = int(block_size)
        self.grid = (
            -(-self.shape[0] // self.block_size),
            -(-self.shape[1] // self.block_size),
        )

    @classmethod
    def from_dense(
        cls, A, block_size: int = 1024, devices=None
    ) -> "BlockMatrix":
        A = jnp.asarray(A, jnp.float32)
        n, m = A.shape
        devs = list(devices) if devices is not None else jax.devices()
        gr = -(-n // block_size)
        gc = -(-m // block_size)
        blocks: Dict[Tuple[int, int], jax.Array] = {}
        for i in range(gr):
            for j in range(gc):
                blk = A[
                    i * block_size: min((i + 1) * block_size, n),
                    j * block_size: min((j + 1) * block_size, m),
                ]
                dev = devs[(i * gc + j) % len(devs)]
                blocks[(i, j)] = jax.device_put(blk, dev)
        return cls(blocks, (n, m), block_size)

    def to_local(self) -> np.ndarray:
        out = np.zeros(self.shape, np.float32)
        bs = self.block_size
        for (i, j), blk in self.blocks.items():
            b = np.asarray(blk)
            out[i * bs: i * bs + b.shape[0], j * bs: j * bs + b.shape[1]] = b
        return out

    def transpose(self) -> "BlockMatrix":
        return BlockMatrix(
            {(j, i): blk.T for (i, j), blk in self.blocks.items()},
            (self.shape[1], self.shape[0]),
            self.block_size,
        )

    def add(self, other: "BlockMatrix") -> "BlockMatrix":
        if self.shape != other.shape or self.block_size != other.block_size:
            raise ValueError("add requires identical shape and block size")
        keys = set(self.blocks) | set(other.blocks)
        out = {}
        for key in keys:
            a = self.blocks.get(key)
            b = other.blocks.get(key)
            out[key] = a + b if (a is not None and b is not None) else (
                a if a is not None else b
            )
        return BlockMatrix(out, self.shape, self.block_size)

    def multiply(self, other: "BlockMatrix") -> "BlockMatrix":
        if self.shape[1] != other.shape[0]:
            raise ValueError(
                f"inner dims mismatch: {self.shape} x {other.shape}"
            )
        if self.block_size != other.block_size:
            raise ValueError("multiply requires matching block size")
        gr, gk = self.grid
        _, gc = other.grid
        out: Dict[Tuple[int, int], jax.Array] = {}
        for i in range(gr):
            for j in range(gc):
                acc = None
                for k in range(gk):
                    a = self.blocks.get((i, k))
                    b = other.blocks.get((k, j))
                    if a is None or b is None:
                        continue
                    if b.device != a.device:
                        b = jax.device_put(b, a.device)
                    term = a @ b
                    if acc is None:
                        acc = term
                    else:
                        # terms for C[i,j] come from different k-blocks'
                        # homes; accumulate on the first term's device
                        if term.device != acc.device:
                            term = jax.device_put(term, acc.device)
                        acc = acc + term
                if acc is not None:
                    out[(i, j)] = acc
        return BlockMatrix(out, (self.shape[0], other.shape[1]),
                           self.block_size)
