"""Gradient-boosted trees.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/tree/
GradientBoostedTrees.scala`` -- sequential stages of regression trees fit to
the loss gradient (squared error: residuals; logistic: sigmoid residuals),
combined with a learning rate; classification margins thresholded at 0.

TPU mapping: every stage reuses the histogram tree (one device scatter-add
per level), and the running prediction/residual updates are elementwise
device ops -- boosting adds no new kernel shapes, just the stage loop.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import numpy as np

from asyncframework_tpu.ml.tree import DecisionTree, DecisionTreeModel


@dataclass
class GradientBoostedTreesModel:
    trees: List[DecisionTreeModel]
    learning_rate: float
    init_value: float
    task: str

    def raw_predict(self, X) -> np.ndarray:
        X = np.asarray(X, np.float32)
        out = np.full(X.shape[0], self.init_value, np.float32)
        for t in self.trees:
            out += self.learning_rate * t.predict(X).astype(np.float32)
        return out

    def predict(self, X) -> np.ndarray:
        raw = self.raw_predict(X)
        if self.task == "classification":
            return (raw >= 0.0).astype(np.int64)
        return raw


class GradientBoostedTrees:
    """``GradientBoostedTrees.train`` analog.

    ``task='regression'``: squared-error boosting (stages fit residuals).
    ``task='classification'``: binary labels {0,1} via logistic loss on the
    +-1 margin formulation, like the reference's ``LogLoss``.
    """

    def __init__(
        self,
        task: str = "regression",
        num_iterations: int = 20,
        learning_rate: float = 0.1,
        max_depth: int = 3,
        max_bins: int = 32,
    ):
        if task not in ("regression", "classification"):
            raise ValueError("task must be regression or classification")
        if not 0 < learning_rate <= 1:
            raise ValueError("learning_rate must be in (0, 1]")
        self.task = task
        self.num_iterations = num_iterations
        self.learning_rate = learning_rate
        self.max_depth = max_depth
        self.max_bins = max_bins

    def fit(self, X, y) -> GradientBoostedTreesModel:
        X = np.asarray(X, np.float32)
        if self.task == "regression":
            target = np.asarray(y, np.float32)
            init = float(target.mean())
        else:
            labels = np.asarray(y).astype(np.float32)
            if not set(np.unique(labels)) <= {0.0, 1.0}:
                raise ValueError("classification labels must be {0, 1}")
            y_pm = 2.0 * labels - 1.0  # {-1, +1} margins (LogLoss parity)
            p = float(labels.mean())
            p = min(max(p, 1e-6), 1 - 1e-6)
            init = float(np.log(p / (1 - p)) / 2.0)

        raw = np.full(X.shape[0], init, np.float32)
        trees: List[DecisionTreeModel] = []
        for _ in range(self.num_iterations):
            if self.task == "regression":
                grad = target - raw  # negative gradient of squared error
            else:
                # -dLogLoss/draw for the +-1 formulation:
                # 2y / (1 + exp(2 y raw))
                grad = np.asarray(
                    2.0 * y_pm / (1.0 + np.exp(2.0 * y_pm * raw)),
                    np.float32,
                )
            stage = DecisionTree(
                task="regression",
                max_depth=self.max_depth,
                max_bins=self.max_bins,
            ).fit(X, grad)
            trees.append(stage)
            raw = raw + self.learning_rate * stage.predict(X).astype(
                np.float32
            )
        return GradientBoostedTreesModel(
            trees=trees,
            learning_rate=self.learning_rate,
            init_value=init,
            task=self.task,
        )
