"""Loss-gradient families for linear models.

Parity: ``mllib/.../optimization/Gradient.scala`` --
``LeastSquaresGradient`` (:285), ``LogisticGradient`` binary case (:166),
``HingeGradient`` (SVM).  The reference computes per-sample ``(grad, loss)``
pairs that a ``treeAggregate`` sums; on TPU a whole masked batch is two
matmuls on the MXU, so the unit here is a *batch*: ``local(X, y, w, mask)``
returns the summed gradient and summed loss over ``mask``-selected rows.
All methods are pure and jax-traceable (usable inside ``jit``/``shard_map``).

Label conventions match MLlib: logistic and hinge take labels in {0, 1}
(hinge internally rescales to {-1, +1} exactly as ``HingeGradient`` does).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


class Gradient:
    """Batched (summed) loss/gradient over masked rows."""

    def local(
        self, X: jax.Array, y: jax.Array, w: jax.Array, mask: jax.Array
    ) -> Tuple[jax.Array, jax.Array]:
        """Returns ``(grad_sum, loss_sum)`` over rows where ``mask`` is 1."""
        raise NotImplementedError

    def loss(self, X: jax.Array, y: jax.Array, w: jax.Array) -> jax.Array:
        """Summed loss over all rows (evaluation path)."""
        ones = jnp.ones(X.shape[0], X.dtype)
        return self.local(X, y, w, ones)[1]


class LeastSquaresGradient(Gradient):
    """loss_i = (x_i.w - y_i)^2 / 2;  grad_i = (x_i.w - y_i) x_i."""

    def local(self, X, y, w, mask):
        r = X @ w - y
        g = X.T @ (mask * r)
        return g, 0.5 * jnp.sum(mask * r * r)


class LogisticGradient(Gradient):
    """Binary logistic loss, labels in {0,1}.

    loss_i = log(1 + e^{x_i.w}) - y_i (x_i.w);
    grad_i = (sigmoid(x_i.w) - y_i) x_i.
    """

    def local(self, X, y, w, mask):
        m = X @ w
        p = jax.nn.sigmoid(m)
        g = X.T @ (mask * (p - y))
        loss = jnp.sum(mask * (jnp.logaddexp(0.0, m) - y * m))
        return g, loss


class HingeGradient(Gradient):
    """SVM hinge loss, labels in {0,1} rescaled to s = 2y-1.

    If ``1 - s (x_i.w) > 0``: loss_i = that margin, grad_i = -s x_i; else 0.
    """

    def local(self, X, y, w, mask):
        s = 2.0 * y - 1.0
        m = X @ w
        viol = 1.0 - s * m
        active = (viol > 0).astype(X.dtype) * mask
        g = X.T @ (-s * active)
        loss = jnp.sum(jnp.maximum(viol, 0.0) * mask)
        return g, loss
