"""Naive Bayes classifiers.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/classification/
NaiveBayes.scala`` -- multinomial and Bernoulli model types with Laplace
smoothing ``lambda``; prediction is ``argmax_c (log pi_c + x . log theta_c)``.
A Gaussian variant is added for continuous features (the reference's ml
package gained one later; same structure).

TPU mapping: training is per-class feature aggregation -- one
``segment_sum`` over the label codes (the scatter-combine replacing the
reference's aggregateByKey job) -- and prediction is one matmul against the
log-probability matrix, which lands on the MXU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class NaiveBayesModel:
    def __init__(self, log_pi, log_theta, model_type: str,
                 gaussian_stats=None):
        self.log_pi = log_pi          # (C,)
        self.log_theta = log_theta    # (C, D) or None for gaussian
        self.model_type = model_type
        self._gauss = gaussian_stats  # (mean (C,D), var (C,D)) for gaussian

    def predict_log_likelihood(self, X) -> jax.Array:
        X = jnp.asarray(X, jnp.float32)
        if self.model_type == "multinomial":
            return self.log_pi + X @ self.log_theta.T
        if self.model_type == "bernoulli":
            # log P = x.log(t) + (1-x).log(1-t), folded into one matmul
            log_t = self.log_theta
            log_1mt = jnp.log1p(-jnp.exp(log_t))
            return self.log_pi + X @ (log_t - log_1mt).T + jnp.sum(
                log_1mt, axis=1
            )
        mean, var = self._gauss
        # fully-batched gaussian log-likelihood: (N,1,D) against (C,D)
        z = (X[:, None, :] - mean[None]) ** 2 / var[None]
        return self.log_pi - 0.5 * jnp.sum(
            z + jnp.log(2 * jnp.pi * var)[None], axis=2
        )

    def predict(self, X) -> np.ndarray:
        return np.asarray(jnp.argmax(self.predict_log_likelihood(X), axis=1))


class NaiveBayes:
    """``NaiveBayes.train(data, lambda, modelType)`` analog."""

    def __init__(self, smoothing: float = 1.0,
                 model_type: str = "multinomial"):
        if model_type not in ("multinomial", "bernoulli", "gaussian"):
            raise ValueError(
                "model_type must be multinomial, bernoulli, or gaussian"
            )
        if smoothing < 0:
            raise ValueError("smoothing must be >= 0")
        self.smoothing = smoothing
        self.model_type = model_type

    def fit(self, X, y, num_classes: Optional[int] = None) -> NaiveBayesModel:
        X = jnp.asarray(X, jnp.float32)
        labels = np.asarray(y).astype(np.int32)
        C = num_classes or int(labels.max()) + 1
        codes = jnp.asarray(labels)
        counts = jax.ops.segment_sum(
            jnp.ones_like(codes, jnp.float32), codes, C
        )
        log_pi = jnp.log(counts) - jnp.log(counts.sum())
        lam = self.smoothing
        if self.model_type == "gaussian":
            s1 = jax.ops.segment_sum(X, codes, C)
            s2 = jax.ops.segment_sum(X * X, codes, C)
            mean = s1 / counts[:, None]
            var = s2 / counts[:, None] - mean**2
            # variance smoothing: epsilon of the max variance (sklearn-style)
            eps = 1e-9 * float(jnp.max(var)) + 1e-12
            return NaiveBayesModel(log_pi, None, "gaussian",
                                   (mean, var + eps))
        if self.model_type == "bernoulli":
            ones = jax.ops.segment_sum((X > 0).astype(jnp.float32), codes, C)
            theta = (ones + lam) / (counts[:, None] + 2 * lam)
            return NaiveBayesModel(log_pi, jnp.log(theta), "bernoulli")
        # multinomial: theta_cd = (sum of feature d in class c + lam) / ...
        feat = jax.ops.segment_sum(X, codes, C)
        num = feat + lam
        den = feat.sum(axis=1, keepdims=True) + lam * X.shape[1]
        return NaiveBayesModel(log_pi, jnp.log(num) - jnp.log(den),
                               "multinomial")
