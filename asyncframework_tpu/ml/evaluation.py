"""Evaluation metrics.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/evaluation/`` --
``BinaryClassificationMetrics.scala`` (ROC/AUC/PR by score thresholds),
``RegressionMetrics.scala``, ``MulticlassMetrics.scala`` (confusion-matrix
derived precision/recall/F1).

TPU mapping: the reference computes these with sort-and-aggregate jobs over
RDDs; here a metric is one device program -- sort by score (XLA sort),
cumulative TP/FP (scan/cumsum), trapezoid AUC (one reduction).  Everything
is O(n log n) on device with static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------- binary
@jax.jit
def _roc_points(scores: jax.Array, labels: jax.Array):
    """Sorted-by-score-descending cumulative TP/FP counts."""
    order = jnp.argsort(-scores)
    y = labels[order].astype(jnp.float32)
    tp = jnp.cumsum(y)
    fp = jnp.cumsum(1.0 - y)
    return tp, fp, scores[order]


class BinaryClassificationMetrics:
    """AUC-ROC / AUC-PR / curves from (score, label in {0,1}) pairs.

    Ties in scores are handled like the reference: threshold points are
    taken at distinct score boundaries, so tied scores move as one block.
    """

    def __init__(self, scores, labels):
        scores = jnp.asarray(scores, jnp.float32)
        labels = jnp.asarray(labels, jnp.float32)
        if scores.shape != labels.shape:
            raise ValueError("scores and labels must have the same shape")
        self._n = int(scores.shape[0])
        tp, fp, sorted_scores = _roc_points(scores, labels)
        # collapse tied scores: keep the LAST cumulative point of each block
        s = np.asarray(sorted_scores)
        tp = np.asarray(tp)
        fp = np.asarray(fp)
        is_boundary = np.ones(self._n, bool)
        if self._n > 1:
            is_boundary[:-1] = s[:-1] != s[1:]
        self._tp = tp[is_boundary]
        self._fp = fp[is_boundary]
        self._thresholds = s[is_boundary]
        self._p = float(tp[-1]) if self._n else 0.0
        self._neg = float(fp[-1]) if self._n else 0.0

    def roc(self) -> Tuple[np.ndarray, np.ndarray]:
        """(fpr, tpr) points, starting at (0,0) and ending at (1,1)."""
        tpr = np.concatenate([[0.0], self._tp / max(self._p, 1e-12)])
        fpr = np.concatenate([[0.0], self._fp / max(self._neg, 1e-12)])
        return fpr, tpr

    def area_under_roc(self) -> float:
        fpr, tpr = self.roc()
        return float(np.trapezoid(tpr, fpr))

    def pr(self) -> Tuple[np.ndarray, np.ndarray]:
        """(recall, precision) points; first point (0, p0) like the
        reference (precision of the highest-score block)."""
        recall = self._tp / max(self._p, 1e-12)
        precision = self._tp / np.maximum(self._tp + self._fp, 1e-12)
        return (
            np.concatenate([[0.0], recall]),
            np.concatenate([[precision[0] if len(precision) else 1.0],
                            precision]),
        )

    def area_under_pr(self) -> float:
        recall, precision = self.pr()
        return float(np.trapezoid(precision, recall))

    def thresholds(self) -> np.ndarray:
        return self._thresholds


# ------------------------------------------------------------- regression
@jax.jit
def _regression_sums(pred, y):
    err = pred - y
    return (
        jnp.sum(err * err),
        jnp.sum(jnp.abs(err)),
        jnp.sum(y),
        jnp.sum(y * y),
        jnp.sum(err),
    )


@dataclass(frozen=True)
class RegressionMetrics:
    """mse / rmse / mae / r2 / explained variance over (pred, label)."""

    mean_squared_error: float
    root_mean_squared_error: float
    mean_absolute_error: float
    r2: float
    explained_variance: float

    @classmethod
    def of(cls, predictions, labels) -> "RegressionMetrics":
        pred = jnp.asarray(predictions, jnp.float32)
        y = jnp.asarray(labels, jnp.float32)
        n = y.shape[0]
        sse, sae, sy, syy, serr = (float(v) for v in _regression_sums(pred, y))
        mse = sse / n
        var_y = syy / n - (sy / n) ** 2
        # explained variance: Var(y) - Var(err) (the reference's definition)
        var_err = sse / n - (serr / n) ** 2
        return cls(
            mean_squared_error=mse,
            root_mean_squared_error=float(np.sqrt(mse)),
            mean_absolute_error=sae / n,
            r2=1.0 - sse / max(n * var_y, 1e-12),
            explained_variance=var_y - var_err,
        )


# -------------------------------------------------------------- multiclass
class MulticlassMetrics:
    """Confusion-matrix metrics over (prediction, label) integer pairs."""

    def __init__(self, predictions, labels, num_classes: Optional[int] = None):
        pred = np.asarray(predictions).astype(np.int64)
        y = np.asarray(labels).astype(np.int64)
        k = num_classes or int(max(pred.max(initial=0), y.max(initial=0))) + 1
        cm = jnp.zeros((k, k), jnp.int32).at[y, pred].add(1)
        self.confusion = np.asarray(cm)
        self._k = k
        self._n = len(y)

    @property
    def accuracy(self) -> float:
        return float(np.trace(self.confusion)) / max(self._n, 1)

    def precision(self, label: int) -> float:
        col = self.confusion[:, label].sum()
        return float(self.confusion[label, label]) / max(col, 1)

    def recall(self, label: int) -> float:
        row = self.confusion[label, :].sum()
        return float(self.confusion[label, label]) / max(row, 1)

    def f1(self, label: int) -> float:
        p, r = self.precision(label), self.recall(label)
        return 2 * p * r / max(p + r, 1e-12)

    def weighted_f1(self) -> float:
        weights = self.confusion.sum(axis=1) / max(self._n, 1)
        return float(sum(w * self.f1(i) for i, w in enumerate(weights)))


class RankingMetrics:
    """Ranking quality over (predicted ranking, ground-truth set) pairs.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/evaluation/
    RankingMetrics.scala`` -- precisionAt(k), meanAveragePrecision, and
    ndcgAt(k) with the reference's exact conventions: predictions beyond
    position k are ignored, queries with empty ground truth contribute 0
    (and log a warning there; silently here), relevance is binary, and
    the IDCG normalizer uses min(|truth|, k) ideal hits.

    Host-side: rankings are short, ragged integer lists; there is no dense
    kernel to win on device.
    """

    def __init__(self, prediction_and_labels):
        self._pairs = [
            (list(pred), set(truth)) for pred, truth in prediction_and_labels
        ]
        if not self._pairs:
            raise ValueError("no (prediction, labels) pairs")

    def precision_at(self, k: int) -> float:
        if k < 1:
            raise ValueError("k must be >= 1")
        vals = []
        for pred, truth in self._pairs:
            top = pred[:k]
            hits = sum(1 for p in top if p in truth)
            # reference divides by k even when fewer predictions exist
            vals.append(hits / k)
        return float(np.mean(vals))

    def mean_average_precision(self) -> float:
        vals = []
        for pred, truth in self._pairs:
            if not truth:
                vals.append(0.0)
                continue
            hits = 0
            score = 0.0
            # duplicate predictions each count (reference semantics:
            # RankingMetrics.scala scans positions, not distinct items)
            for i, p in enumerate(pred):
                if p in truth:
                    hits += 1
                    score += hits / (i + 1.0)
            vals.append(score / len(truth))
        return float(np.mean(vals))

    def ndcg_at(self, k: int) -> float:
        if k < 1:
            raise ValueError("k must be >= 1")
        vals = []
        for pred, truth in self._pairs:
            if not truth:
                vals.append(0.0)
                continue
            n = min(k, len(pred))
            dcg = sum(
                1.0 / np.log2(i + 2.0)
                for i in range(n) if pred[i] in truth
            )
            ideal = sum(
                1.0 / np.log2(i + 2.0) for i in range(min(len(truth), k))
            )
            vals.append(dcg / ideal)
        return float(np.mean(vals))


class MultilabelMetrics:
    """Multi-label classification metrics over (predicted set, true set)
    pairs.

    Parity: ``mllib/.../evaluation/MultilabelMetrics.scala`` -- document-
    averaged accuracy/precision/recall/F1, subset accuracy, Hamming loss,
    and micro-averaged precision/recall/F1 over the label universe.
    """

    def __init__(self, prediction_and_labels):
        self._pairs = [
            (set(pred), set(truth)) for pred, truth in prediction_and_labels
        ]
        if not self._pairs:
            raise ValueError("no (prediction, labels) pairs")
        # label universe from GROUND TRUTH only (MultilabelMetrics.scala
        # derives numLabels from the label sets; counting predicted-only
        # labels would deflate hamming_loss)
        self._labels = sorted({x for _p, t in self._pairs for x in t})

    @property
    def accuracy(self) -> float:
        return float(np.mean([
            len(p & t) / max(len(p | t), 1) for p, t in self._pairs
        ]))

    @property
    def precision(self) -> float:
        return float(np.mean([
            len(p & t) / len(p) if p else 0.0 for p, t in self._pairs
        ]))

    @property
    def recall(self) -> float:
        return float(np.mean([
            len(p & t) / len(t) if t else 0.0 for p, t in self._pairs
        ]))

    @property
    def f1_measure(self) -> float:
        return float(np.mean([
            2.0 * len(p & t) / (len(p) + len(t))
            if (p or t) else 0.0
            for p, t in self._pairs
        ]))

    @property
    def subset_accuracy(self) -> float:
        return float(np.mean([p == t for p, t in self._pairs]))

    @property
    def hamming_loss(self) -> float:
        n_labels = max(len(self._labels), 1)
        return float(np.mean([
            len(p ^ t) / n_labels for p, t in self._pairs
        ]))

    @property
    def micro_precision(self) -> float:
        tp = sum(len(p & t) for p, t in self._pairs)
        fp = sum(len(p - t) for p, t in self._pairs)
        return tp / max(tp + fp, 1)

    @property
    def micro_recall(self) -> float:
        tp = sum(len(p & t) for p, t in self._pairs)
        fn = sum(len(t - p) for p, t in self._pairs)
        return tp / max(tp + fn, 1)

    @property
    def micro_f1_measure(self) -> float:
        p, r = self.micro_precision, self.micro_recall
        return 2 * p * r / max(p + r, 1e-12)
