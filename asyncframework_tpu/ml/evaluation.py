"""Evaluation metrics.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/evaluation/`` --
``BinaryClassificationMetrics.scala`` (ROC/AUC/PR by score thresholds),
``RegressionMetrics.scala``, ``MulticlassMetrics.scala`` (confusion-matrix
derived precision/recall/F1).

TPU mapping: the reference computes these with sort-and-aggregate jobs over
RDDs; here a metric is one device program -- sort by score (XLA sort),
cumulative TP/FP (scan/cumsum), trapezoid AUC (one reduction).  Everything
is O(n log n) on device with static shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------- binary
@jax.jit
def _roc_points(scores: jax.Array, labels: jax.Array):
    """Sorted-by-score-descending cumulative TP/FP counts."""
    order = jnp.argsort(-scores)
    y = labels[order].astype(jnp.float32)
    tp = jnp.cumsum(y)
    fp = jnp.cumsum(1.0 - y)
    return tp, fp, scores[order]


class BinaryClassificationMetrics:
    """AUC-ROC / AUC-PR / curves from (score, label in {0,1}) pairs.

    Ties in scores are handled like the reference: threshold points are
    taken at distinct score boundaries, so tied scores move as one block.
    """

    def __init__(self, scores, labels):
        scores = jnp.asarray(scores, jnp.float32)
        labels = jnp.asarray(labels, jnp.float32)
        if scores.shape != labels.shape:
            raise ValueError("scores and labels must have the same shape")
        self._n = int(scores.shape[0])
        tp, fp, sorted_scores = _roc_points(scores, labels)
        # collapse tied scores: keep the LAST cumulative point of each block
        s = np.asarray(sorted_scores)
        tp = np.asarray(tp)
        fp = np.asarray(fp)
        is_boundary = np.ones(self._n, bool)
        if self._n > 1:
            is_boundary[:-1] = s[:-1] != s[1:]
        self._tp = tp[is_boundary]
        self._fp = fp[is_boundary]
        self._thresholds = s[is_boundary]
        self._p = float(tp[-1]) if self._n else 0.0
        self._neg = float(fp[-1]) if self._n else 0.0

    def roc(self) -> Tuple[np.ndarray, np.ndarray]:
        """(fpr, tpr) points, starting at (0,0) and ending at (1,1)."""
        tpr = np.concatenate([[0.0], self._tp / max(self._p, 1e-12)])
        fpr = np.concatenate([[0.0], self._fp / max(self._neg, 1e-12)])
        return fpr, tpr

    def area_under_roc(self) -> float:
        fpr, tpr = self.roc()
        return float(np.trapezoid(tpr, fpr))

    def pr(self) -> Tuple[np.ndarray, np.ndarray]:
        """(recall, precision) points; first point (0, p0) like the
        reference (precision of the highest-score block)."""
        recall = self._tp / max(self._p, 1e-12)
        precision = self._tp / np.maximum(self._tp + self._fp, 1e-12)
        return (
            np.concatenate([[0.0], recall]),
            np.concatenate([[precision[0] if len(precision) else 1.0],
                            precision]),
        )

    def area_under_pr(self) -> float:
        recall, precision = self.pr()
        return float(np.trapezoid(precision, recall))

    def thresholds(self) -> np.ndarray:
        return self._thresholds


# ------------------------------------------------------------- regression
@jax.jit
def _regression_sums(pred, y):
    err = pred - y
    return (
        jnp.sum(err * err),
        jnp.sum(jnp.abs(err)),
        jnp.sum(y),
        jnp.sum(y * y),
        jnp.sum(err),
    )


@dataclass(frozen=True)
class RegressionMetrics:
    """mse / rmse / mae / r2 / explained variance over (pred, label)."""

    mean_squared_error: float
    root_mean_squared_error: float
    mean_absolute_error: float
    r2: float
    explained_variance: float

    @classmethod
    def of(cls, predictions, labels) -> "RegressionMetrics":
        pred = jnp.asarray(predictions, jnp.float32)
        y = jnp.asarray(labels, jnp.float32)
        n = y.shape[0]
        sse, sae, sy, syy, serr = (float(v) for v in _regression_sums(pred, y))
        mse = sse / n
        var_y = syy / n - (sy / n) ** 2
        # explained variance: Var(y) - Var(err) (the reference's definition)
        var_err = sse / n - (serr / n) ** 2
        return cls(
            mean_squared_error=mse,
            root_mean_squared_error=float(np.sqrt(mse)),
            mean_absolute_error=sae / n,
            r2=1.0 - sse / max(n * var_y, 1e-12),
            explained_variance=var_y - var_err,
        )


# -------------------------------------------------------------- multiclass
class MulticlassMetrics:
    """Confusion-matrix metrics over (prediction, label) integer pairs."""

    def __init__(self, predictions, labels, num_classes: Optional[int] = None):
        pred = np.asarray(predictions).astype(np.int64)
        y = np.asarray(labels).astype(np.int64)
        k = num_classes or int(max(pred.max(initial=0), y.max(initial=0))) + 1
        cm = jnp.zeros((k, k), jnp.int32).at[y, pred].add(1)
        self.confusion = np.asarray(cm)
        self._k = k
        self._n = len(y)

    @property
    def accuracy(self) -> float:
        return float(np.trace(self.confusion)) / max(self._n, 1)

    def precision(self, label: int) -> float:
        col = self.confusion[:, label].sum()
        return float(self.confusion[label, label]) / max(col, 1)

    def recall(self, label: int) -> float:
        row = self.confusion[label, :].sum()
        return float(self.confusion[label, label]) / max(row, 1)

    def f1(self, label: int) -> float:
        p, r = self.precision(label), self.recall(label)
        return 2 * p * r / max(p + r, 1e-12)

    def weighted_f1(self) -> float:
        weights = self.confusion.sum(axis=1) / max(self._n, 1)
        return float(sum(w * self.f1(i) for i, w in enumerate(weights)))
