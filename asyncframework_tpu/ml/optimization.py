"""Optimizers: mini-batch gradient descent and L-BFGS.

Parity:
- ``GradientDescent.runMiniBatchSGD`` (``mllib/.../GradientDescent.scala:197-295``):
  per 1-indexed iteration, Bernoulli-sample fraction ``b``, aggregate
  ``(grad_sum, loss_sum, count)``, record ``loss_sum/count + reg_val(prev)``
  in the stochastic loss history, update via the pluggable ``Updater``;
  convergence tolerance on the weight-vector delta
  (``GradientDescent.scala:300-310``: ``||w_t - w_{t-1}|| < tol * max(||w_t||, 1)``).
- The fork's trajectory delta: ``Warray: ListBuffer[(wallclock, weights)]``
  appended every 100 iterations (``GradientDescent.scala:156,255-259``) and
  surfaced through ``Optimizer.getAllWeights`` (``Optimizer.scala:39-40``) --
  here :meth:`GradientDescent.get_all_weights`, recorded every
  ``snapshot_every`` iterations.
- ``LBFGS.scala:42`` (breeze L-BFGS over a full-batch ``CostFun``): here a
  host-driven two-loop-recursion L-BFGS whose full-batch value+gradient is one
  jitted SPMD computation per evaluation.

TPU re-design: the reference launches one cluster job per iteration/evaluation;
here the SGD loop is a single compiled ``shard_map`` + ``lax.scan`` program
(data stays in HBM, `psum` over ICI per step), and L-BFGS's direction/line
search bookkeeping (tiny, O(m*d) on host) wraps a jitted loss/grad kernel.
"""

from __future__ import annotations

import time
from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.ml.gradient import Gradient, LeastSquaresGradient
from asyncframework_tpu.ml.updater import SimpleUpdater, Updater
from asyncframework_tpu.parallel.mesh import (
    make_mesh,
    pad_and_shard,
    resolve_shard_map,
)


class GradientDescent:
    """Mini-batch SGD with pluggable :class:`Gradient` / :class:`Updater`.

    The whole optimization loop compiles to one XLA program; the stochastic
    loss history and weight snapshots come back as stacked scan outputs.
    """

    def __init__(
        self,
        gradient: Optional[Gradient] = None,
        updater: Optional[Updater] = None,
        step_size: float = 1.0,
        num_iterations: int = 100,
        reg_param: float = 0.0,
        mini_batch_fraction: float = 1.0,
        convergence_tol: float = 0.0,
        seed: int = 42,
        snapshot_every: int = 100,
    ):
        self.gradient = gradient or LeastSquaresGradient()
        self.updater = updater or SimpleUpdater()
        self.step_size = step_size
        self.num_iterations = num_iterations
        self.reg_param = reg_param
        self.mini_batch_fraction = mini_batch_fraction
        self.convergence_tol = convergence_tol
        self.seed = seed
        self.snapshot_every = snapshot_every
        self._weight_history: List[Tuple[float, np.ndarray]] = []
        self._train_cache: dict = {}

    def _build(self, mesh: Mesh, want_full: bool, axis: str = "dp"):
        grad, upd = self.gradient, self.updater
        b = self.mini_batch_fraction
        step_size, reg = self.step_size, self.reg_param
        T = self.num_iterations
        every = self.snapshot_every
        # snapshots at iterations every, 2*every, ... plus always the final
        # iterate (Warray cadence: GradientDescent.scala:255-259 appends
        # every 100 iterations)
        n_snaps = max(T // every, 1)

        def body(carry, it, X, y, valid):
            w, key, prev_reg_val, snaps = carry
            key, sub = jax.random.split(key)
            sub = jax.random.fold_in(sub, jax.lax.axis_index(axis))
            mask = jax.random.bernoulli(sub, b, (X.shape[0],)).astype(X.dtype)
            mask = mask * valid
            local_g, local_loss = grad.local(X, y, w, mask)
            g, loss_sum, count_raw = jax.lax.psum(
                (local_g, local_loss, jnp.sum(mask)), axis
            )
            count = jnp.maximum(count_raw, 1.0)
            # MLlib records loss BEFORE this iteration's update, with the
            # regularization value produced by the PREVIOUS update
            # (GradientDescent.scala:271-274).
            stoch_loss = loss_sum / count + prev_reg_val
            w_upd, reg_upd = upd.apply(w, g / count, step_size, it, reg)
            # MLlib skips the whole iteration when the Bernoulli draw selects
            # zero rows (no update, no loss-history entry) -- `took` lets the
            # host drop the phantom entry; the weights must not shrink on
            # no data (L1/L2 would otherwise decay from sampling noise).
            took = count_raw > 0.0
            w2 = jnp.where(took, w_upd, w)
            reg_val = jnp.where(took, reg_upd, prev_reg_val)
            # write w2 into its snapshot slot when it is a multiple of
            # ``every`` (bounded buffer instead of the full (T, d) stack)
            it_i = it.astype(jnp.int32)
            slot = jnp.clip(it_i // every - 1, 0, n_snaps - 1)
            take = (it_i % every == 0).astype(w2.dtype)
            row = jax.lax.dynamic_slice_in_dim(snaps, slot, 1, axis=0)
            new_row = take * w2[None, :] + (1.0 - take) * row
            snaps = jax.lax.dynamic_update_slice_in_dim(
                snaps, new_row, slot, axis=0
            )
            out = (
                (stoch_loss, took, w2) if want_full else (stoch_loss, took)
            )
            return (w2, key, reg_val, snaps), out

        out_specs = (
            (P(None), P(None), P(None), P(None), P(None))
            if want_full
            else (P(None), P(None), P(None), P(None))
        )

        @partial(
            resolve_shard_map(),
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(axis), P(None), P(None)),
            out_specs=out_specs,
        )
        def train(X, y, valid, w0, key0):
            # MLlib seeds the loss history's regularization term from the
            # initial weights: updater.compute(w0, 0, 0, 1, reg)._2
            # (GradientDescent.scala:251-253).
            _, reg0 = upd.apply(
                w0, jnp.zeros_like(w0), 0.0, jnp.asarray(1.0, w0.dtype), reg
            )
            snaps0 = jnp.zeros((n_snaps, w0.shape[0]), w0.dtype)

            def scan_body(carry, it):
                return body(carry, it, X, y, valid)

            (wT, _, _, snaps), outs = jax.lax.scan(
                scan_body,
                (w0, key0, reg0, snaps0),
                jnp.arange(1, T + 1, dtype=jnp.float32),
            )
            if want_full:
                losses, took, ws = outs
                return wT, losses, took, snaps, ws
            losses, took = outs
            return wT, losses, took, snaps

        return jax.jit(train)

    def _get_train(self, mesh: Mesh, shape, want_full: bool):
        """Cache compiled programs per (mesh, data shape, output mode) --
        jit's cache is keyed on function identity, so rebuilding the closure
        per call would recompile every fit."""
        key = (
            tuple(d.id for d in mesh.devices.flat),
            mesh.axis_names,
            shape,
            want_full,
        )
        if key not in self._train_cache:
            self._train_cache[key] = self._build(mesh, want_full)
        return self._train_cache[key]

    def optimize(
        self,
        X: np.ndarray,
        y: np.ndarray,
        w0: Optional[np.ndarray] = None,
        mesh: Optional[Mesh] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Returns ``(w_final, stochastic_loss_history)``."""
        mesh = mesh or make_mesh()
        Xs, ys, vs, _n = pad_and_shard(mesh, X, y)
        w0 = np.zeros(X.shape[1], np.float32) if w0 is None else np.asarray(w0)
        # convergence_tol needs the per-iteration iterates to find the
        # stopping point; otherwise only the bounded snapshot buffer is
        # materialized (full (T, d) stacks don't scale to wide models)
        want_full = self.convergence_tol > 0
        t0 = time.monotonic()
        train = self._get_train(mesh, Xs.shape, want_full)
        out = train(
            Xs, ys, vs, jnp.asarray(w0, jnp.float32),
            jax.random.PRNGKey(self.seed),
        )
        wT = np.asarray(out[0])
        losses, took = np.asarray(out[1]), np.asarray(out[2])
        snaps = np.asarray(out[3])
        elapsed_ms = (time.monotonic() - t0) * 1e3
        T, every = self.num_iterations, self.snapshot_every

        def build_history(upto_iter: int, w_last: np.ndarray):
            """Warray parity: (wall-clock ms, weights) at iterations every,
            2*every, ... <= upto_iter, plus the final iterate.  The scan ran
            as one device program, so timestamps are reconstructed
            proportionally over the measured run (the reference stamps real
            per-iteration wall clock; ours bounds the same curve)."""
            iters = list(range(every, upto_iter + 1, every))
            hist = [
                (elapsed_ms * it / T, snaps[i]) for i, it in enumerate(iters)
            ]
            if upto_iter % every != 0 or not iters:
                hist.append((elapsed_ms * upto_iter / T, w_last))
            return hist

        if want_full:
            ws = np.asarray(out[4])
            prev = w0
            for i in range(len(ws)):
                if not took[i]:
                    continue  # skipped iteration (zero-row sample)
                diff = np.linalg.norm(ws[i] - prev)
                if diff < self.convergence_tol * max(np.linalg.norm(ws[i]), 1.0):
                    # truncate the trajectory at the convergence point so
                    # get_all_weights agrees with the returned model
                    self._weight_history = build_history(i + 1, ws[i])
                    return ws[i], losses[: i + 1][took[: i + 1]]
                prev = ws[i]
        self._weight_history = build_history(T, wT)
        # drop phantom entries for iterations whose sample drew zero rows
        # (MLlib appends no history entry for those)
        return wT, losses[took]

    def get_all_weights(self) -> List[Tuple[float, np.ndarray]]:
        """The fork's ``Optimizer.getAllWeights`` trajectory accessor."""
        return list(self._weight_history)


class LBFGS:
    """Limited-memory BFGS over the full-batch regularized objective.

    Parity: ``LBFGS.scala:42`` + its breeze ``CostFun`` -- objective is
    ``mean loss + reg_val(w)`` with L2 regularization handled analytically.
    The two-loop recursion and Armijo backtracking run on host (O(m d) math);
    each objective/gradient evaluation is one jitted SPMD computation.
    """

    def __init__(
        self,
        gradient: Optional[Gradient] = None,
        num_corrections: int = 10,
        convergence_tol: float = 1e-6,
        max_iterations: int = 100,
        reg_param: float = 0.0,
    ):
        self.gradient = gradient or LeastSquaresGradient()
        self.m = num_corrections
        self.tol = convergence_tol
        self.max_iterations = max_iterations
        self.reg_param = reg_param
        self._weight_history: List[Tuple[float, np.ndarray]] = []
        self.loss_history: List[float] = []
        self._vg_cache: dict = {}

    def _get_value_grad(self, mesh: Mesh, shape):
        """Per-(mesh, shape) compiled full-batch value+gradient (rebuilding
        the closure per call would recompile on every fit)."""
        key = (
            tuple(d.id for d in mesh.devices.flat),
            mesh.axis_names,
            shape,
        )
        hit = self._vg_cache.get(key)
        # the compiled program closes over the gradient object; keep a strong
        # reference in the entry and verify identity on lookup (a bare id()
        # key could collide after the original object is garbage-collected)
        if hit is not None and hit[0] is self.gradient:
            return hit[1]
        grad = self.gradient

        @partial(
            resolve_shard_map(),
            mesh=mesh,
            in_specs=(P("dp", None), P("dp"), P("dp"), P(None)),
            out_specs=(P(), P(None)),
        )
        def value_grad(Xl, yl, vl, w):
            g, loss = grad.local(Xl, yl, w, vl)
            g, loss = jax.lax.psum((g, loss), "dp")
            return loss, g

        compiled = jax.jit(value_grad)
        self._vg_cache[key] = (grad, compiled)
        return compiled

    def optimize(
        self,
        X: np.ndarray,
        y: np.ndarray,
        w0: Optional[np.ndarray] = None,
        mesh: Optional[Mesh] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        mesh = mesh or make_mesh()
        Xs, ys, vs, n = pad_and_shard(mesh, X, y)
        reg = self.reg_param
        self._weight_history = []
        self.loss_history = []
        value_grad = self._get_value_grad(mesh, Xs.shape)

        def f_g(w: np.ndarray) -> Tuple[float, np.ndarray]:
            loss, g = value_grad(Xs, ys, vs, jnp.asarray(w, jnp.float32))
            f = float(loss) / n + 0.5 * reg * float(w @ w)
            return f, np.asarray(g) / n + reg * w

        w = (np.zeros(X.shape[1], np.float32) if w0 is None
             else np.asarray(w0, np.float32))
        t0 = time.monotonic()
        f, g = f_g(w)
        s_list: List[np.ndarray] = []
        y_list: List[np.ndarray] = []
        self.loss_history = [f]
        for _ in range(self.max_iterations):
            # two-loop recursion
            q = g.copy()
            alphas = []
            for s, yk in zip(reversed(s_list), reversed(y_list)):
                a = (s @ q) / (yk @ s)
                q -= a * yk
                alphas.append(a)
            if y_list:
                yk, s = y_list[-1], s_list[-1]
                q *= (s @ yk) / (yk @ yk)
            for (s, yk), a in zip(zip(s_list, y_list), reversed(alphas)):
                beta = (yk @ q) / (yk @ s)
                q += (a - beta) * s
            d = -q
            if g @ d > 0:  # safeguard: fall back to steepest descent
                d = -g
            # Armijo backtracking
            t = 1.0
            gd = g @ d
            for _ls in range(30):
                f_new, g_new = f_g(w + t * d)
                if f_new <= f + 1e-4 * t * gd:
                    break
                t *= 0.5
            s = t * d
            yk = g_new - g
            if np.linalg.norm(s) < self.tol * max(np.linalg.norm(w), 1.0):
                w, f, g = w + s, f_new, g_new
                self.loss_history.append(f)
                break
            if yk @ s > 1e-10:  # curvature condition, keep pair
                s_list.append(s)
                y_list.append(yk)
                if len(s_list) > self.m:
                    s_list.pop(0)
                    y_list.pop(0)
            w, f, g = w + s, f_new, g_new
            self.loss_history.append(f)
            self._weight_history.append(
                ((time.monotonic() - t0) * 1e3, w.copy())
            )
            if len(self.loss_history) >= 2:
                prev, cur = self.loss_history[-2], self.loss_history[-1]
                if abs(prev - cur) / max(abs(prev), abs(cur), 1e-12) < self.tol:
                    break
        return w, np.asarray(self.loss_history)

    def get_all_weights(self) -> List[Tuple[float, np.ndarray]]:
        """Real trajectory (the reference's ``LBFGS.getAllWeights`` is a stub
        -- ``LBFGS.scala:45-49``; we return the actual iterates)."""
        return list(self._weight_history)
