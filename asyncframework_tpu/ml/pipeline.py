"""Pipelines and model selection.

Parity: the spark.ml composition layer the reference ships alongside mllib
(Spark 2.3's ``ml/Pipeline.scala``: an ordered list of transformers ending
in an estimator, fit as a unit) and ``ml/tuning/CrossValidator.scala``
(k-fold selection over a parameter grid with a metric).

Protocol (duck-typed like the reference's Params):
- transformer stages expose ``transform(X)`` (and optionally ``fit(X)`` for
  fitted transformers like scalers / IDF);
- the FINAL stage is an estimator exposing ``fit(X, y) -> model`` whose
  model exposes ``predict(X)``.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np


def _needs_labels(fit) -> bool:
    """True when a stage's fit requires more than one positional argument
    (estimator-style fit(X, y)); signature inspection, not try/except --
    swallowing a TypeError raised INSIDE fit would mask real errors."""
    import inspect

    try:
        params = [
            p for p in inspect.signature(fit).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
    except (TypeError, ValueError):  # builtins without signatures
        return False
    required = [p for p in params if p.default is inspect.Parameter.empty]
    return len(required) > 1


def _fit_transform(stage, X):
    """Fit a transformer stage if it is fittable, then transform.

    Stage outputs pass through UNCONVERTED: transformers hand device arrays
    to the next stage directly (an np.asarray here would round-trip the full
    matrix through the host per stage)."""
    if hasattr(stage, "fit") and not _needs_labels(stage.fit):
        fitted = stage.fit(X)
        # scalers return self; IDF returns a model -- use whichever object
        # carries transform
        stage = fitted if hasattr(fitted, "transform") else stage
    return stage, stage.transform(X)


@dataclass
class PipelineModel:
    transformers: List[Any]
    model: Any

    def _apply(self, X):
        for t in self.transformers:
            X = t.transform(X)  # device arrays pass through stage to stage
        return X

    def predict(self, X) -> np.ndarray:
        return self.model.predict(self._apply(X))


class Pipeline:
    """``Pipeline(stages=[...]).fit(X, y)`` analog."""

    def __init__(self, stages: Sequence[Any]):
        if not stages:
            raise ValueError("a pipeline needs at least one stage")
        self.stages = list(stages)

    def fit(self, X, y=None) -> PipelineModel:
        fitted: List[Any] = []
        for stage in self.stages[:-1]:
            if not hasattr(stage, "transform"):
                raise TypeError(
                    f"intermediate stage {type(stage).__name__} has no "
                    "transform(); only the final stage may be an estimator"
                )
            stage, X = _fit_transform(stage, X)
            fitted.append(stage)
        last = self.stages[-1]
        if hasattr(last, "fit") and y is not None:
            model = last.fit(X, y)
        elif hasattr(last, "fit"):
            model = last.fit(X)
        else:
            raise TypeError("the final pipeline stage must expose fit()")
        return PipelineModel(transformers=fitted, model=model)


def train_test_split(
    X, y, test_fraction: float = 0.25, seed: int = 42
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """``randomSplit`` analog for supervised fixtures."""
    if not 0.0 < test_fraction < 1.0:
        raise ValueError("test_fraction must be in (0, 1)")
    X = np.asarray(X)
    y = np.asarray(y)
    rs = np.random.default_rng(seed)
    perm = rs.permutation(len(X))
    cut = int(round(len(X) * (1.0 - test_fraction)))
    if cut == 0 or cut == len(X):
        raise ValueError(
            f"test_fraction={test_fraction} leaves an empty partition for "
            f"{len(X)} rows"
        )
    tr, te = perm[:cut], perm[cut:]
    return X[tr], y[tr], X[te], y[te]


@dataclass
class CrossValidatorModel:
    best_params: Dict[str, Any]
    best_score: float
    best_model: Any
    all_scores: List[Tuple[Dict[str, Any], float]]

    def predict(self, X) -> np.ndarray:
        return self.best_model.predict(X)


class CrossValidator:
    """k-fold selection over a parameter grid.

    ``estimator_factory(**params)`` builds a fresh estimator per candidate;
    ``scorer(model, X_val, y_val) -> float`` (higher is better).  Parity:
    ``ml/tuning/CrossValidator.scala`` (sequential folds; the reference
    parallelizes fits across the cluster, here each fit is already a device
    program).
    """

    def __init__(
        self,
        estimator_factory: Callable[..., Any],
        param_grid: Dict[str, Sequence[Any]],
        scorer: Callable[[Any, np.ndarray, np.ndarray], float],
        num_folds: int = 3,
        seed: int = 42,
    ):
        if num_folds < 2:
            raise ValueError("num_folds must be >= 2")
        if not param_grid:
            raise ValueError("param_grid must name at least one parameter")
        self.factory = estimator_factory
        self.grid = dict(param_grid)
        self.scorer = scorer
        self.num_folds = num_folds
        self.seed = seed

    def _candidates(self) -> List[Dict[str, Any]]:
        names = sorted(self.grid)
        return [
            dict(zip(names, combo))
            for combo in itertools.product(*(self.grid[n] for n in names))
        ]

    def fit(self, X, y) -> CrossValidatorModel:
        X = np.asarray(X)
        y = np.asarray(y)
        if len(X) < self.num_folds:
            raise ValueError(
                f"{self.num_folds}-fold CV needs at least that many rows; "
                f"got {len(X)} (an empty fold would score NaN)"
            )
        rs = np.random.default_rng(self.seed)
        perm = rs.permutation(len(X))
        folds = np.array_split(perm, self.num_folds)
        results: List[Tuple[Dict[str, Any], float]] = []
        for params in self._candidates():
            scores = []
            for i in range(self.num_folds):
                val = folds[i]
                trn = np.concatenate(
                    [folds[j] for j in range(self.num_folds) if j != i]
                )
                model = self.factory(**params).fit(X[trn], y[trn])
                scores.append(float(self.scorer(model, X[val], y[val])))
            results.append((params, float(np.mean(scores))))
        valid = [r for r in results if not np.isnan(r[1])]
        if not valid:
            raise ValueError(
                "every candidate scored NaN (scorer undefined on these "
                "folds, e.g. constant-target validation splits)"
            )
        best_params, best_score = max(valid, key=lambda r: r[1])
        best_model = self.factory(**best_params).fit(X, y)  # refit on all
        return CrossValidatorModel(
            best_params=best_params,
            best_score=best_score,
            best_model=best_model,
            all_scores=results,
        )


def accuracy_scorer(model, X, y) -> float:
    return float((np.asarray(model.predict(X)) == np.asarray(y)).mean())


def r2_scorer(model, X, y) -> float:
    from asyncframework_tpu.ml.evaluation import RegressionMetrics

    return RegressionMetrics.of(model.predict(X), y).r2
