"""Decision trees: histogram-based, level-wise, device-batched.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/tree/DecisionTree.scala``
-- the reference grows trees level by level; each level is one aggregation
job computing per-(node, feature, bin) statistics over binned features
(``findSplitsBins`` quantile binning, ``DTStatsAggregator``), then the
driver picks best splits by impurity gain (gini/entropy/variance).

TPU mapping: that per-level aggregation IS a scatter-add -- every sample
contributes one count per feature into a flat (node, feature, bin, stat)
histogram, which XLA compiles to a single static scatter kernel per level.
The split search over the (tiny) histogram and the tree bookkeeping stay on
the host, exactly like the reference's driver-side best-split loop.  Nodes
live in a binary-heap layout (root 0, children 2i+1 / 2i+2) so the sample ->
node assignment update is one vectorized gather/where per level.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def quantile_bins(X: np.ndarray, max_bins: int) -> np.ndarray:
    """Per-feature split thresholds from quantiles (findSplitsBins parity).

    Returns (F, max_bins - 1) thresholds; feature value v falls in bin
    ``searchsorted(thresholds, v, 'left')`` (value <= threshold goes left).
    """
    X = np.asarray(X, np.float32)
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    thr = np.quantile(X, qs, axis=0).T.astype(np.float32)  # (F, B-1)
    return thr


@partial(jax.jit, static_argnums=(3, 4, 5))
def _class_histogram(bins, node_of, y, n_nodes, max_bins, num_classes):
    """(n_nodes, F, B, C) class counts in one scatter-add."""
    n, F = bins.shape
    f_idx = jnp.arange(F)[None, :]
    flat = (
        (node_of[:, None] * F + f_idx) * max_bins + bins
    ) * num_classes + y[:, None]
    out = jnp.zeros(n_nodes * F * max_bins * num_classes, jnp.float32)
    out = out.at[flat.ravel()].add(1.0)
    return out.reshape(n_nodes, F, max_bins, num_classes)


@partial(jax.jit, static_argnums=(3, 4))
def _reg_histogram(bins, node_of, y, n_nodes, max_bins):
    """(n_nodes, F, B, 3) [count, sum, sumsq] in one scatter-add."""
    n, F = bins.shape
    f_idx = jnp.arange(F)[None, :]
    flat = (node_of[:, None] * F + f_idx) * max_bins + bins
    stats = jnp.stack(
        [jnp.ones_like(y), y, y * y], axis=1
    )  # (n, 3)
    out = jnp.zeros((n_nodes * F * max_bins, 3), jnp.float32)
    out = out.at[flat.ravel()].add(jnp.repeat(stats, F, axis=0))
    return out.reshape(n_nodes, F, max_bins, 3)


def _gini(counts: np.ndarray) -> np.ndarray:
    """Gini impurity of (..., C) count stacks; 0 for empty."""
    total = counts.sum(-1, keepdims=True)
    p = counts / np.maximum(total, 1e-12)
    return (1.0 - (p * p).sum(-1)) * (total[..., 0] > 0)


@dataclass
class DecisionTreeModel:
    """Heap-layout arrays: node i's children are 2i+1 / 2i+2."""

    feature: np.ndarray    # (n_nodes,) split feature, -1 at leaves
    threshold: np.ndarray  # (n_nodes,) go left when x[f] <= thr
    prediction: np.ndarray # (n_nodes,) class id or regression mean
    depth: int
    task: str

    def predict(self, X) -> np.ndarray:
        X = jnp.asarray(X, jnp.float32)
        feat = jnp.asarray(self.feature)
        thr = jnp.asarray(self.threshold)
        node = jnp.zeros(X.shape[0], jnp.int32)

        def step(_, node):
            f = feat[node]
            is_leaf = f < 0
            x = jnp.take_along_axis(
                X, jnp.maximum(f, 0)[:, None], axis=1
            )[:, 0]
            go_right = x > thr[node]
            child = 2 * node + 1 + go_right.astype(jnp.int32)
            return jnp.where(is_leaf, node, child)

        node = jax.lax.fori_loop(0, self.depth, step, node)
        pred = jnp.asarray(self.prediction)[node]
        out = np.asarray(pred)
        return out.astype(np.int64) if self.task == "classification" else out


class DecisionTree:
    """``DecisionTree.trainClassifier / trainRegressor`` analog."""

    def __init__(
        self,
        task: str = "classification",
        max_depth: int = 5,
        max_bins: int = 32,
        min_instances_per_node: int = 1,
        min_info_gain: float = 0.0,
        num_classes: Optional[int] = None,
        feature_subset: Optional[int] = None,
        seed: int = 0,
    ):
        """``feature_subset``: consider only that many randomly drawn
        features PER NODE (random-forest mode; the reference's
        ``featureSubsetStrategy`` samples per node too)."""
        if task not in ("classification", "regression"):
            raise ValueError("task must be classification or regression")
        if max_depth < 1 or max_bins < 2:
            raise ValueError("max_depth >= 1 and max_bins >= 2 required")
        self.task = task
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_node = min_instances_per_node
        self.min_gain = min_info_gain
        self.num_classes = num_classes
        self.feature_subset = feature_subset
        self.seed = seed

    def fit(self, X, y) -> DecisionTreeModel:
        Xh = np.asarray(X, np.float32)
        n, F = Xh.shape
        thr_table = quantile_bins(Xh, self.max_bins)
        bins_h = np.empty((n, F), np.int32)
        for f in range(F):
            bins_h[:, f] = np.searchsorted(thr_table[f], Xh[:, f], "left")
        bins = jnp.asarray(bins_h)
        B = self.max_bins

        if self.task == "classification":
            labels = np.asarray(y).astype(np.int32)
            C = self.num_classes or int(labels.max()) + 1
            y_dev = jnp.asarray(labels)
        else:
            y_dev = jnp.asarray(np.asarray(y, np.float32))

        max_nodes = 2 ** (self.max_depth + 1) - 1
        feature = np.full(max_nodes, -1, np.int32)
        threshold = np.zeros(max_nodes, np.float32)
        split_bin = np.zeros(max_nodes, np.int32)
        prediction = np.zeros(max_nodes, np.float32)
        node_of = jnp.zeros(n, jnp.int32)

        rng = np.random.default_rng(self.seed)
        level_start, level_size = 0, 1
        for depth in range(self.max_depth + 1):
            n_nodes_total = level_start + level_size
            if self.task == "classification":
                hist = np.asarray(_class_histogram(
                    bins, node_of, y_dev, n_nodes_total, B, C
                ))[level_start:]
            else:
                hist = np.asarray(_reg_histogram(
                    bins, node_of, y_dev, n_nodes_total, B
                ))[level_start:]

            any_split = False
            for li in range(level_size):
                node = level_start + li
                h = hist[li]  # (F, B, C) or (F, B, 3)
                if self.task == "classification":
                    node_counts = h.sum(axis=(0, 1)) / F  # per-class
                    total = node_counts.sum()
                    prediction[node] = float(np.argmax(node_counts))
                    parent_imp = float(_gini(node_counts[None])[0])
                else:
                    node_stats = h.sum(axis=(0, 1)) / F  # [cnt, s, ss]
                    total = node_stats[0]
                    mean = node_stats[1] / max(total, 1e-12)
                    prediction[node] = float(mean)
                    parent_imp = float(
                        node_stats[2] / max(total, 1e-12) - mean**2
                    )
                if (
                    depth == self.max_depth
                    or total < 2 * self.min_node
                    or parent_imp <= 1e-12
                ):
                    continue  # stays a leaf (feature[node] == -1)

                # vectorized best-split search over (F, B-1) candidates
                left = np.cumsum(h, axis=1)[:, :-1]       # (F, B-1, S)
                if self.task == "classification":
                    right = h.sum(axis=1, keepdims=True) - left
                    nl = left.sum(-1)
                    nr = right.sum(-1)
                    child = (
                        nl * _gini(left) + nr * _gini(right)
                    ) / max(total, 1e-12)
                else:
                    right = h.sum(axis=1, keepdims=True) - left
                    nl, sl, ssl = left[..., 0], left[..., 1], left[..., 2]
                    nr, sr, ssr = right[..., 0], right[..., 1], right[..., 2]
                    vl = ssl / np.maximum(nl, 1e-12) - (
                        sl / np.maximum(nl, 1e-12)
                    ) ** 2
                    vr = ssr / np.maximum(nr, 1e-12) - (
                        sr / np.maximum(nr, 1e-12)
                    ) ** 2
                    child = (nl * vl + nr * vr) / max(total, 1e-12)
                gain = parent_imp - child
                ok = (nl >= self.min_node) & (nr >= self.min_node)
                gain = np.where(ok, gain, -np.inf)
                if self.feature_subset is not None and self.feature_subset < F:
                    allowed = rng.choice(F, self.feature_subset, replace=False)
                    mask = np.full(F, True)
                    mask[allowed] = False
                    gain[mask] = -np.inf
                f_best, b_best = np.unravel_index(
                    np.argmax(gain), gain.shape
                )
                if gain[f_best, b_best] <= self.min_gain:
                    continue
                feature[node] = f_best
                threshold[node] = thr_table[f_best, b_best]
                split_bin[node] = b_best
                any_split = True

            if not any_split:
                break
            # advance sample assignments through this level's splits
            feat_dev = jnp.asarray(feature)
            is_split = feat_dev >= 0
            f_of = jnp.maximum(feat_dev, 0)
            b_of_split = jnp.asarray(split_bin)
            sample_bin = jnp.take_along_axis(
                bins, f_of[node_of][:, None], axis=1
            )[:, 0]
            go_right = sample_bin > b_of_split[node_of]
            child = 2 * node_of + 1 + go_right.astype(jnp.int32)
            node_of = jnp.where(is_split[node_of], child, node_of)
            level_start += level_size
            level_size *= 2

        return DecisionTreeModel(
            feature=feature,
            threshold=threshold,
            prediction=prediction,
            depth=self.max_depth,
            task=self.task,
        )
