"""Feature transforms.

Parity: MLlib ``feature/`` -- ``StandardScaler`` (fit column mean/std over a
distributed dataset, then transform), ``Normalizer`` (row p-norm scaling),
``MinMaxScaler``.  The fit statistics come from one jitted pass (optionally
``psum``-reduced over a mesh for sharded data -- see ``ml/stat.py`` which
these reuse); transform is elementwise XLA.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from asyncframework_tpu.ml.stat import col_stats


class StandardScaler:
    """(x - mean) / std per column; either part optional (MLlib flags)."""

    def __init__(self, with_mean: bool = True, with_std: bool = True):
        self.with_mean = with_mean
        self.with_std = with_std
        self.mean_: Optional[np.ndarray] = None
        self.std_: Optional[np.ndarray] = None

    def fit(self, X) -> "StandardScaler":
        s = col_stats(X)
        self.mean_ = np.asarray(s.mean)
        # MLlib uses the corrected sample std
        self.std_ = np.sqrt(np.asarray(s.variance))
        return self

    def transform(self, X):
        if self.mean_ is None:
            raise RuntimeError("fit() before transform()")
        X = jnp.asarray(X, jnp.float32)
        if self.with_mean:
            X = X - self.mean_
        if self.with_std:
            X = X / jnp.where(self.std_ > 0, self.std_, 1.0)
        return X

    def fit_transform(self, X):
        return self.fit(X).transform(X)


class MinMaxScaler:
    """Scale columns to [lo, hi] from fitted per-column min/max."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        if hi <= lo:
            raise ValueError("hi must exceed lo")
        self.lo = lo
        self.hi = hi
        self.min_: Optional[np.ndarray] = None
        self.max_: Optional[np.ndarray] = None

    def fit(self, X) -> "MinMaxScaler":
        s = col_stats(X)
        self.min_ = np.asarray(s.min)
        self.max_ = np.asarray(s.max)
        return self

    def transform(self, X):
        if self.min_ is None:
            raise RuntimeError("fit() before transform()")
        X = jnp.asarray(X, jnp.float32)
        rng = self.max_ - self.min_
        unit = (X - self.min_) / jnp.where(rng > 0, rng, 1.0)
        # constant columns land mid-range, like MLlib
        unit = jnp.where(rng > 0, unit, 0.5)
        return unit * (self.hi - self.lo) + self.lo

    def fit_transform(self, X):
        return self.fit(X).transform(X)


class Normalizer:
    """Scale each row to unit p-norm (p in {1, 2, inf}); zero rows pass."""

    def __init__(self, p: float = 2.0):
        if p not in (1.0, 2.0, float("inf")):
            raise ValueError("p must be 1, 2, or inf")
        self.p = p

    def transform(self, X):
        X = jnp.asarray(X, jnp.float32)
        if self.p == 1.0:
            n = jnp.sum(jnp.abs(X), axis=1, keepdims=True)
        elif self.p == 2.0:
            n = jnp.sqrt(jnp.sum(X * X, axis=1, keepdims=True))
        else:
            n = jnp.max(jnp.abs(X), axis=1, keepdims=True)
        return X / jnp.where(n > 0, n, 1.0)


class HashingTF:
    """Term-frequency vectors by the hashing trick.

    Parity: ``mllib/.../feature/HashingTF.scala`` -- term -> bucket via a
    stable hash mod ``num_features``; a document's vector counts bucket
    hits.  TPU mapping: per-document token hashes are computed host-side
    (strings), the count matrix lands via one device scatter-add.
    """

    def __init__(self, num_features: int = 1 << 10):
        if num_features < 1:
            raise ValueError("num_features must be >= 1")
        self.num_features = num_features

    def indices(self, doc) -> np.ndarray:
        from asyncframework_tpu.data.pairs import portable_hash

        return np.asarray(
            [portable_hash(t) % self.num_features for t in doc], np.int32
        )

    def transform(self, docs) -> jnp.ndarray:
        """docs: iterable of token iterables -> (n_docs, num_features)."""
        docs = list(docs)
        if not docs:
            # empty corpora flow through (filter-then-vectorize pipelines)
            return jnp.zeros((0, self.num_features), jnp.float32)
        # (rows/cols built host-side; the count matrix is one scatter-add)
        rows = []
        cols = []
        for i, doc in enumerate(docs):
            idx = self.indices(doc)
            rows.append(np.full(len(idx), i, np.int32))
            cols.append(idx)
        r = jnp.asarray(np.concatenate(rows))
        c = jnp.asarray(np.concatenate(cols))
        out = jnp.zeros((len(docs), self.num_features), jnp.float32)
        return out.at[r, c].add(1.0)


class IDFModel:
    def __init__(self, idf: jnp.ndarray):
        self.idf = idf

    def transform(self, tf) -> jnp.ndarray:
        return jnp.asarray(tf, jnp.float32) * self.idf[None, :]


class IDF:
    """Inverse document frequency (``mllib/.../feature/IDF.scala``):
    ``idf = log((n_docs + 1) / (df + 1))`` with ``min_doc_freq`` zeroing
    rare terms, fit as one device reduction over the TF matrix."""

    def __init__(self, min_doc_freq: int = 0):
        self.min_doc_freq = min_doc_freq

    def fit(self, tf) -> IDFModel:
        tf = jnp.asarray(tf, jnp.float32)
        n = tf.shape[0]
        df = jnp.sum(tf > 0, axis=0).astype(jnp.float32)
        idf = jnp.log((n + 1.0) / (df + 1.0))
        if self.min_doc_freq > 0:
            idf = jnp.where(df >= self.min_doc_freq, idf, 0.0)
        return IDFModel(idf)


class ElementwiseProduct:
    """Hadamard scaling by a fixed weight vector.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/feature/
    ElementwiseProduct.scala`` -- one broadcasted multiply on device.
    """

    def __init__(self, scaling_vector):
        self.scaling_vector = jnp.asarray(
            np.asarray(scaling_vector), jnp.float32
        )

    def transform(self, X) -> jnp.ndarray:
        X = jnp.asarray(X, jnp.float32)
        w = self.scaling_vector
        return X * (w[None, :] if X.ndim == 2 else w)


class ChiSqSelectorModel:
    def __init__(self, selected: np.ndarray):
        self.selected = np.asarray(selected, np.int64)  # sorted feature ids

    def transform(self, X) -> jnp.ndarray:
        X = jnp.asarray(X, jnp.float32)
        idx = jnp.asarray(self.selected)
        return X[:, idx] if X.ndim == 2 else X[idx]


class ChiSqSelector:
    """Chi-squared feature selection for categorical features.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/feature/
    ChiSqSelector.scala`` -- ranks features by the chi-squared test of
    independence against the label and keeps ``num_top_features`` (the
    reference's default selector type); selected indices are sorted so
    transformed columns keep their relative order.

    Contingency tables are tiny (distinct feature values x labels) and are
    built host-side; the chi-squared statistic itself reuses
    ``chi_sq_test_matrix``.
    """

    def __init__(self, num_top_features: int = 50):
        if num_top_features < 1:
            raise ValueError("num_top_features must be >= 1")
        self.num_top_features = num_top_features

    def fit(self, X, y) -> ChiSqSelectorModel:
        from asyncframework_tpu.ml.stat import chi_sq_test_matrix

        X = np.asarray(X)
        y = np.asarray(y)
        labels, li = np.unique(y, return_inverse=True)
        # rank by p-value, not raw statistic: features with different numbers
        # of distinct values have different degrees of freedom, and the
        # reference sorts (p-value, index) ascending (ChiSqSelector.scala)
        pvals = np.empty(X.shape[1])
        for j in range(X.shape[1]):
            vals, vi = np.unique(X[:, j], return_inverse=True)
            cont = np.zeros((len(vals), len(labels)), np.float64)
            np.add.at(cont, (vi, li), 1.0)
            pvals[j] = chi_sq_test_matrix(cont).p_value
        k = min(self.num_top_features, pvals.shape[0])
        top = np.argsort(pvals, kind="stable")[:k]
        return ChiSqSelectorModel(np.sort(top))
