"""Linear model wrappers over the optimizers.

Parity: ``mllib/.../regression/LinearRegression.scala`` (the fork touches it
at :178-183 to surface the weight trajectory), ``classification/
LogisticRegressionWithSGD`` and ``SVMWithSGD`` via
``GeneralizedLinearAlgorithm.scala:318-320`` -- train = run the optimizer on
the (optionally intercept-augmented) design matrix, wrap weights in a typed
model with ``predict``.

The fork's `LinearRegression` delta -- exposing ``optimizer.getAllWeights``
so the baseline driver can compute loss-vs-time post hoc -- is
:attr:`LinearModel.weight_history` here.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
from jax.sharding import Mesh

from asyncframework_tpu.ml.gradient import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
)
from asyncframework_tpu.ml.optimization import GradientDescent
from asyncframework_tpu.ml.updater import (
    L1Updater,
    SimpleUpdater,
    SquaredL2Updater,
)


def _augment(X: np.ndarray, fit_intercept: bool) -> np.ndarray:
    if not fit_intercept:
        return X
    return np.concatenate([X, np.ones((X.shape[0], 1), X.dtype)], axis=1)


class LinearModel:
    """weights + intercept + the training loss/weight trajectories."""

    def __init__(
        self,
        weights: np.ndarray,
        intercept: float,
        loss_history: np.ndarray,
        weight_history: List[Tuple[float, np.ndarray]],
    ):
        self.weights = weights
        self.intercept = intercept
        self.loss_history = loss_history
        self.weight_history = weight_history

    def margin(self, X: np.ndarray) -> np.ndarray:
        return X @ self.weights + self.intercept

    def predict(self, X: np.ndarray) -> np.ndarray:
        return self.margin(X)


class _SGDEstimator:
    _gradient_cls = LeastSquaresGradient
    _default_updater = SimpleUpdater

    def __init__(
        self,
        step_size: float = 1.0,
        num_iterations: int = 100,
        reg_param: float = 0.0,
        mini_batch_fraction: float = 1.0,
        fit_intercept: bool = False,
        updater: str = "default",
        convergence_tol: float = 0.0,
        seed: int = 42,
        snapshot_every: int = 100,
    ):
        upd = {
            "default": self._default_updater(),
            "simple": SimpleUpdater(),
            "l1": L1Updater(),
            "l2": SquaredL2Updater(),
        }[updater]
        self.fit_intercept = fit_intercept
        self.optimizer = GradientDescent(
            gradient=self._gradient_cls(),
            updater=upd,
            step_size=step_size,
            num_iterations=num_iterations,
            reg_param=reg_param,
            mini_batch_fraction=mini_batch_fraction,
            convergence_tol=convergence_tol,
            seed=seed,
            snapshot_every=snapshot_every,
        )

    def _make_model(self, w_aug: np.ndarray, losses: np.ndarray) -> LinearModel:
        if self.fit_intercept:
            w, b = w_aug[:-1], float(w_aug[-1])
        else:
            w, b = w_aug, 0.0
        return self._model_cls(
            w, b, losses, self.optimizer.get_all_weights()
        )

    _model_cls = LinearModel

    def fit(
        self,
        X: np.ndarray,
        y: np.ndarray,
        w0: Optional[np.ndarray] = None,
        mesh: Optional[Mesh] = None,
    ):
        Xa = _augment(np.asarray(X, np.float32), self.fit_intercept)
        if w0 is not None and self.fit_intercept:
            w0 = np.concatenate([w0, [0.0]]).astype(np.float32)
        w_aug, losses = self.optimizer.optimize(
            Xa, np.asarray(y, np.float32), w0=w0, mesh=mesh
        )
        return self._make_model(w_aug, losses)


class LinearRegression(_SGDEstimator):
    """``LinearRegressionWithSGD`` analog (least squares, simple updater)."""

    _gradient_cls = LeastSquaresGradient
    _default_updater = SimpleUpdater


class LogisticRegressionModel(LinearModel):
    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-self.margin(X)))

    def predict(self, X: np.ndarray, threshold: float = 0.5) -> np.ndarray:
        return (self.predict_proba(X) >= threshold).astype(np.int32)


class LogisticRegression(_SGDEstimator):
    """``LogisticRegressionWithSGD`` analog (labels in {0,1})."""

    _gradient_cls = LogisticGradient
    _default_updater = SimpleUpdater
    _model_cls = LogisticRegressionModel


class SVMModel(LinearModel):
    def predict(self, X: np.ndarray, threshold: float = 0.0) -> np.ndarray:
        return (self.margin(X) >= threshold).astype(np.int32)


class LinearSVM(_SGDEstimator):
    """``SVMWithSGD`` analog (hinge loss, L2 updater by default)."""

    _gradient_cls = HingeGradient
    _default_updater = SquaredL2Updater
    _model_cls = SVMModel


class SoftmaxRegressionModel:
    """Multinomial logistic model: W (d, C) + b (C,)."""

    def __init__(self, W: np.ndarray, b: np.ndarray,
                 loss_history: np.ndarray):
        self.W = W
        self.b = b
        self.loss_history = loss_history

    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        logits = jnp.asarray(X, jnp.float32) @ jnp.asarray(self.W) + \
            jnp.asarray(self.b)
        return np.asarray(jax.nn.softmax(logits, axis=1))

    def predict(self, X: np.ndarray) -> np.ndarray:
        return np.argmax(self.predict_proba(X), axis=1)


class SoftmaxRegression:
    """Multinomial logistic regression (``LogisticRegressionWithLBFGS``'s
    ``setNumClasses(k)`` mode).

    One jitted ``lax.scan`` runs the whole full-batch gradient loop: the
    per-iteration cost is two MXU matmuls (logits, X^T residual) -- the
    multiclass analog of the fused MiniBatchSGD design.
    """

    def __init__(
        self,
        step_size: float = 1.0,
        num_iterations: int = 200,
        reg_param: float = 0.0,
        num_classes: Optional[int] = None,
    ):
        self.step_size = step_size
        self.num_iterations = num_iterations
        self.reg_param = reg_param
        self.num_classes = num_classes

    def fit(self, X: np.ndarray, y: np.ndarray) -> SoftmaxRegressionModel:
        import jax
        import jax.numpy as jnp

        Xd = jnp.asarray(X, jnp.float32)
        labels = np.asarray(y).astype(np.int32)
        C = self.num_classes or int(labels.max()) + 1
        Y = jax.nn.one_hot(jnp.asarray(labels), C, dtype=jnp.float32)
        n, d = Xd.shape
        lr = self.step_size
        reg = self.reg_param

        def step(carry, _):
            W, b = carry
            logits = Xd @ W + b
            p = jax.nn.softmax(logits, axis=1)
            # mean cross-entropy + L2; gradient via the softmax residual
            loss = -jnp.mean(
                jnp.sum(Y * jax.nn.log_softmax(logits, axis=1), axis=1)
            ) + 0.5 * reg * jnp.sum(W * W)
            resid = (p - Y) / n
            gW = Xd.T @ resid + reg * W
            gb = resid.sum(axis=0)
            return (W - lr * gW, b - lr * gb), loss

        init = (jnp.zeros((d, C), jnp.float32), jnp.zeros(C, jnp.float32))
        (W, b), losses = jax.lax.scan(
            step, init, None, length=self.num_iterations
        )
        return SoftmaxRegressionModel(
            W=np.asarray(W), b=np.asarray(b), loss_history=np.asarray(losses)
        )


class RidgeRegression(_SGDEstimator):
    """``RidgeRegressionWithSGD`` analog: least squares + L2 updater."""

    _gradient_cls = LeastSquaresGradient
    _default_updater = SquaredL2Updater


class Lasso(_SGDEstimator):
    """``LassoWithSGD`` analog: least squares + L1 (soft-threshold) updater."""

    _gradient_cls = LeastSquaresGradient
    _default_updater = L1Updater
