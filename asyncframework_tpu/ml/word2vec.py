"""Word2Vec: skip-gram embeddings trained on device.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/feature/Word2Vec.scala``
-- skip-gram word embeddings with windowed contexts, a min-count vocabulary,
and ``findSynonyms`` by cosine similarity.  Design delta, documented: the
reference trains with hierarchical softmax (a Huffman tree walked per word
-- pointer-chasing that a TPU cannot batch); here training is skip-gram with
NEGATIVE SAMPLING (the other canonical word2vec objective), whose step is
dense embedding gathers + a batched dot-product sigmoid -- one jitted scan
over minibatches with negatives drawn inside the scan from the
unigram^(3/4) table.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class Word2VecModel:
    def __init__(self, vocab: List[str], vectors: np.ndarray):
        self.vocab = vocab
        self.vectors = vectors  # (V, d)
        self._index = {w: i for i, w in enumerate(vocab)}
        norms = np.linalg.norm(vectors, axis=1, keepdims=True)
        self._unit = vectors / np.maximum(norms, 1e-12)

    def __contains__(self, word: str) -> bool:
        return word in self._index

    def _idx(self, word: str) -> int:
        if word not in self._index:
            raise KeyError(f"word {word!r} not in vocabulary")
        return self._index[word]

    def transform(self, word: str) -> np.ndarray:
        return self.vectors[self._idx(word)]

    def similarity(self, a: str, b: str) -> float:
        return float(self._unit[self._idx(a)] @ self._unit[self._idx(b)])

    def find_synonyms(self, word: str, num: int) -> List[tuple]:
        """Top-``num`` (word, cosine) excluding the query (reference API)."""
        q = self._unit[self._idx(word)]
        sims = self._unit @ q
        order = np.argsort(-sims)
        out = []
        for i in order:
            if self.vocab[i] == word:
                continue
            out.append((self.vocab[i], float(sims[i])))
            if len(out) == num:
                break
        return out


class Word2Vec:
    def __init__(
        self,
        vector_size: int = 64,
        window: int = 5,
        min_count: int = 2,
        negative: int = 5,
        learning_rate: float = 0.25,
        num_iterations: int = 3,
        batch_size: int = 512,
        seed: int = 0,
    ):
        if vector_size < 1 or window < 1 or negative < 1:
            raise ValueError("vector_size, window, negative must be >= 1")
        if batch_size < 1 or num_iterations < 1:
            raise ValueError("batch_size and num_iterations must be >= 1")
        self.vector_size = vector_size
        self.window = window
        self.min_count = min_count
        self.negative = negative
        self.lr = learning_rate
        self.epochs = num_iterations
        self.batch_size = batch_size
        self.seed = seed

    def _pairs(self, sentences, index) -> np.ndarray:
        pairs = []
        for sent in sentences:
            ids = [index[w] for w in sent if w in index]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window)
                hi = min(len(ids), i + self.window + 1)
                for j in range(lo, hi):
                    if j != i:
                        pairs.append((c, ids[j]))
        return np.asarray(pairs, np.int32)

    def fit(self, sentences: Sequence[Sequence[str]]) -> Word2VecModel:
        freq = Counter(w for s in sentences for w in s)
        vocab = sorted(w for w, c in freq.items() if c >= self.min_count)
        if len(vocab) < 2:
            raise ValueError(
                "vocabulary needs >= 2 words above min_count"
            )
        index = {w: i for i, w in enumerate(vocab)}
        V, d = len(vocab), self.vector_size
        pairs = self._pairs(sentences, index)
        if len(pairs) == 0:
            raise ValueError("no (center, context) pairs within the window")

        rs = np.random.default_rng(self.seed)
        B = min(self.batch_size, len(pairs))

        def epoch_batches() -> np.ndarray:
            """Fresh shuffle + remainder wrap per epoch: a fixed wrap would
            give the same pairs double gradient weight in every epoch, the
            mirror image of the tail-exclusion bias it replaces."""
            perm = rs.permutation(len(pairs))
            p = pairs[perm]
            r = len(p) % B
            if r:
                p = np.concatenate([p, p[: B - r]])
            return p.reshape(len(p) // B, B, 2)

        # negative-sampling distribution: unigram^(3/4)
        counts = np.asarray([freq[w] for w in vocab], np.float64) ** 0.75
        log_neg = jnp.asarray(np.log(counts / counts.sum()), jnp.float32)

        W_in0 = jnp.asarray(
            (rs.random((V, d)) - 0.5) / d, dtype=jnp.float32
        )
        W_out0 = jnp.zeros((V, d), jnp.float32)
        lr = self.lr
        K = self.negative

        def loss_fn(params, centers, contexts, negs):
            W_in, W_out = params
            v = W_in[centers]                      # (B, d)
            u_pos = W_out[contexts]                # (B, d)
            u_neg = W_out[negs]                    # (B, K, d)
            pos = jnp.sum(v * u_pos, axis=1)
            neg = jnp.einsum("bd,bkd->bk", v, u_neg)
            # a drawn negative that collides with the pair's true context
            # would push the same dot product both ways in one step; mask
            # it out (canonical SGNS skips target == positive)
            valid = (negs != contexts[:, None]).astype(neg.dtype)
            return -(
                jnp.mean(jax.nn.log_sigmoid(pos))
                + jnp.mean(
                    jnp.sum(jax.nn.log_sigmoid(-neg) * valid, axis=1)
                )
            )

        grad_fn = jax.value_and_grad(loss_fn)

        # pairs ride as a jit ARGUMENT: a captured closure would bake the
        # whole dataset into the executable as a constant (same note as
        # clustering._pic_iterate)
        @jax.jit
        def epoch(params, key, batches):
            def step(carry, batch):
                params, key = carry
                key, sub = jax.random.split(key)
                centers, contexts = batch[:, 0], batch[:, 1]
                negs = jax.random.categorical(
                    sub, log_neg, shape=(batch.shape[0], K)
                )
                loss, grads = grad_fn(params, centers, contexts, negs)
                params = jax.tree_util.tree_map(
                    lambda p, g: p - lr * g, params, grads
                )
                return (params, key), loss

            (params, key), losses = jax.lax.scan(step, (params, key), batches)
            return params, key, jnp.mean(losses)

        params = (W_in0, W_out0)
        key = jax.random.PRNGKey(self.seed)
        for _ in range(self.epochs):
            params, key, _loss = epoch(
                params, key, jnp.asarray(epoch_batches())
            )
        return Word2VecModel(vocab, np.asarray(params[0]))
