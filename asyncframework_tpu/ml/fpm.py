"""Frequent pattern mining: FP-Growth and association rules.

Parity: ``mllib/src/main/scala/org/apache/spark/mllib/fpm/FPGrowth.scala``
and ``AssociationRules.scala`` -- conditional FP-tree mining with a minimum
support threshold, then rules filtered by confidence.

Host-side by design: frequent-itemset mining is symbolic tree recursion
over hash maps -- no dense array structure for a TPU to accelerate, and the
reference's distribution strategy (group-dependent transactions) exists for
datasets far beyond this framework's single-host scope.  The capability is
the API and the exact semantics; the compute is pointer-chasing either way.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, List, Sequence, Tuple


class _FPNode:
    __slots__ = ("item", "count", "parent", "children")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.parent = parent
        self.children: Dict[object, _FPNode] = {}


def _build_tree(transactions, min_count):
    """(root, header links item -> [nodes]) for the frequent items only.

    ``transactions`` is a sequence of item iterables, or a dict mapping a
    path tuple to its multiplicity (conditional pattern bases) -- item
    frequencies MUST be weighted by that multiplicity.
    """
    weighted = (
        list(transactions.items())
        if isinstance(transactions, dict)
        else [(t, 1) for t in transactions]
    )
    freq = Counter()
    for t, mult in weighted:
        for i in set(t):
            freq[i] += mult
    keep = {i for i, c in freq.items() if c >= min_count}
    order = {i: (-freq[i], repr(i)) for i in keep}  # support-desc, stable
    root = _FPNode(None, None)
    header: Dict[object, List[_FPNode]] = defaultdict(list)
    for t, mult in weighted:
        items = sorted(set(t) & keep, key=lambda i: order[i])
        node = root
        for item in items:
            child = node.children.get(item)
            if child is None:
                child = _FPNode(item, node)
                node.children[item] = child
                header[item].append(child)
            child.count += mult
            node = child
    return root, header, freq, keep


def _mine(header, min_count, suffix, out):
    # items ascending by support: mine least-frequent first (classic order)
    for item in sorted(header, key=lambda i: sum(n.count for n in header[i])):
        nodes = header[item]
        support = sum(n.count for n in nodes)
        if support < min_count:
            continue
        itemset = suffix | {item}
        out[frozenset(itemset)] = support
        # conditional pattern base: prefix paths with this node's count
        conditional: Dict[Tuple, int] = defaultdict(int)
        for n in nodes:
            path = []
            p = n.parent
            while p is not None and p.item is not None:
                path.append(p.item)
                p = p.parent
            if path:
                conditional[tuple(path)] += n.count
        if conditional:
            _root, sub_header, _f, _k = _build_tree(
                dict(conditional), min_count
            )
            _mine(sub_header, min_count, itemset, out)


@dataclass(frozen=True)
class Rule:
    antecedent: FrozenSet
    consequent: FrozenSet
    confidence: float
    support: float  # of antecedent+consequent, as a fraction


class FPGrowthModel:
    def __init__(self, itemsets: Dict[FrozenSet, int], num_transactions: int):
        self.freq_itemsets = itemsets
        self.num_transactions = num_transactions

    def itemsets(self) -> List[Tuple[FrozenSet, int]]:
        """Frequent itemsets with absolute support counts, support-desc."""
        return sorted(
            self.freq_itemsets.items(),
            key=lambda kv: (-kv[1], sorted(map(repr, kv[0]))),
        )

    def association_rules(self, min_confidence: float = 0.8) -> List[Rule]:
        """``AssociationRules.run`` parity: single-consequent rules X -> y
        with confidence = support(X+y) / support(X)."""
        rules: List[Rule] = []
        for items, count in self.freq_itemsets.items():
            if len(items) < 2:
                continue
            for y in items:
                antecedent = items - {y}
                base = self.freq_itemsets.get(antecedent)
                if not base:
                    continue
                conf = count / base
                if conf >= min_confidence:
                    rules.append(Rule(
                        antecedent=antecedent,
                        consequent=frozenset({y}),
                        confidence=conf,
                        support=count / self.num_transactions,
                    ))
        return sorted(
            rules, key=lambda r: (-r.confidence, sorted(map(repr, r.antecedent)))
        )


class AssociationRules:
    """Standalone rule generator (``AssociationRules.scala`` public API):
    takes pre-mined (itemset, count) pairs, emits single-consequent rules.
    ``FPGrowthModel.association_rules`` delegates the same logic."""

    def __init__(self, min_confidence: float = 0.8):
        if not 0.0 <= min_confidence <= 1.0:
            raise ValueError("min_confidence must be in [0, 1]")
        self.min_confidence = min_confidence

    def run(
        self,
        freq_itemsets: Iterable[Tuple[Iterable, int]],
        num_transactions: int,
    ) -> List[Rule]:
        if num_transactions < 1:
            # support fractions are counts / num_transactions; guessing the
            # denominator would silently misreport every rule's support
            raise ValueError("num_transactions must be >= 1")
        table = {frozenset(items): int(c) for items, c in freq_itemsets}
        return FPGrowthModel(table, num_transactions).association_rules(
            self.min_confidence
        )


@dataclass(frozen=True)
class FreqSequence:
    """A frequent sequential pattern: a tuple of itemsets + its support."""

    sequence: Tuple[FrozenSet, ...]
    freq: int


class PrefixSpan:
    """Sequential pattern mining by prefix-projected growth.

    Parity: ``mllib/src/main/scala/org/apache/spark/mllib/fpm/
    PrefixSpan.scala`` -- patterns are sequences of itemsets, grown one
    item at a time either by EXTENDING the last itemset (same-element
    growth) or APPENDING a new itemset, counting support in the projected
    database (Pei et al.'s PrefixSpan).  ``min_support`` is a fraction of
    sequences; ``max_pattern_length`` bounds the total item count.

    Host-side for the same reason as FP-Growth (symbolic recursion over
    projections; the reference distributes only to shard candidate
    prefixes).
    """

    def __init__(
        self,
        min_support: float = 0.1,
        max_pattern_length: int = 10,
    ):
        if not 0.0 < min_support <= 1.0:
            raise ValueError("min_support must be in (0, 1]")
        if max_pattern_length < 1:
            raise ValueError("max_pattern_length must be >= 1")
        self.min_support = min_support
        self.max_len = max_pattern_length

    def run(self, sequences: Sequence[Sequence[Iterable]]) -> List[FreqSequence]:
        import math

        db = [[frozenset(ev) for ev in seq if ev] for seq in sequences]
        n = len(db)
        if n == 0:
            raise ValueError("no sequences")
        min_count = max(1, math.ceil(self.min_support * n - 1e-9))
        out: List[FreqSequence] = []
        # projections: list of (seq_idx, event_idx, within-event frontier)
        start = [(i, 0, frozenset()) for i in range(len(db))]
        self._grow((), start, db, min_count, 0, out)
        return sorted(
            out,
            key=lambda f: (-f.freq, len(f.sequence),
                           [sorted(map(repr, s)) for s in f.sequence]),
        )

    def _grow(self, prefix, proj, db, min_count, length, out):
        if length >= self.max_len:
            return
        # candidate growth items: 'append' starts a new itemset; 'extend'
        # adds to the prefix's last itemset (only items > frontier items
        # are considered, using repr order for a canonical form)
        append_support: Dict[object, set] = defaultdict(set)
        extend_support: Dict[object, set] = defaultdict(set)
        for (si, ei, frontier) in proj:
            seq = db[si]
            if frontier:
                # same-element extension: the current event must contain
                # the frontier and a strictly "later" item
                for ev_i in range(ei, len(seq)):
                    ev = seq[ev_i]
                    if frontier <= ev:
                        for item in ev - frontier:
                            if repr(item) > max(map(repr, frontier)):
                                extend_support[item].add(si)
            for ev_i in range(ei + (1 if frontier else 0), len(seq)):
                for item in seq[ev_i]:
                    append_support[item].add(si)
        for item, seqs in sorted(
            extend_support.items(), key=lambda kv: repr(kv[0])
        ):
            if len(seqs) < min_count:
                continue
            last = prefix[-1] | {item}
            pattern = prefix[:-1] + (last,)
            out.append(FreqSequence(pattern, len(seqs)))
            new_proj = []
            for (si, ei, frontier) in proj:
                if si not in seqs or not frontier:
                    continue
                seq = db[si]
                for ev_i in range(ei, len(seq)):
                    if last <= seq[ev_i]:
                        new_proj.append((si, ev_i, last))
                        break
            self._grow(pattern, new_proj, db, min_count, length + 1, out)
        for item, seqs in sorted(
            append_support.items(), key=lambda kv: repr(kv[0])
        ):
            if len(seqs) < min_count:
                continue
            pattern = prefix + (frozenset({item}),)
            out.append(FreqSequence(pattern, len(seqs)))
            new_proj = []
            for (si, ei, frontier) in proj:
                if si not in seqs:
                    continue
                seq = db[si]
                for ev_i in range(ei + (1 if frontier else 0), len(seq)):
                    if item in seq[ev_i]:
                        new_proj.append((si, ev_i, frozenset({item})))
                        break
            self._grow(pattern, new_proj, db, min_count, length + 1, out)


class FPGrowth:
    """``new FPGrowth().setMinSupport(s).run(transactions)`` analog."""

    def __init__(self, min_support: float = 0.3):
        if not 0.0 < min_support <= 1.0:
            raise ValueError("min_support must be in (0, 1]")
        self.min_support = min_support

    def run(self, transactions: Sequence[Iterable]) -> FPGrowthModel:
        txs = [list(t) for t in transactions]
        n = len(txs)
        if n == 0:
            raise ValueError("no transactions")
        import math

        min_count = max(1, math.ceil(self.min_support * n - 1e-9))
        _root, header, _freq, _keep = _build_tree(txs, min_count)
        out: Dict[FrozenSet, int] = {}
        _mine(header, min_count, frozenset(), out)
        return FPGrowthModel(out, n)
