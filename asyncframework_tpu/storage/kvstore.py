"""Embedded persistent key/value store (history / app-status state).

Parity: the reference's ``common/kvstore`` -- a LevelDB-backed (leveldbjni,
``pom.xml:468``) embedded KV used by the UI/status store and history server,
NOT by the data path.  Here the same capability is an append-only record log
with an in-memory index and compaction:

- native backend: ``native/kvstore.cc`` via ctypes (built on demand);
- pure-Python fallback speaking the **identical file format** (magic
  ``AKV1``; ``[u32 klen][u32 vlen][key][val]`` records, ``vlen=0xFFFFFFFF``
  tombstones), so a store written by either implementation opens in both.

The Python-facing API is dict-like over ``bytes``/``str`` keys and values,
plus a JSON object layer (:meth:`put_obj`/:meth:`get_obj`) matching how the
reference stores typed records via its ``KVStoreSerializer``.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
import threading
from typing import Dict, Iterator, Optional, Union

_MAGIC = b"AKV1"
_TOMBSTONE = 0xFFFFFFFF

Bytes = Union[bytes, str]


def _to_bytes(x: Bytes) -> bytes:
    return x.encode() if isinstance(x, str) else x


def string_hash_code(s: Bytes) -> int:
    """Java ``String.hashCode`` semantics (parity with the reference's only
    in-tree C file, ``R/pkg/src-native/string_hash_code.c``): int32 rolling
    ``h = 31*h + byte`` with wraparound."""
    h = 0
    for b in _to_bytes(s):
        h = (h * 31 + b) & 0xFFFFFFFF
    return h - 0x100000000 if h >= 0x80000000 else h


_LIB = None

#: native symbol -> pure-Python twin (native-oracle lint contract).
#: The twin here is class-shaped: ``_PyBackend`` speaks the identical
#: AKV1 file format and backend selection happens once, in
#: ``KVStore.__init__``.
NATIVE_ORACLES = {
    "kv_open": "_PyBackend.__init__",
    "kv_put": "_PyBackend.put",
    "kv_get": "_PyBackend.get",
    "kv_get_len": "_PyBackend.get",
    "kv_delete": "_PyBackend.delete",
    "kv_count": "_PyBackend.count",
    "kv_compact": "_PyBackend.compact",
    "kv_keys_size": "_PyBackend.keys",
    "kv_keys_fill": "_PyBackend.keys",
    "kv_close": "_PyBackend.close",
    "string_hash_code": "string_hash_code",
}


def _native_lib():
    global _LIB
    if _LIB is not None:
        return _LIB or None
    try:
        from asyncframework_tpu.native_build import ensure_built
        path = ensure_built("kvstore")
    except Exception:
        path = None
    if path is None:
        _LIB = False
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        _LIB = False
        return None
    lib.kv_open.restype = ctypes.c_void_p
    lib.kv_open.argtypes = [ctypes.c_char_p]
    lib.kv_put.restype = ctypes.c_int
    lib.kv_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                           ctypes.c_char_p, ctypes.c_uint32]
    lib.kv_get.restype = ctypes.c_longlong
    lib.kv_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32,
                           ctypes.c_char_p, ctypes.c_longlong]
    lib.kv_get_len.restype = ctypes.c_longlong
    lib.kv_get_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.kv_delete.restype = ctypes.c_int
    lib.kv_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint32]
    lib.kv_count.restype = ctypes.c_longlong
    lib.kv_count.argtypes = [ctypes.c_void_p]
    lib.kv_compact.restype = ctypes.c_int
    lib.kv_compact.argtypes = [ctypes.c_void_p]
    lib.kv_keys_size.restype = ctypes.c_longlong
    lib.kv_keys_size.argtypes = [ctypes.c_void_p]
    lib.kv_keys_fill.restype = ctypes.c_longlong
    lib.kv_keys_fill.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_longlong]
    lib.kv_close.restype = None
    lib.kv_close.argtypes = [ctypes.c_void_p]
    lib.string_hash_code.restype = ctypes.c_int
    lib.string_hash_code.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    _LIB = lib
    return lib


class _PyBackend:
    """Pure-Python reader/writer of the shared AKV1 log format."""

    def __init__(self, path: str):
        self.path = path
        self.live: Dict[bytes, bytes] = {}
        fresh = not os.path.exists(path)
        if not fresh and os.path.getsize(path) < len(_MAGIC):
            # crash between file creation and the magic write: treat as fresh
            # (consistent with the torn-tail truncation policy) instead of
            # failing every subsequent open as "not an AKV1 kvstore"
            os.remove(path)
            fresh = True
        if not fresh:
            valid_end = self._load()
            if valid_end is not None:
                # torn tail from a crashed writer: truncate before appending,
                # otherwise new records land after garbage and the *next*
                # reopen misparses everything from the torn point on
                with open(path, "r+b") as f:
                    f.truncate(valid_end)
        self._f = open(path, "ab")
        if fresh:
            self._f.write(_MAGIC)
            self._f.flush()

    def _load(self) -> Optional[int]:
        """Replay the log; returns the offset of a torn tail (to truncate)
        or None when the file ends on a record boundary."""
        with open(self.path, "rb") as f:
            if f.read(4) != _MAGIC:
                raise ValueError(f"{self.path}: not an AKV1 kvstore")
            while True:
                rec_start = f.tell()
                hdr = f.read(8)
                if not hdr:
                    return None  # clean end
                if len(hdr) < 8:
                    return rec_start
                kl, vl = struct.unpack("<II", hdr)
                key = f.read(kl)
                if len(key) < kl:
                    return rec_start  # torn record
                if vl == _TOMBSTONE:
                    self.live.pop(key, None)
                    continue
                val = f.read(vl)
                if len(val) < vl:
                    return rec_start  # torn record
                self.live[key] = val

    def put(self, key: bytes, val: bytes) -> None:
        self._f.write(struct.pack("<II", len(key), len(val)))
        self._f.write(key)
        self._f.write(val)
        self._f.flush()
        self.live[key] = val

    def get(self, key: bytes) -> Optional[bytes]:
        return self.live.get(key)

    def delete(self, key: bytes) -> None:
        self._f.write(struct.pack("<II", len(key), _TOMBSTONE))
        self._f.write(key)
        self._f.flush()
        self.live.pop(key, None)

    def count(self) -> int:
        return len(self.live)

    def keys(self):
        return list(self.live.keys())

    def compact(self) -> None:
        tmp = self.path + ".compact"
        with open(tmp, "wb") as f:
            f.write(_MAGIC)
            for k, v in self.live.items():
                f.write(struct.pack("<II", len(k), len(v)))
                f.write(k)
                f.write(v)
        self._f.close()
        os.replace(tmp, self.path)
        self._f = open(self.path, "ab")

    def close(self) -> None:
        self._f.close()


class _NativeBackend:
    def __init__(self, lib, path: str):
        self._lib = lib
        self._h = lib.kv_open(path.encode())
        if not self._h:
            raise ValueError(f"{path}: native kv_open failed (bad magic?)")

    def put(self, key: bytes, val: bytes) -> None:
        if self._lib.kv_put(self._h, key, len(key), val, len(val)) != 0:
            raise IOError("kv_put failed")

    def get(self, key: bytes) -> Optional[bytes]:
        n = self._lib.kv_get_len(self._h, key, len(key))
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.kv_get(self._h, key, len(key), buf, n)
        if got < 0:
            return None
        return buf.raw[:got]

    def delete(self, key: bytes) -> None:
        self._lib.kv_delete(self._h, key, len(key))

    def count(self) -> int:
        return int(self._lib.kv_count(self._h))

    def keys(self):
        size = self._lib.kv_keys_size(self._h)
        buf = ctypes.create_string_buffer(int(size) or 1)
        n = self._lib.kv_keys_fill(self._h, buf, size)
        out, off = [], 0
        raw = buf.raw[: max(n, 0)]
        while off < len(raw):
            (kl,) = struct.unpack_from("<I", raw, off)
            off += 4
            out.append(raw[off:off + kl])
            off += kl
        return out

    def compact(self) -> None:
        if self._lib.kv_compact(self._h) != 0:
            raise IOError("kv_compact failed")

    def close(self) -> None:
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None


class KVStore:
    """Dict-like persistent store; ``backend`` is 'auto' | 'native' | 'python'."""

    def __init__(self, path, backend: str = "auto"):
        path = str(path)
        self._lock = threading.Lock()
        if backend not in ("auto", "native", "python"):
            raise ValueError(f"unknown backend {backend!r}")
        lib = _native_lib() if backend in ("auto", "native") else None
        if backend == "native" and lib is None:
            raise RuntimeError("native kvstore backend unavailable (no g++?)")
        self._b = _NativeBackend(lib, path) if lib is not None else _PyBackend(path)
        self.backend = "native" if lib is not None else "python"

    # ------------------------------------------------------------- raw bytes
    def put(self, key: Bytes, val: Bytes) -> None:
        with self._lock:
            self._b.put(_to_bytes(key), _to_bytes(val))

    def get(self, key: Bytes, default: Optional[bytes] = None) -> Optional[bytes]:
        with self._lock:
            v = self._b.get(_to_bytes(key))
        return default if v is None else v

    def delete(self, key: Bytes) -> None:
        with self._lock:
            self._b.delete(_to_bytes(key))

    def __contains__(self, key: Bytes) -> bool:
        return self.get(key) is not None

    def __len__(self) -> int:
        with self._lock:
            return self._b.count()

    def keys(self) -> Iterator[bytes]:
        with self._lock:
            return iter(sorted(self._b.keys()))

    def compact(self) -> None:
        with self._lock:
            self._b.compact()

    def close(self) -> None:
        with self._lock:
            self._b.close()

    # ----------------------------------------------------------- JSON object
    def put_obj(self, key: Bytes, obj) -> None:
        self.put(key, json.dumps(obj).encode())

    def get_obj(self, key: Bytes, default=None):
        v = self.get(key)
        return default if v is None else json.loads(v.decode())

    def __enter__(self) -> "KVStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
