from asyncframework_tpu.storage.kvstore import KVStore, string_hash_code

__all__ = ["KVStore", "string_hash_code"]
