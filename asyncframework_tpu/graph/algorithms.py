"""Graph algorithms on the Pregel substrate.

Parity: GraphX ``lib/`` -- ``PageRank.scala`` (damping 0.85, teleport
``(1-a)/n`` formulation in the standalone runner) and
``ConnectedComponents.scala`` (min-id label propagation).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from asyncframework_tpu.graph.graph import Graph
from asyncframework_tpu.graph.pregel import pregel


def _pagerank_impl(
    graph: Graph,
    teleport: jnp.ndarray,
    alpha: float,
    num_iterations: int,
    tol: Optional[float],
) -> jnp.ndarray:
    """One power-iteration lowering shared by both PageRank variants:
    ``r' = (1-a)*teleport + a*(sum_in r/outdeg + dangling_mass*teleport)``
    -- uniform ``teleport`` is classic PageRank, a one-hot is the
    personalized form.  Teleport and dangling mass share the same
    destination distribution (both variants' semantics)."""
    outdeg = graph.out_degrees().astype(jnp.float32)
    safe_deg = jnp.maximum(outdeg, 1)
    dangling = (outdeg == 0).astype(jnp.float32)

    def vprog(r, incoming):
        # dangling vertices' rank re-enters via the teleport distribution;
        # recomputed from the *current* ranks so it is one fused pass
        d_mass = jnp.sum(r * dangling)
        return (1.0 - alpha) * teleport + alpha * (
            incoming + d_mass * teleport
        )

    def send_msg(src_r, dst_r, _e):
        # message = r[src]/outdeg[src]: the division rides the edge gather
        return src_r / safe_deg[graph.src]

    return pregel(
        graph, teleport, vprog, send_msg, merge="sum",
        max_iterations=num_iterations, tol=tol,
    )


def pagerank(
    graph: Graph,
    alpha: float = 0.85,
    num_iterations: int = 20,
    tol: Optional[float] = None,
) -> jnp.ndarray:
    """Normalized PageRank (ranks sum to 1; dangling mass redistributed).

    ``r' = (1-a)/n + a * (sum_in r/outdeg + dangling/n)``.
    With ``tol`` set, stops early once max-abs rank change <= tol.
    """
    n = graph.num_vertices
    uniform = jnp.full(n, 1.0 / n, jnp.float32)
    return _pagerank_impl(graph, uniform, alpha, num_iterations, tol)


def personalized_pagerank(
    graph: Graph,
    source: int,
    alpha: float = 0.85,
    num_iterations: int = 20,
    tol: Optional[float] = None,
) -> jnp.ndarray:
    """Personalized PageRank from a single source vertex (GraphX
    ``PageRank.runWithOptions`` with ``srcId`` semantics): the teleport
    mass returns to ``source`` instead of spreading uniformly, so ranks
    measure proximity to the source."""
    n = graph.num_vertices
    if not 0 <= source < n:
        raise ValueError(f"source {source} out of range [0, {n})")
    onehot = jnp.zeros(n, jnp.float32).at[source].set(1.0)
    return _pagerank_impl(graph, onehot, alpha, num_iterations, tol)


def connected_components(graph: Graph, max_iterations: int = 100) -> jnp.ndarray:
    """Label each vertex with the smallest vertex id in its (weakly)
    connected component (GraphX ``ConnectedComponents`` semantics)."""
    n = graph.num_vertices
    # weak connectivity: propagate along both edge directions
    src = jnp.concatenate([graph.src, graph.dst])
    dst = jnp.concatenate([graph.dst, graph.src])
    g2 = Graph(src, dst, n)

    # int32 labels: exact for every representable vertex count (float32
    # would collide ids above 2**24); the min-merge identity is INT32_MAX
    labels0 = jnp.arange(n, dtype=jnp.int32)

    def vprog(lbl, incoming):
        return jnp.minimum(lbl, incoming)

    def send_msg(src_lbl, dst_lbl, _e):
        return src_lbl

    return pregel(
        g2, labels0, vprog, send_msg, merge="min",
        max_iterations=max_iterations,
    )


def triangle_count(graph: Graph) -> jnp.ndarray:
    """Per-vertex triangle counts (GraphX ``TriangleCount.scala`` semantics:
    the graph is canonicalized -- undirected, deduped, no self loops).

    TPU-first: the reference intersects per-vertex neighbor sets through a
    shuffle; here the graph is materialized as a dense 0/1 adjacency matrix
    and counted with matmuls on the MXU -- ``count_v = (A @ A * A).sum(row)/2``
    counts, for each edge (v,u), the common neighbors of v and u.  O(n^2)
    memory by design: the dense regime (n up to ~2^14, 1 GB HBM at f32)
    covers the reference's own benchmark graphs; larger graphs shard A's
    rows over the mesh.
    """
    n = graph.num_vertices
    src, dst = graph.src, graph.dst
    keep = src != dst  # drop self loops
    A = jnp.zeros((n, n), jnp.float32)
    A = A.at[src, dst].max(jnp.where(keep, 1.0, 0.0))
    A = jnp.maximum(A, A.T)  # canonical undirected, deduped
    common = (A @ A) * A
    return (common.sum(axis=1) / 2).astype(jnp.int32)


def label_propagation(graph: Graph, max_iterations: int = 10) -> jnp.ndarray:
    """Community detection by synchronous label propagation (GraphX
    ``LabelPropagation.scala``): every step each vertex adopts the most
    frequent label among its neighbors (ties -> smallest label, a
    deterministic refinement of the reference's map-ordering tie).

    Dense label-histogram formulation: labels live in ``0..n-1``, so one
    scatter-add builds the (n, n) neighbor-label histogram per step --
    O(n^2) memory, same regime note as :func:`triangle_count`.
    """
    n = graph.num_vertices
    src = jnp.concatenate([graph.src, graph.dst])
    dst = jnp.concatenate([graph.dst, graph.src])
    labels = jnp.arange(n, dtype=jnp.int32)

    def step(_, labels):
        hist = jnp.zeros((n, n), jnp.int32).at[dst, labels[src]].add(1)
        # most frequent neighbor label; ties break to the SMALLEST label
        # (argmax returns the first maximum)
        best = jnp.argmax(hist, axis=1).astype(jnp.int32)
        has_neighbors = hist.sum(axis=1) > 0
        return jnp.where(has_neighbors, best, labels)

    import jax

    return jax.lax.fori_loop(0, max_iterations, step, labels)


def shortest_paths(
    graph: Graph, landmarks, max_iterations: int = 50
) -> jnp.ndarray:
    """Hop-count distances from every vertex to each landmark (GraphX
    ``ShortestPaths.scala``).  Returns (n, L) float32 with ``inf`` for
    unreachable pairs.  One Pregel run with a vector vertex attribute:
    the per-edge message is ``dist[src] + 1`` and the merge is ``min`` --
    the min-plus semiring ridden by a segment-min.
    """
    n = graph.num_vertices
    lms = jnp.asarray(landmarks, jnp.int32)
    L = int(lms.shape[0])
    # undirected hop counts: propagate along both edge directions
    g2 = Graph(
        jnp.concatenate([graph.dst, graph.src]),
        jnp.concatenate([graph.src, graph.dst]),
        n,
    )
    d0 = jnp.full((n, L), jnp.inf, jnp.float32)
    d0 = d0.at[lms, jnp.arange(L)].set(0.0)

    def vprog(d, incoming):
        return jnp.minimum(d, incoming)

    def send_msg(src_d, dst_d, _e):
        return src_d + 1.0

    return pregel(
        g2, d0, vprog, send_msg, merge="min",
        max_iterations=max_iterations,
    )


def strongly_connected_components(
    graph: Graph, max_iterations: int = 100
) -> jnp.ndarray:
    """Label each vertex with the smallest vertex id in its strongly
    connected component (GraphX ``StronglyConnectedComponents.scala``
    semantics).

    Forward-backward reachability on dense boolean adjacency: vertices u, v
    are in the same SCC iff v reaches u AND u reaches v.  Reachability
    closure is computed by log-squaring the adjacency matrix on the MXU
    (O(log n) matmuls) -- the dense-regime trade documented for
    :func:`triangle_count` (the reference instead peels color-by-color
    through repeated Pregel rounds).  The SCC label is the min id over the
    intersection of forward and backward reachable sets.
    """
    import jax

    n = graph.num_vertices
    keep = graph.src != graph.dst
    A = jnp.zeros((n, n), jnp.bool_)
    A = A.at[graph.src, graph.dst].max(keep)
    R = A | jnp.eye(n, dtype=jnp.bool_)  # reflexive reachability

    # transitive closure by boolean log-squaring: R <- R "or-and" R
    iters = max(1, min(int(jnp.ceil(jnp.log2(max(n, 2)))), max_iterations))

    def square(_, R):
        Rf = R.astype(jnp.float32)
        return R | ((Rf @ Rf) > 0)

    R = jax.lax.fori_loop(0, iters, square, R)
    both = R & R.T  # u ~ v iff mutual reachability
    ids = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(jnp.iinfo(jnp.int32).max)
    return jnp.min(jnp.where(both, ids[None, :], big), axis=1)


def svd_plus_plus(
    src,
    dst,
    ratings,
    rank: int = 8,
    num_iterations: int = 200,
    lr: float = 0.5,
    reg: float = 0.015,
    num_users: Optional[int] = None,
    num_items: Optional[int] = None,
    seed: int = 0,
):
    """SVD++ collaborative filtering on a bipartite rating graph.

    Parity: GraphX ``lib/SVDPlusPlus.scala`` (Koren's model) -- prediction

        r_hat(u, i) = mu + b_u + b_i + q_i . (p_u + |N(u)|^-1/2 sum_j y_j)

    trained by gradient steps on squared error with L2 regularization.
    The reference runs per-edge Pregel messages; here every iteration is
    one jitted dense gather/scatter-add pass over the edge list (edges are
    the batch dimension -- MXU-friendly), full-batch GD instead of the
    reference's per-edge SGD (documented delta: same objective, stabler on
    a batched device).

    Returns an :class:`SVDPlusPlusModel` carrying the effective user
    vectors (explicit + implicit-feedback term already folded in).
    """
    import jax

    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    r = jnp.asarray(ratings, jnp.float32)
    nu = int(num_users) if num_users is not None else int(src.max()) + 1
    ni = int(num_items) if num_items is not None else int(dst.max()) + 1
    # validate explicit bounds: an underestimate would silently corrupt
    # training (jit scatter drops OOB rows, gather clamps to the last id)
    if int(src.max()) >= nu or int(src.min()) < 0:
        raise ValueError(f"user ids must be in [0, {nu}) -- got "
                         f"[{int(src.min())}, {int(src.max())}]")
    if int(dst.max()) >= ni or int(dst.min()) < 0:
        raise ValueError(f"item ids must be in [0, {ni}) -- got "
                         f"[{int(dst.min())}, {int(dst.max())}]")
    mu = float(jnp.mean(r))

    key = jax.random.PRNGKey(seed)
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / jnp.sqrt(rank)
    P0 = jax.random.normal(k1, (nu, rank), jnp.float32) * scale * 0.1
    Q0 = jax.random.normal(k2, (ni, rank), jnp.float32) * scale * 0.1
    Y0 = jax.random.normal(k3, (ni, rank), jnp.float32) * scale * 0.1

    # |N(u)|^{-1/2} and the per-user implicit-feedback item sets ride the
    # edge list: sum_j y_j per user is one segment-sum over edges
    deg = jnp.zeros(nu, jnp.float32).at[src].add(1.0)
    inv_sqrt = 1.0 / jnp.sqrt(jnp.maximum(deg, 1.0))

    def loss_fn(params):
        P, Q, Y, bu, bi = params
        ysum = jnp.zeros((nu, rank), jnp.float32).at[src].add(Y[dst])
        pu_eff = P + ysum * inv_sqrt[:, None]
        pred = (
            mu + bu[src] + bi[dst]
            + jnp.sum(Q[dst] * pu_eff[src], axis=1)
        )
        err = pred - r
        l2 = (
            jnp.sum(P * P) + jnp.sum(Q * Q) + jnp.sum(Y * Y)
            + jnp.sum(bu * bu) + jnp.sum(bi * bi)
        )
        # per-edge normalization makes the learning rate scale-free (the
        # reference's per-edge SGD has the same property by construction)
        m = r.shape[0]
        return (0.5 * jnp.sum(err * err) + 0.5 * reg * l2) / m

    @jax.jit
    def train(params):
        def step(_, params):
            grads = jax.grad(loss_fn)(params)
            return jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads
            )

        return jax.lax.fori_loop(0, num_iterations, step, params)

    P, Q, Y, bu, bi = train(
        (P0, Q0, Y0, jnp.zeros(nu, jnp.float32), jnp.zeros(ni, jnp.float32))
    )
    import numpy as np

    # fold the implicit-feedback sum into effective user vectors once, so
    # prediction needs no edge list
    ysum = jnp.zeros((nu, rank), jnp.float32).at[src].add(Y[dst])
    P_eff = P + ysum * inv_sqrt[:, None]
    return SVDPlusPlusModel(
        user_vectors=np.asarray(P_eff),
        item_vectors=np.asarray(Q),
        user_bias=np.asarray(bu),
        item_bias=np.asarray(bi),
        mean=mu,
    )


class SVDPlusPlusModel:
    """Trained SVD++ factors; ``predict`` is one gather + dot per pair."""

    def __init__(self, user_vectors, item_vectors, user_bias, item_bias,
                 mean: float):
        self.user_vectors = user_vectors  # effective: implicit term folded
        self.item_vectors = item_vectors
        self.user_bias = user_bias
        self.item_bias = item_bias
        self.mean = mean

    def predict(self, users, items):
        import numpy as np

        u = np.asarray(users, np.int64)
        i = np.asarray(items, np.int64)
        return (
            self.mean + self.user_bias[u] + self.item_bias[i]
            + np.sum(self.item_vectors[i] * self.user_vectors[u], axis=1)
        )


# ------------------------------------------------------------- partitioning
def partition_edges(
    graph: Graph, num_partitions: int, strategy: str = "edge_2d"
) -> jnp.ndarray:
    """Edge -> partition assignment (GraphX ``PartitionStrategy.scala``).

    Strategies: ``edge_1d`` (hash src -- co-locates out-edges),
    ``edge_2d`` (sqrt-grid block of (src, dst) -- bounds vertex replication
    by 2*sqrt(p)), ``random_vertex_cut`` (hash of the ordered pair),
    ``canonical_random_vertex_cut`` (hash of the sorted pair, so both
    directions of an undirected edge land together).  Deterministic: a
    mixed-congruential integer hash, no process salt.
    """
    if num_partitions < 1:
        raise ValueError("num_partitions must be >= 1")
    src = graph.src.astype(jnp.uint32)
    dst = graph.dst.astype(jnp.uint32)
    p = jnp.uint32(num_partitions)

    def mix(x):
        # xorshift-multiply mix (splitmix-style), stable across runs
        x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
        x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
        return x ^ (x >> 16)

    if strategy == "edge_1d":
        out = mix(src) % p
    elif strategy == "edge_2d":
        import math

        side = int(math.ceil(math.sqrt(num_partitions)))
        col = mix(src) % jnp.uint32(side)
        row = mix(dst) % jnp.uint32(side)
        out = (col * jnp.uint32(side) + row) % p
    elif strategy == "random_vertex_cut":
        out = mix(src * jnp.uint32(0x9E3779B1) ^ dst) % p
    elif strategy == "canonical_random_vertex_cut":
        lo = jnp.minimum(src, dst)
        hi = jnp.maximum(src, dst)
        out = mix(lo * jnp.uint32(0x9E3779B1) ^ hi) % p
    else:
        raise ValueError(
            "strategy must be edge_1d / edge_2d / random_vertex_cut / "
            "canonical_random_vertex_cut"
        )
    return out.astype(jnp.int32)
