"""Graph algorithms on the Pregel substrate.

Parity: GraphX ``lib/`` -- ``PageRank.scala`` (damping 0.85, teleport
``(1-a)/n`` formulation in the standalone runner) and
``ConnectedComponents.scala`` (min-id label propagation).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from asyncframework_tpu.graph.graph import Graph
from asyncframework_tpu.graph.pregel import pregel


def pagerank(
    graph: Graph,
    alpha: float = 0.85,
    num_iterations: int = 20,
    tol: Optional[float] = None,
) -> jnp.ndarray:
    """Normalized PageRank (ranks sum to 1; dangling mass redistributed).

    ``r' = (1-a)/n + a * (sum_in r/outdeg + dangling/n)``.
    With ``tol`` set, stops early once max-abs rank change <= tol.
    """
    n = graph.num_vertices
    outdeg = graph.out_degrees().astype(jnp.float32)
    safe_deg = jnp.maximum(outdeg, 1)
    dangling = (outdeg == 0).astype(jnp.float32)

    def vprog(r, incoming):
        # dangling vertices' rank spreads uniformly; recompute their mass
        # from the *current* ranks so it is one fused pass
        d_mass = jnp.sum(r * dangling)
        return (1.0 - alpha) / n + alpha * (incoming + d_mass / n)

    r0 = jnp.full(n, 1.0 / n, jnp.float32)

    def send_msg(src_r, dst_r, _e):
        # message = r[src]/outdeg[src]: the division rides the edge gather
        return src_r / safe_deg[graph.src]

    return pregel(
        graph, r0, vprog, send_msg, merge="sum",
        max_iterations=num_iterations, tol=tol,
    )


def connected_components(graph: Graph, max_iterations: int = 100) -> jnp.ndarray:
    """Label each vertex with the smallest vertex id in its (weakly)
    connected component (GraphX ``ConnectedComponents`` semantics)."""
    n = graph.num_vertices
    # weak connectivity: propagate along both edge directions
    src = jnp.concatenate([graph.src, graph.dst])
    dst = jnp.concatenate([graph.dst, graph.src])
    g2 = Graph(src, dst, n)

    # int32 labels: exact for every representable vertex count (float32
    # would collide ids above 2**24); the min-merge identity is INT32_MAX
    labels0 = jnp.arange(n, dtype=jnp.int32)

    def vprog(lbl, incoming):
        return jnp.minimum(lbl, incoming)

    def send_msg(src_lbl, dst_lbl, _e):
        return src_lbl

    return pregel(
        g2, labels0, vprog, send_msg, merge="min",
        max_iterations=max_iterations,
    )
