from asyncframework_tpu.graph.graph import Graph
from asyncframework_tpu.graph.pregel import pregel
from asyncframework_tpu.graph.algorithms import (
    SVDPlusPlusModel,
    connected_components,
    label_propagation,
    pagerank,
    partition_edges,
    personalized_pagerank,
    shortest_paths,
    strongly_connected_components,
    svd_plus_plus,
    triangle_count,
)

__all__ = [
    "Graph", "pregel", "pagerank", "connected_components",
    "triangle_count", "label_propagation", "shortest_paths",
    "partition_edges", "strongly_connected_components",
    "svd_plus_plus", "SVDPlusPlusModel", "personalized_pagerank",
]
