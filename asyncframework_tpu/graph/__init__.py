from asyncframework_tpu.graph.graph import Graph
from asyncframework_tpu.graph.pregel import pregel
from asyncframework_tpu.graph.algorithms import connected_components, pagerank

__all__ = ["Graph", "pregel", "pagerank", "connected_components"]
