from asyncframework_tpu.graph.graph import Graph
from asyncframework_tpu.graph.pregel import pregel
from asyncframework_tpu.graph.algorithms import (
    connected_components,
    label_propagation,
    pagerank,
    partition_edges,
    shortest_paths,
    triangle_count,
)

__all__ = [
    "Graph", "pregel", "pagerank", "connected_components",
    "triangle_count", "label_propagation", "shortest_paths",
    "partition_edges",
]
