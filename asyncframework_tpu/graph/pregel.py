"""Pregel: bulk-synchronous vertex programs as one compiled XLA loop.

Parity: ``graphx/.../Pregel.scala:59`` -- iterate { aggregateMessages;
joinVertices(vprog) } until no messages or maxIterations.  The reference's
signature is per-vertex/per-edge callbacks over RDD triplets with an
arbitrary ``mergeMsg`` closure executed during a shuffle.

TPU re-design (deliberate deltas, documented here because they ARE the
design):
- The whole loop is one ``lax.while_loop`` inside ``jit``: no per-iteration
  host round trip, no shuffle -- gather vertex attrs to edges, compute
  messages vectorized over all edges, segment-combine to vertices.
- ``merge`` is a named monoid ('sum' | 'min' | 'max') rather than an
  arbitrary closure: scatter-combine on TPU hardware supports exactly these,
  and every GraphX algorithm in the reference's ``lib/`` uses a monoid.
- Vertices are always "active"; convergence is detected globally (attrs
  unchanged -> stop), which subsumes the reference's empty-message
  termination for monoid merges with identity elements.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from asyncframework_tpu.graph.graph import Graph

_MERGES = ("sum", "min", "max")


def merge_identity(dtype, merge: str):
    """The monoid identity in the message dtype (a vertex with no incoming
    edges keeps exactly this value): 0 for sum, dtype-max for min, dtype-min
    for max -- exact for integer dtypes, +/-inf for floats."""
    if merge == "sum":
        return jnp.zeros((), dtype)
    if jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if merge == "min" else info.min, dtype)
    return jnp.asarray(jnp.inf if merge == "min" else -jnp.inf, dtype)


def segment_combine(msgs, dst, num_vertices: int, merge: str):
    """Combine per-edge messages into per-vertex aggregates."""
    if merge not in _MERGES:
        raise ValueError(f"merge must be one of {sorted(_MERGES)}")
    shape = (num_vertices,) + msgs.shape[1:]
    init = jnp.full(shape, merge_identity(msgs.dtype, merge), msgs.dtype)
    tgt = init.at[dst]
    if merge == "sum":
        return tgt.add(msgs)
    if merge == "min":
        return tgt.min(msgs)
    return tgt.max(msgs)


def pregel(
    graph: Graph,
    initial_attr,
    vprog: Callable,
    send_msg: Callable,
    merge: str = "sum",
    max_iterations: int = 100,
    tol: Optional[float] = None,
):
    """Run a vertex program to convergence.

    ``vprog(attr, agg) -> attr'`` -- vectorized over ALL vertices; ``agg`` is
    the merged message array (monoid identity where a vertex got none).
    ``send_msg(src_attr, dst_attr, edge_attr) -> msgs`` -- vectorized over
    ALL edges (``src_attr = attr[g.src]`` etc.).
    Stops after ``max_iterations`` or when the attribute update is within
    ``tol`` (max-abs for float attrs; exact equality when ``tol`` is None).
    Returns the final vertex attribute array.
    """
    init = jnp.asarray(initial_attr)
    if init.shape[0] != graph.num_vertices:
        raise ValueError("initial_attr first dim must equal num_vertices")
    src, dst = graph.src, graph.dst
    eattr = graph.edge_attr
    n = graph.num_vertices

    def step(attr):
        msgs = send_msg(attr[src], attr[dst], eattr)
        agg = segment_combine(msgs, dst, n, merge)
        return vprog(attr, agg)

    @jax.jit
    def run(attr0):
        def cond(state):
            it, attr, prev = state
            changed = (
                jnp.any(jnp.abs(attr - prev) > tol)
                if tol is not None
                else jnp.any(attr != prev)
            )
            # it == 0 forces the first iteration (prev0 == attr0)
            return jnp.logical_and(
                it < max_iterations, jnp.logical_or(it == 0, changed)
            )

        def body(state):
            it, attr, _ = state
            return it + 1, step(attr), attr

        _, attr, _ = jax.lax.while_loop(cond, body, (0, attr0, attr0))
        return attr

    return run(init)
