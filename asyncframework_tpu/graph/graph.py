"""Graph representation: dense edge arrays in device memory.

Parity: GraphX's ``Graph``/``VertexRDD``/``EdgeRDD`` (``graphx/.../Graph.scala``
family) -- there, vertices and edges are partitioned RDDs with routing tables
so triplets can join vertex attrs to edges.  TPU re-design: a graph is two
int32 edge-endpoint arrays plus optional vertex/edge attribute arrays, all
static-shaped device residents.  The "join" is a gather (``attr[src]``), the
"message aggregation" is a segment combine (scatter-add/min/max) -- both
single XLA ops that map onto the TPU's gather/scatter units, replacing
GraphX's shuffle-based ``aggregateMessages`` with zero communication (or a
mesh collective when edge-sharded).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


class Graph:
    """Immutable edge-list graph.

    ``src``/``dst``: int32 arrays of shape (E,).  Vertex ids are dense
    ``0..num_vertices-1`` (the reference allows arbitrary i64 ids and pays a
    routing table for it; dense ids keep every op a flat gather/scatter).
    """

    def __init__(
        self,
        src,
        dst,
        num_vertices: Optional[int] = None,
        vertex_attr=None,
        edge_attr=None,
    ):
        self.src = jnp.asarray(src, jnp.int32)
        self.dst = jnp.asarray(dst, jnp.int32)
        if self.src.shape != self.dst.shape or self.src.ndim != 1:
            raise ValueError("src/dst must be 1-d arrays of equal length")
        if num_vertices is None:
            if self.src.size == 0:
                raise ValueError("num_vertices required for an empty graph")
            num_vertices = int(
                max(int(jnp.max(self.src)), int(jnp.max(self.dst))) + 1
            )
        self.num_vertices = int(num_vertices)
        self.vertex_attr = (
            None if vertex_attr is None else jnp.asarray(vertex_attr)
        )
        self.edge_attr = None if edge_attr is None else jnp.asarray(edge_attr)
        if (
            self.vertex_attr is not None
            and self.vertex_attr.shape[0] != self.num_vertices
        ):
            raise ValueError("vertex_attr first dim must equal num_vertices")
        if self.edge_attr is not None and self.edge_attr.shape[0] != self.src.shape[0]:
            raise ValueError("edge_attr first dim must equal num_edges")

    @classmethod
    def from_edge_ids(
        cls,
        src_ids,
        dst_ids,
        vertex_attr_by_id: Optional[dict] = None,
        edge_attr=None,
    ) -> "Graph":
        """Build a graph from ARBITRARY vertex ids (sparse i64, hashes --
        the ids GraphX accepts and pays a routing table for).

        The dense relabeling is computed once on host (`np.unique` over the
        edge endpoints) and remembered: ``vertex_ids[j]`` is the original id
        of dense vertex ``j``, and every algorithm's per-vertex output can
        be re-keyed with :meth:`original_ids`.  This is the routing table's
        job done once at construction instead of per-superstep shuffle.
        """
        src_ids = np.asarray(src_ids)
        dst_ids = np.asarray(dst_ids)
        endpoints = np.concatenate([src_ids, dst_ids])
        # ids supplied only through attributes become ISOLATED vertices
        # (GraphX keeps the vertex set's extra ids; silently dropping an
        # entity the caller named would corrupt per-vertex outputs)
        universe = endpoints
        if vertex_attr_by_id is not None:
            universe = np.concatenate([
                endpoints,
                np.asarray(list(vertex_attr_by_id), endpoints.dtype),
            ])
        ids = np.unique(universe)
        inv = np.searchsorted(ids, endpoints)
        e = len(src_ids)
        vattr = None
        if vertex_attr_by_id is not None:
            missing = [i for i in ids.tolist() if i not in vertex_attr_by_id]
            if missing:
                raise ValueError(
                    f"vertex_attr_by_id missing ids (first few): "
                    f"{missing[:5]}"
                )
            vattr = np.asarray([vertex_attr_by_id[i] for i in ids.tolist()])
        g = cls(
            inv[:e].astype(np.int32),
            inv[e:].astype(np.int32),
            num_vertices=int(len(ids)),
            vertex_attr=vattr,
            edge_attr=edge_attr,
        )
        g.vertex_ids = ids  # dense index -> original id
        return g

    def original_ids(self) -> np.ndarray:
        """Original vertex id per dense index (identity for graphs built
        with dense ids)."""
        ids = getattr(self, "vertex_ids", None)
        return ids if ids is not None else np.arange(self.num_vertices)

    def _keep_ids(self, g: "Graph") -> "Graph":
        """Views preserve the vertex DOMAIN, so the original-id mapping
        carries over unchanged (derived graphs must re-key correctly)."""
        ids = getattr(self, "vertex_ids", None)
        if ids is not None:
            g.vertex_ids = ids
        return g

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    # --------------------------------------------------------------- degrees
    def out_degrees(self) -> jax.Array:
        return jnp.zeros(self.num_vertices, jnp.int32).at[self.src].add(1)

    def in_degrees(self) -> jax.Array:
        return jnp.zeros(self.num_vertices, jnp.int32).at[self.dst].add(1)

    def degrees(self) -> jax.Array:
        return self.out_degrees() + self.in_degrees()

    # ---------------------------------------------------------------- views
    def reverse(self) -> "Graph":
        return self._keep_ids(Graph(
            self.dst, self.src, self.num_vertices, self.vertex_attr,
            self.edge_attr,
        ))

    def with_vertex_attr(self, attr) -> "Graph":
        return self._keep_ids(
            Graph(self.src, self.dst, self.num_vertices, attr,
                  self.edge_attr)
        )

    def map_vertices(self, f) -> "Graph":
        """``Graph.mapVertices`` parity: new vertex attributes from one
        vectorized map over the attribute array."""
        if self.vertex_attr is None:
            raise ValueError("graph has no vertex_attr to map")
        return self.with_vertex_attr(f(self.vertex_attr))

    def map_edges(self, f) -> "Graph":
        """``Graph.mapEdges`` parity (vectorized over the edge array)."""
        if self.edge_attr is None:
            raise ValueError("graph has no edge_attr to map")
        return self._keep_ids(Graph(
            self.src, self.dst, self.num_vertices, self.vertex_attr,
            f(self.edge_attr),
        ))

    def subgraph(self, edge_mask=None, vertex_mask=None) -> "Graph":
        """``Graph.subgraph`` parity: keep edges passing ``edge_mask``
        whose BOTH endpoints pass ``vertex_mask``.  Vertex ids are
        preserved (dropped vertices just become isolates), matching the
        reference's behavior of keeping the vertex domain."""
        keep = jnp.ones(self.num_edges, bool)
        if edge_mask is not None:
            keep = keep & jnp.asarray(edge_mask, bool)
        if vertex_mask is not None:
            vm = jnp.asarray(vertex_mask, bool)
            if vm.shape[0] != self.num_vertices:
                raise ValueError("vertex_mask must have num_vertices entries")
            keep = keep & vm[self.src] & vm[self.dst]
        idx = np.nonzero(np.asarray(keep))[0]
        return self._keep_ids(Graph(
            np.asarray(self.src)[idx], np.asarray(self.dst)[idx],
            self.num_vertices, self.vertex_attr,
            None if self.edge_attr is None
            else np.asarray(self.edge_attr)[idx],
        ))

    def aggregate_messages(self, send_msg, merge: str = "sum"):
        """``Graph.aggregateMessages`` parity -- THE GraphX primitive: per
        edge, ``send_msg(src_attr, dst_attr, edge_attr)`` produces a message
        to the edge's destination; messages combine per vertex with one
        device segment-``merge``.  Returns the (num_vertices, ...) combined
        array (vertices with no messages get the merge identity)."""
        from asyncframework_tpu.graph.pregel import segment_combine

        sa = (
            self.vertex_attr[self.src]
            if self.vertex_attr is not None else None
        )
        da = (
            self.vertex_attr[self.dst]
            if self.vertex_attr is not None else None
        )
        msgs = send_msg(sa, da, self.edge_attr)
        return segment_combine(msgs, self.dst, self.num_vertices, merge)

    @classmethod
    def from_edges(cls, edges, num_vertices: Optional[int] = None) -> "Graph":
        """Build from an (E, 2) array or list of (src, dst) pairs."""
        e = np.asarray(edges, np.int32)
        if e.ndim != 2 or e.shape[1] != 2:
            raise ValueError("edges must be (E, 2)")
        return cls(e[:, 0], e[:, 1], num_vertices)

    def __repr__(self) -> str:  # pragma: no cover
        return f"Graph(V={self.num_vertices}, E={self.num_edges})"
