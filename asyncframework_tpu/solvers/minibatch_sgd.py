"""Synchronous mini-batch SGD as a single compiled SPMD program.

Parity: MLlib's ``GradientDescent.runMiniBatchSGD``
(``mllib/.../optimization/GradientDescent.scala:197-295``): per iteration,
broadcast w, Bernoulli-sample fraction ``b``, tree-aggregate
(gradient_sum, loss_sum, count), update via an ``Updater`` (simple / L2 / L1 --
``Updater.scala:41,70,140``), record a stochastic loss history, and (the
fork's delta) a weight trajectory every ``snapshot_every`` iterations
(``Warray``, ``GradientDescent.scala:156,255-259``).

TPU re-design: the reference runs one cluster job per iteration (broadcast +
barrier per step).  Here the *entire* training loop is one jitted
``shard_map``'d ``lax.scan`` over the device mesh: data stays sharded in HBM
across the batch axis, each scan step draws a per-device mask (stateless
fold_in keys -- ``sample(false, b, seed+i)`` parity), computes the local
gradient sum, ``psum``s it over ICI, and applies the update on every device
identically.  Zero host round-trips for the whole run; the per-step stochastic
loss and the weight trajectory come back as stacked scan outputs.

2-D meshes: with a mesh carrying a model-dim axis (``("dp", "md")``), rows
shard over ``dp`` AND features over ``md`` (net-new tensor-parallel scope:
the reference replicates its whole ``w``, which caps it at models that fit
one executor heap).  Per step the partial products ``X_l w_l`` psum over
``md`` into the full margin, the gradient slice psums over ``dp``, and each
device updates only ITS ``w`` slice -- both collectives ride ICI, and ``w``
never materializes whole on any chip.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.metrics import trace as _trace
from asyncframework_tpu.parallel.mesh import (
    make_mesh,
    pad_and_shard,
    resolve_shard_map,
)


class MiniBatchSGD:
    """Updaters: 'simple' (no reg), 'l2', 'l1' (soft-threshold), matching the
    reference's three Updater classes."""

    def __init__(
        self,
        gamma: float = 1.0,
        batch_rate: float = 1.0,
        num_iterations: int = 100,
        loss: str = "least_squares",
        updater: str = "simple",
        reg_param: float = 0.0,
        seed: int = 42,
        snapshot_every: int = 100,
        convergence_tol: float = 0.0,
        trace_sample: Optional[float] = None,
    ):
        if updater not in ("simple", "l2", "l1"):
            raise ValueError(f"unknown updater {updater!r}")
        if loss not in ("least_squares", "logistic"):
            raise ValueError(f"unknown loss {loss!r}")
        self.gamma = gamma
        self.batch_rate = batch_rate
        self.num_iterations = num_iterations
        self.loss = loss
        self.updater = updater
        self.reg_param = reg_param
        self.seed = seed
        self.snapshot_every = snapshot_every
        self.convergence_tol = convergence_tol
        # in-process engine policy (see SolverConfig.trace_sample): tracing
        # is explicit opt-in; the conf default governs the DCN plane only
        self.trace_sample = trace_sample

    def _build(
        self,
        mesh: Mesh,
        n_global: int,
        axis: str = "dp",
        md_axis: Optional[str] = None,
    ):
        gamma, b = self.gamma, self.batch_rate
        loss_kind, upd, reg = self.loss, self.updater, self.reg_param
        T = self.num_iterations

        def body(carry, it, X, y, valid):
            w, key = carry
            key, sub = jax.random.split(key)
            # fold by dp index ONLY: with an md axis, every feature shard
            # of the same row block must draw the identical sample mask
            sub = jax.random.fold_in(sub, jax.lax.axis_index(axis))
            mask = jax.random.bernoulli(sub, b, (X.shape[0],)).astype(X.dtype)
            mask = mask * valid  # exclude padding rows from sample & count
            margin = X @ w
            if md_axis is not None:
                # partial products over the feature shards -> full margin
                margin = jax.lax.psum(margin, md_axis)
            if loss_kind == "least_squares":
                r = margin - y
                # MLlib LeastSquaresGradient: loss_i = diff^2 / 2
                local_loss = 0.5 * jnp.sum(mask * r * r)
                local_g = X.T @ (mask * r)
            else:
                p = jax.nn.sigmoid(margin)
                local_loss = jnp.sum(
                    mask * (jnp.logaddexp(0.0, margin) - y * margin)
                )
                local_g = X.T @ (mask * (p - y))
            # gradient slices combine over rows only; loss/count are
            # identical across md shards (same r, same mask), so they
            # psum over dp alone in both layouts
            g, loss_sum, count = jax.lax.psum(
                (local_g, local_loss, jnp.sum(mask)), axis
            )
            count = jnp.maximum(count, 1.0)
            lr = gamma / jnp.sqrt(it + 1.0)
            step = lr * g / count
            if upd == "simple":
                w2 = w - step
                reg_val = 0.0
            elif upd == "l2":
                # SquaredL2Updater: w2 = w(1 - lr*reg) - step; reg = reg/2 |w|^2
                w2 = w * (1.0 - lr * reg) - step
                sq = jnp.sum(w2 * w2)
                if md_axis is not None:
                    sq = jax.lax.psum(sq, md_axis)  # |w|^2 spans the shards
                reg_val = 0.5 * reg * sq
            else:
                # L1Updater: soft threshold at lr*reg; reg = reg * |w|_1
                shrink = lr * reg
                raw = w - step
                w2 = jnp.sign(raw) * jnp.maximum(jnp.abs(raw) - shrink, 0.0)
                l1 = jnp.sum(jnp.abs(w2))
                if md_axis is not None:
                    l1 = jax.lax.psum(l1, md_axis)
                reg_val = reg * l1
            stoch_loss = loss_sum / count + reg_val
            return (w2, key), (stoch_loss, w2)

        in_specs = (
            P(axis, md_axis), P(axis), P(axis), P(md_axis), P(None),
        )
        out_specs = (P(md_axis), P(None), P(None, md_axis))

        @partial(
            resolve_shard_map(),
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
        )
        def train(X, y, valid, w0, key0):
            def scan_body(carry, it):
                return body(carry, it, X, y, valid)

            (wT, _), (losses, ws) = jax.lax.scan(
                scan_body, (w0, key0), jnp.arange(T, dtype=jnp.float32)
            )
            return wT, losses, ws

        return jax.jit(train)

    def run(
        self,
        X: np.ndarray,
        y: np.ndarray,
        mesh: Optional[Mesh] = None,
        w0: Optional[np.ndarray] = None,
    ):
        """Returns (w_final, loss_history, snapshots) where snapshots is the
        Warray analog: [(iteration, w)] every ``snapshot_every`` steps.

        With a 2-D mesh (axes ``("dp", "md")``, md size > 1) the feature
        dimension shards over ``md`` -- see the module docstring.
        """
        mesh = mesh or make_mesh()
        n, d = X.shape
        md_axis = (
            "md"
            if ("md" in mesh.axis_names and mesh.shape["md"] > 1)
            else None
        )
        train = self._build(mesh, n_global=n, md_axis=md_axis)
        w0 = np.zeros(d, np.float32) if w0 is None else np.asarray(w0)
        if md_axis is None:
            Xs, ys, vs, _n = pad_and_shard(mesh, X, y)
            w_dev = jnp.asarray(w0)
        else:
            from asyncframework_tpu.parallel.mesh import pad_and_shard_2d

            Xs, ys, vs, w_dev, _d = pad_and_shard_2d(mesh, X, y, w0)
        key0 = jax.random.PRNGKey(self.seed)
        t_run0 = _trace.now_ms()
        wT, losses, ws = train(Xs, ys, vs, w_dev, key0)
        # distributed-trace boundary: the whole fused lax.scan IS one
        # compute span by construction (no host between updates, so the
        # per-update decomposition the async solvers record cannot exist
        # here); fold it into the process-global aggregator so a bench run
        # mixing drivers still shows where the wall-clock went.  The
        # readbacks below fence the dispatch, so stamp the span after them.
        # Explicit opt-in like every in-process solver (a seconds-long
        # whole-run span in the shared aggregator's compute stage must be
        # asked for, not ambient).
        _traced = (self.trace_sample is not None
                   and float(self.trace_sample) > 0)
        if md_axis is not None:
            wT = wT[:d]
            ws = ws[:, :d]
        losses = np.asarray(losses)
        ws = np.asarray(ws)
        if _traced:
            agg = _trace.aggregator()
            ctx = _trace.TraceContext(_trace._new_id(16), 0,
                                      self.num_iterations)
            agg.add(_trace.Span(
                stage=_trace.COMPUTE, trace_id=ctx.trace_id,
                span_id=ctx.span_id, parent_id=None, worker_id=0,
                model_version=self.num_iterations, start_ms=t_run0,
                dur_ms=max(0.0, _trace.now_ms() - t_run0),
            ))
        snaps = [
            (i, ws[i]) for i in range(0, self.num_iterations, self.snapshot_every)
        ]
        if self.convergence_tol > 0:
            # post-hoc convergence-tolerance cut (MLlib stops the loop; one
            # compiled scan can't, so we trim the tail after the fact)
            for i in range(1, len(losses)):
                prev, cur = losses[i - 1], losses[i]
                denom = max(abs(prev), abs(cur), 1e-12)
                if abs(prev - cur) / denom < self.convergence_tol:
                    return ws[i], losses[: i + 1], [s for s in snaps if s[0] <= i]
        return np.asarray(wT), losses, snaps
