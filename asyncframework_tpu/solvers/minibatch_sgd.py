"""Synchronous mini-batch SGD as a single compiled SPMD program.

Parity: MLlib's ``GradientDescent.runMiniBatchSGD``
(``mllib/.../optimization/GradientDescent.scala:197-295``): per iteration,
broadcast w, Bernoulli-sample fraction ``b``, tree-aggregate
(gradient_sum, loss_sum, count), update via an ``Updater`` (simple / L2 / L1 --
``Updater.scala:41,70,140``), record a stochastic loss history, and (the
fork's delta) a weight trajectory every ``snapshot_every`` iterations
(``Warray``, ``GradientDescent.scala:156,255-259``).

TPU re-design: the reference runs one cluster job per iteration (broadcast +
barrier per step).  Here the *entire* training loop is one jitted
``shard_map``'d ``lax.scan`` over the device mesh: data stays sharded in HBM
across the batch axis, each scan step draws a per-device mask (stateless
fold_in keys -- ``sample(false, b, seed+i)`` parity), computes the local
gradient sum, ``psum``s it over ICI, and applies the update on every device
identically.  Zero host round-trips for the whole run; the per-step stochastic
loss and the weight trajectory come back as stacked scan outputs.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from asyncframework_tpu.parallel.mesh import make_mesh, pad_and_shard


class MiniBatchSGD:
    """Updaters: 'simple' (no reg), 'l2', 'l1' (soft-threshold), matching the
    reference's three Updater classes."""

    def __init__(
        self,
        gamma: float = 1.0,
        batch_rate: float = 1.0,
        num_iterations: int = 100,
        loss: str = "least_squares",
        updater: str = "simple",
        reg_param: float = 0.0,
        seed: int = 42,
        snapshot_every: int = 100,
        convergence_tol: float = 0.0,
    ):
        if updater not in ("simple", "l2", "l1"):
            raise ValueError(f"unknown updater {updater!r}")
        if loss not in ("least_squares", "logistic"):
            raise ValueError(f"unknown loss {loss!r}")
        self.gamma = gamma
        self.batch_rate = batch_rate
        self.num_iterations = num_iterations
        self.loss = loss
        self.updater = updater
        self.reg_param = reg_param
        self.seed = seed
        self.snapshot_every = snapshot_every
        self.convergence_tol = convergence_tol

    def _build(self, mesh: Mesh, n_global: int, axis: str = "dp"):
        gamma, b = self.gamma, self.batch_rate
        loss_kind, upd, reg = self.loss, self.updater, self.reg_param
        T = self.num_iterations

        def body(carry, it, X, y, valid):
            w, key = carry
            key, sub = jax.random.split(key)
            sub = jax.random.fold_in(sub, jax.lax.axis_index(axis))
            mask = jax.random.bernoulli(sub, b, (X.shape[0],)).astype(X.dtype)
            mask = mask * valid  # exclude padding rows from sample & count
            if loss_kind == "least_squares":
                r = X @ w - y
                # MLlib LeastSquaresGradient: loss_i = diff^2 / 2
                local_loss = 0.5 * jnp.sum(mask * r * r)
                local_g = X.T @ (mask * r)
            else:
                m = X @ w
                p = jax.nn.sigmoid(m)
                local_loss = jnp.sum(mask * (jnp.logaddexp(0.0, m) - y * m))
                local_g = X.T @ (mask * (p - y))
            g, loss_sum, count = jax.lax.psum(
                (local_g, local_loss, jnp.sum(mask)), axis
            )
            count = jnp.maximum(count, 1.0)
            lr = gamma / jnp.sqrt(it + 1.0)
            step = lr * g / count
            if upd == "simple":
                w2 = w - step
                reg_val = 0.0
            elif upd == "l2":
                # SquaredL2Updater: w2 = w(1 - lr*reg) - step; reg = reg/2 |w|^2
                w2 = w * (1.0 - lr * reg) - step
                reg_val = 0.5 * reg * jnp.sum(w2 * w2)
            else:
                # L1Updater: soft threshold at lr*reg; reg = reg * |w|_1
                shrink = lr * reg
                raw = w - step
                w2 = jnp.sign(raw) * jnp.maximum(jnp.abs(raw) - shrink, 0.0)
                reg_val = reg * jnp.sum(jnp.abs(w2))
            stoch_loss = loss_sum / count + reg_val
            return (w2, key), (stoch_loss, w2)

        @partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis), P(axis), P(None), P(None)),
            out_specs=(P(None), P(None), P(None)),
        )
        def train(X, y, valid, w0, key0):
            def scan_body(carry, it):
                return body(carry, it, X, y, valid)

            (wT, _), (losses, ws) = jax.lax.scan(
                scan_body, (w0, key0), jnp.arange(T, dtype=jnp.float32)
            )
            return wT, losses, ws

        return jax.jit(train)

    def run(
        self,
        X: np.ndarray,
        y: np.ndarray,
        mesh: Optional[Mesh] = None,
        w0: Optional[np.ndarray] = None,
    ):
        """Returns (w_final, loss_history, snapshots) where snapshots is the
        Warray analog: [(iteration, w)] every ``snapshot_every`` steps."""
        mesh = mesh or make_mesh()
        n = X.shape[0]
        train = self._build(mesh, n_global=n)
        Xs, ys, vs, _n = pad_and_shard(mesh, X, y)
        w0 = np.zeros(X.shape[1], np.float32) if w0 is None else w0
        key0 = jax.random.PRNGKey(self.seed)
        wT, losses, ws = train(Xs, ys, vs, jnp.asarray(w0), key0)
        losses = np.asarray(losses)
        ws = np.asarray(ws)
        snaps = [
            (i, ws[i]) for i in range(0, self.num_iterations, self.snapshot_every)
        ]
        if self.convergence_tol > 0:
            # post-hoc convergence-tolerance cut (MLlib stops the loop; one
            # compiled scan can't, so we trim the tail after the fact)
            for i in range(1, len(losses)):
                prev, cur = losses[i - 1], losses[i]
                denom = max(abs(prev), abs(cur), 1e-12)
                if abs(prev - cur) / denom < self.convergence_tol:
                    return ws[i], losses[: i + 1], [s for s in snaps if s[0] <= i]
        return np.asarray(wT), losses, snaps
