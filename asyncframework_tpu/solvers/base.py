"""Common solver configuration and result types.

``SolverConfig`` carries the reference drivers' 13 positional knobs
(``SparkASGDThread.scala:28-48``: path/file/d/N are data-loading concerns
handled by the data layer; the remaining 9 algorithmic knobs appear here
under their long names) plus TPU-build extensions (loss kind, device update
mode, calibration override).
"""

from __future__ import annotations

import math
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from asyncframework_tpu.checkpoint import CheckpointManager
from asyncframework_tpu.data.sharded import ShardedDataset


class DeadWorkerError(RuntimeError):
    """A synchronous drain can never complete: a cohort worker's executor
    is dead and nothing will replace it.  Carries the per-worker liveness
    diagnostic (who is dead, last-heartbeat ages, who already reported)."""


def dead_worker_diagnostic(pool, dead: Dict[int, float],
                           collected: Optional[set] = None) -> str:
    """Per-worker liveness table for the fail-fast abort message."""
    collected = collected or set()
    lines = [
        "synchronous drain cannot complete: "
        f"executor(s) {sorted(dead)} dead with no replacement"
    ]
    for wid, ex in sorted(pool.executors.items()):
        age = ex._clock.now_ms() - ex.last_heartbeat_ms
        lines.append(
            f"  wid {wid:3d}: {'DEAD' if not ex.alive else 'alive':5s} "
            f"last-heartbeat {age:8.0f}ms ago  busy={ex.busy!s:5s} "
            f"reported={'yes' if wid in collected else 'no'}"
        )
    return "\n".join(lines)


def collect_checked(ctx, waiter, timeout_s: float, pool=None,
                    cohort=None, dead_grace_s: float = 1.0,
                    collected: Optional[set] = None):
    """Blocking collect that surfaces a job abort instead of hanging --
    and, when given the executor ``pool``, fails FAST with a per-worker
    liveness diagnostic when a cohort executor dies and stays dead past
    ``dead_grace_s`` (nobody will ever deliver its result), instead of
    sitting out the full ``timeout_s``.  With the heartbeat monitor
    running, a killed executor is replaced within the grace window and
    its entry here self-clears; with monitoring off, this is the only
    thing standing between a SIGKILLed worker and a silent full-timeout
    hang of the synchronous barrier."""
    deadline = time.monotonic() + timeout_s
    dead_since: Dict[int, float] = {}
    while True:
        if waiter.failed is not None:
            raise RuntimeError("job aborted during drain") from waiter.failed
        try:
            return ctx.collect_all(timeout=0.1)
        except queue.Empty:
            now = time.monotonic()
            if pool is not None and not pool.closed:
                watch = cohort if cohort is not None else list(pool.executors)
                for wid in watch:
                    ex = pool.executors.get(wid)
                    if (ex is not None and not ex.alive
                            and not ex.shutdown_requested):
                        first = dead_since.setdefault(wid, now)
                        if now - first > dead_grace_s:
                            raise DeadWorkerError(dead_worker_diagnostic(
                                pool, dead_since, collected
                            ))
                    else:
                        # replaced (heartbeat path) or healthy again
                        dead_since.pop(wid, None)
            if now > deadline:
                raise TimeoutError("sync drain timed out")


def check_hbm_plan(X, cfg: "SolverConfig", devices, history_table: bool) -> None:
    """Consult the HBM planner before committing to a run (VERDICT item 10):
    host arrays are planned from shape BEFORE placement; a pre-built dataset
    has its actual residency measured.  Raises ``MemoryError`` with the
    planner's accounting when the budget is oversubscribed."""
    from asyncframework_tpu.utils.hbm import plan_for_run

    num_devices = max(len(set(devices)), 1)
    versions = (
        cfg.max_live_versions if cfg.stale_read_offset is not None else 2
    )
    target = (X.shape[0], X.shape[1]) if isinstance(X, np.ndarray) else X
    plan_for_run(
        target,
        cfg.num_workers,
        num_devices,
        history_table=history_table,
        model_versions=versions,
        budget_bytes=cfg.hbm_budget_bytes,
    ).require_fits()


def resolve_dataset(X, y, num_workers: int, devices):
    """Accept host arrays (sharded here) or a pre-built dataset
    (:class:`ShardedDataset` or
    :class:`~asyncframework_tpu.data.sparse.SparseShardedDataset`);
    validate consistency with the solver's setup."""
    from asyncframework_tpu.data.sparse import SparseShardedDataset

    if isinstance(X, (ShardedDataset, SparseShardedDataset)):
        if y is not None:
            raise ValueError(
                "y must be None when passing a pre-built dataset "
                "(its labels are already resident on device)"
            )
        if X.num_workers != num_workers:
            raise ValueError(
                f"dataset is sharded for {X.num_workers} workers but the "
                f"solver is configured for {num_workers}"
            )
        for wid in range(num_workers):
            expect = devices[wid % len(devices)]
            actual = X.shard(wid).device
            if actual != expect:
                raise ValueError(
                    f"shard {wid} lives on {actual} but the solver will "
                    f"dispatch worker {wid} to {expect}; rebuild the dataset "
                    f"with the solver's device list"
                )
        return X
    return ShardedDataset(X, y, num_workers, devices)


def run_fused_plan(make_runner, carry, total_rounds: int, nw: int,
                   printer_freq: int, w_of, chunk_cap: int = 16):
    """Shared chunk/warm-up/snapshot/timing machinery of the fused
    device-resident solvers (ASGD.run_fused / ASAGA.run_fused) -- ONE
    definition so their benchmark numbers stay comparable.

    ``make_runner(length)`` builds a jitted callable ``carry -> (carry,
    W_snap)`` running ``length`` rounds; ``w_of(carry)`` extracts the model
    handle.  The full-chunk and remainder executables are BOTH warmed and
    **fenced** (``jax.block_until_ready``) before the clock starts --
    unfenced warm-up dispatches would still be executing at ``start_wall``
    and serialize the first timed chunk behind them, understating the
    fused rate.  Returns ``(carry, snapshots, start_wall, done_rounds)``;
    the caller fences the final model (``np.asarray``) before taking
    elapsed, as everywhere else.
    """
    import jax as _jax

    chunk = min(chunk_cap, total_rounds)
    full, rem = divmod(total_rounds, chunk)
    runner = make_runner(chunk)
    tail = make_runner(rem) if rem else None
    _jax.block_until_ready(runner(carry))
    if tail is not None:
        _jax.block_until_ready(tail(carry))
    start_wall = time.monotonic()
    snapshots: List[Tuple[float, object]] = [(0.0, w_of(carry))]
    snap_every = max(1, printer_freq // nw)
    done = 0
    plan = [(runner, chunk)] * full + ([(tail, rem)] if rem else [])
    for r, length in plan:
        carry, W_snap = r(carry)
        # chunk timestamps are dispatch-side; the caller's final fence
        # keeps elapsed honest
        t_ms = (time.monotonic() - start_wall) * 1e3
        for j in range(0, length, snap_every):
            snapshots.append((t_ms, W_snap[j]))
        done += length
    return carry, snapshots, start_wall, done


class FlopsAccountingMixin:
    """Shared counted-flops accounting for the async solvers.

    Hosts expect ``self._recovery`` (shard view), ``self._sparse`` and
    ``self.ds`` -- both ASGD and ASAGA provide them.  One implementation so
    a flop-model change can never make the two solvers disagree.
    """

    def _task_flops(self, wid: int) -> float:
        """Counted flops of one worker gradient (utils/flops.py model);
        cached per worker -- re-homed shards keep their shapes.  A solver
        whose sparse step compacts masked rows (ASGD) sets
        ``_sparse_compact`` so only the compacted rows count."""
        cache = self.__dict__.setdefault("_flops_cache", {})
        cached = cache.get(wid)
        if cached is None:
            from asyncframework_tpu.utils import flops as _fl

            shard = self._recovery.shard(wid)
            rows = shard.size
            if getattr(self, "_sparse_compact" if self._sparse
                       else "_dense_compact", False):
                from asyncframework_tpu.ops.steps import sparse_step_capacity

                rows = sparse_step_capacity(self.cfg.batch_rate, shard.size)
            cached = (
                _fl.sparse_task_flops(rows, shard.cols.shape[1])
                if self._sparse
                else _fl.dense_task_flops(rows, self.ds.d)
            )
            cache[wid] = cached
        return cached


class SolverCheckpointer:
    """Shared checkpoint plumbing for the async solvers.

    Owns the manager, the compatibility metadata, the save-cadence decision,
    and the restore-with-validation step, so ASGD and ASAGA differ only in
    *which* state fields they save (ASAGA adds the history table).
    """

    def __init__(self, cfg: "SolverConfig", solver: str, d: int, n: int):
        self.cfg = cfg
        self.meta = {
            "solver": solver, "num_workers": cfg.num_workers, "d": d, "n": n
        }
        self.mgr = (
            CheckpointManager(cfg.checkpoint_dir, cfg.checkpoint_keep)
            if cfg.checkpoint_dir
            else None
        )

    @property
    def enabled(self) -> bool:
        return self.mgr is not None

    def restore(self) -> Optional[Dict]:
        """Latest checkpoint, validated against this run; None = cold start."""
        if self.mgr is None:
            return None
        ck = self.mgr.restore_latest_or_none()
        if ck is not None:
            validate_resume(ck.get("meta", {}), **self.meta)
        return ck

    def should_save(self, k: int) -> bool:
        return (
            self.mgr is not None
            and self.cfg.checkpoint_freq > 0
            and k % self.cfg.checkpoint_freq == 0
        )

    def should_save_range(self, k_old: int, k_new: int) -> bool:
        """True when any k in (k_old, k_new] hits the cadence -- a batched
        drain may jump OVER a checkpoint boundary and must still save."""
        freq = self.cfg.checkpoint_freq
        return (
            self.mgr is not None
            and freq > 0
            and k_new // freq > k_old // freq
        )

    def save(self, k: int, **state) -> None:
        self.mgr.save(k, {**state, "k": k, "meta": self.meta})


def validate_resume(meta: Dict, **expect) -> None:
    """Fail fast when a checkpoint does not match the resuming run.

    A checkpoint written under a different worker count / dataset shape /
    solver would otherwise crash deep in the training loop (missing worker
    ids, wrong history-slice sizes) or silently resume the wrong model.
    """
    for key, want in expect.items():
        got = meta.get(key)
        if got != want:
            raise ValueError(
                f"checkpoint incompatible with this run: {key}={got!r} "
                f"in checkpoint but {want!r} configured"
            )


@dataclass
class SolverConfig:
    num_workers: int = 8          # [num partitions]
    num_iterations: int = 1000    # [num iterations] (accepted updates / rounds)
    gamma: float = 0.1            # [step size]
    taw: int = 2**31 - 1          # [taw] staleness bound
    batch_rate: float = 0.1       # [batch rate] Bernoulli b
    bucket_ratio: float = 0.5     # [bucket ratio] cohort threshold
    printer_freq: int = 100       # [printer freq] trajectory snapshot period
    coeff: float = 0.0            # [coeff] delay intensity; -1 = cloud mode
    seed: int = 42                # [seed]
    loss: str = "least_squares"
    # TPU-build extensions
    calibration_iters: Optional[int] = None  # default 100 * num_workers
    collect_timeout_s: float = 0.05
    run_timeout_s: float = 600.0
    # updater drain batching (SparkASGDThread.scala:154-158 drains the whole
    # queue per wake; with drain_batch > 1 a drained batch also folds into
    # ONE device dispatch -- exact for ASGD's w-independent step sizes).
    # Default 1: on fast-dispatch backends the stack copy outweighs the
    # saved dispatches (measured: 5.7k updates/s at 1 vs 3.4k at 8 on the
    # tunneled v5e); large values win modestly when per-dispatch latency
    # dominates (6.2k updates/s at 128, +10%, same chip).
    drain_batch: int = 1
    # DCN data plane (parallel/ps_dcn.py).  pull_mode: None = resolve from
    # conf async.pull.mode ('full' ships the whole model per PULL,
    # byte-identical legacy wire; 'delta' negotiates NOT_MODIFIED /
    # byte-exact XOR delta / full per pull).  push_merge: None = resolve
    # from conf async.push.merge (max pushes the PS coalesces into one
    # fused device apply at lock acquisition; 1 = classic serial path).
    pull_mode: Optional[str] = None
    push_merge: Optional[int] = None
    # push_codec: None = resolve from conf async.codec.push ('off' ships
    # raw f32 gradients, byte-identical legacy wire; 'fp16'/'int8'
    # quantize dense ASGD pushes with per-worker error-feedback residual
    # accumulation -- net/wirecodec.py; ASAGA and sparse-encoded pushes
    # always ship exact).
    push_codec: Optional[str] = None
    # pipeline_depth: None = resolve from conf async.pipeline.depth
    # (0 = the classic serial worker loop, byte- and step-identical;
    # >= 1 = prefetched pulls on a second connection + a bounded
    # in-flight push sender with at most this many unacked pushes).
    pipeline_depth: Optional[int] = None
    # mesh_devices: None = resolve from conf async.mesh.devices (0 = the
    # classic single-device worker gradient step, byte- and step-
    # identical; >= 2 = each DCN worker computes its mini-batch gradient
    # batch-parallel over a local dp mesh of this many chips -- shard
    # rows resident in HBM across the run, per-device partials psum-
    # reduced locally, ONE fused gradient per PUSH, wire unchanged).
    # Clamped to the rig's device count; degrades to the serial path
    # (logged) when fewer than 2 devices result or the shard is sparse.
    mesh_devices: Optional[int] = None
    # checkpoint/resume (SURVEY.md section 5: a capability the reference lacks)
    checkpoint_dir: Optional[str] = None  # None = checkpointing off
    checkpoint_freq: int = 0              # accepted updates between saves; 0 = off
    checkpoint_keep: int = 3
    # observability (EventLoggingListener / MetricsSystem parity; None = off)
    # live dashboard (SparkUI.scala:39 parity): HTTP port serving run state
    # DURING the run; 0 = ephemeral (metrics/live.py); None = off
    ui_port: Optional[int] = None
    event_log: Optional[str] = None       # JSONL (.gz ok) event log path
    metrics_csv: Optional[str] = None     # CsvSink path
    metrics_jsonl: Optional[str] = None   # JsonlSink path
    metrics_period_s: float = 1.0
    # distributed tracing (metrics/trace.py): per-update sampling rate for
    # lifecycle spans (compute / merge.queue / merge.apply here; the DCN
    # path adds the wire stages).  None (default) = OFF for the in-process
    # engine -- its updater thread is the measured hot path, so tracing it
    # is explicit opt-in (--trace-sample / --conf async.trace.sample); the
    # async.trace.sample conf default (1/64) governs the DCN plane, whose
    # stages are network-dominated.
    trace_sample: Optional[float] = None
    # convergence telemetry (metrics/timeseries.py): every Nth update per
    # logical DCN worker evaluates its shard's mean loss + grad norm and
    # piggybacks the sample on the next PUSH header (``cv``) for the PS's
    # loss-vs-wallclock / loss-vs-version curves.  None = resolve from
    # conf async.convergence.sample (default 0 = off: one extra jitted
    # eval per sample, and byte-identity suites compare exact wires);
    # async-cluster flips it to 16.  In-process solvers fold their
    # post-hoc trajectory instead -- this knob is DCN-worker-side only.
    conv_sample: Optional[int] = None
    # failure detection / elastic recovery (HeartbeatReceiver parity)
    heartbeat: bool = True                # liveness monitoring during the run
    heartbeat_timeout_ms: float = 2000.0
    heartbeat_interval_s: float = 0.25
    max_slot_failures: int = 2            # repeated deaths => re-home the shard
    # speculative execution (TaskSetManager.checkSpeculatableTasks parity)
    speculation: bool = False
    speculation_quantile: float = 0.75
    speculation_multiplier: float = 1.5
    speculation_min_ms: float = 100.0
    # dynamic executor allocation (ExecutorAllocationManager.scala:82
    # parity): sibling host threads added to backlogged slots, retired idle
    dynamic_allocation: bool = False
    allocation_max_extra: int = 1
    allocation_backlog_threshold: int = 2
    allocation_idle_timeout_s: float = 1.0
    # stale-read experiment (ASYNCbroadcast.value(index) parity): workers
    # read model version (latest - offset) from a VersionedModelStore
    stale_read_offset: Optional[int] = None
    max_live_versions: int = 4
    # HBM budget consulted before placement; None = query the device
    hbm_budget_bytes: Optional[int] = None

    def effective_calibration_iters(self) -> int:
        if self.calibration_iters is not None:
            return self.calibration_iters
        return 100 * self.num_workers

    @property
    def bucket_threshold(self) -> int:
        return math.floor(self.num_workers * self.bucket_ratio)


@dataclass
class TrainResult:
    """What a driver run produces (the reference prints these; we return them).

    ``trajectory`` is the optVars analog evaluated post-hoc in one pass:
    ``(wall_ms_since_start, objective)`` where objective is the mean loss over
    the full dataset.
    """

    final_w: np.ndarray
    trajectory: List[Tuple[float, float]]
    elapsed_s: float
    accepted: int = 0
    dropped: int = 0
    rounds: int = 0
    max_staleness: int = 0
    avg_delay_ms: float = 0.0
    updates_per_sec: float = 0.0
    # counted worker-gradient flops (utils/flops.py model; excludes the
    # post-hoc trajectory evaluation) -- the MFU numerator
    total_flops: float = 0.0
    waiting_time_ms: Dict[int, float] = field(default_factory=dict)
    extras: Dict[str, object] = field(default_factory=dict)

    @property
    def final_objective(self) -> float:
        return self.trajectory[-1][1] if self.trajectory else float("nan")


class WaitingTimeTable:
    """Per-worker idle-gap bookkeeping.

    Parity: ``WaitingTime`` / ``SubmitJobTime`` / ``FinishTimeTable``
    (``SparkASGDThread.scala:112-115,328-335``): at submit, a worker's waiting
    time grows by (submit wall time - its last finish wall time).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.submit_ms: Dict[int, float] = {}
        self.finish_ms: Dict[int, float] = {}
        self.waiting_ms: Dict[int, float] = {}

    def on_submit(self, worker_ids, now_ms: float) -> None:
        with self._lock:
            for wid in worker_ids:
                gap = now_ms - self.finish_ms.get(wid, now_ms)
                self.waiting_ms[wid] = self.waiting_ms.get(wid, 0.0) + gap
                self.submit_ms[wid] = now_ms

    def on_finish(self, worker_id: int, now_ms: float) -> float:
        """Record finish; returns (finish - submit) for delay calibration."""
        with self._lock:
            dt = now_ms - self.submit_ms.get(worker_id, now_ms)
            self.finish_ms[worker_id] = now_ms
            return dt

    def snapshot(self) -> Dict[int, float]:
        with self._lock:
            return dict(self.waiting_ms)


class DelayCalibrator:
    """Average-delay measurement over the warm-up phase.

    Parity: ``culTime``/``culCount`` accumulation while ``k < 100*numPart``
    and the one-shot ``avgDelay = culTime/culCount``
    (``SparkASGDThread.scala:174-183,244-249``).
    """

    def __init__(self, calibration_iters: int):
        self._iters = calibration_iters
        self._cul_time = 0.0
        self._cul_count = 0
        self._lock = threading.Lock()
        self.avg_delay_ms = 0.0
        self.calibrated = False

    def record(self, k: int, task_ms: float) -> None:
        with self._lock:
            if k < self._iters:
                self._cul_time += task_ms
                self._cul_count += 1

    def maybe_finalize(self, k: int) -> bool:
        """Returns True the single time calibration completes."""
        with self._lock:
            if not self.calibrated and k > self._iters and self._cul_count > 0:
                self.avg_delay_ms = self._cul_time / self._cul_count
                self.calibrated = True
                return True
            return False


def make_allocation_manager(cfg: "SolverConfig", scheduler):
    """Start a dynamic-allocation manager when the config asks for one
    (``ExecutorAllocationManager`` parity); returns None otherwise.  Shared
    by every solver run path."""
    if not cfg.dynamic_allocation:
        return None
    from asyncframework_tpu.engine.allocation import ExecutorAllocationManager

    mgr = ExecutorAllocationManager(
        scheduler,
        max_extra_per_slot=cfg.allocation_max_extra,
        backlog_threshold=cfg.allocation_backlog_threshold,
        idle_timeout_s=cfg.allocation_idle_timeout_s,
    )
    mgr.start()
    return mgr
