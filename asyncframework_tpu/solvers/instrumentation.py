"""Run instrumentation: the sidecar subsystems wired INTO solver runs.

The reference's observability and resilience live *inside* jobs, not beside
them: every task launch flows through ``LiveListenerBus`` listeners
(``scheduler/LiveListenerBus.scala:44``), ``EventLoggingListener`` streams the
run to disk (``scheduler/EventLoggingListener.scala:55``), ``MetricsSystem``
polls sources on an interval (``metrics/MetricsSystem.scala:70``), and
``HeartbeatReceiver`` (``HeartbeatReceiver.scala:59``) watches executor
liveness for the scheduler.  :class:`RunInstruments` is the per-run bundle of
those capabilities for this framework's solvers: one object the solver
creates from its :class:`~asyncframework_tpu.solvers.base.SolverConfig`,
posts events to from its hot threads, and closes at the end of the run.

Everything here is optional and off the hot path: posting to the bus is a
non-blocking enqueue; when no event log / metrics sink / heartbeat is
configured the instruments are inert no-ops.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from asyncframework_tpu.metrics.bus import (
    Event,
    GradientMerged,
    ListenerBus,
    ModelSnapshot,
    RoundSubmitted,
    ShardMoved,
    SpeculativeLaunch,
    WorkerLost,
)
from asyncframework_tpu.metrics import trace as trace_mod
from asyncframework_tpu.metrics.eventlog import EventLogWriter
from asyncframework_tpu.metrics.system import CsvSink, JsonlSink, MetricsSystem


class _GlobalTraceFold:
    """Bus listener folding TraceSpan events into the process-global
    aggregator (bench.py / tools read it) -- on the dispatch thread, so
    the solver's updater never pays for histogram updates."""

    def on_trace_span(self, ev) -> None:
        trace_mod.aggregator().add(trace_mod.Span(
            stage=ev.stage, trace_id=ev.trace_id, span_id=ev.span_id,
            parent_id=ev.parent_id, worker_id=ev.worker_id,
            model_version=ev.model_version, start_ms=ev.start_ms,
            dur_ms=ev.dur_ms, staleness=ev.staleness,
            staleness_ms=ev.staleness_ms, accepted=ev.accepted,
        ))

    def on_event(self, event) -> None:
        pass


class RunInstruments:
    """Per-run observability bundle: listener bus + event log + metrics.

    The solver calls the ``on_*`` hooks from its submitter/updater threads;
    they update metrics instruments synchronously (cheap: a lock and an
    append) and post typed events to the asynchronous bus (never blocks).
    """

    def __init__(self, cfg, num_workers: int):
        self.cfg = cfg
        self._t0 = time.monotonic()
        self.bus = ListenerBus()
        self.writer: Optional[EventLogWriter] = None
        self.metrics: Optional[MetricsSystem] = None
        self.workers_lost = 0
        self.shards_moved = 0
        self._lock = threading.Lock()

        event_log = getattr(cfg, "event_log", None)
        if event_log:
            self.writer = EventLogWriter(event_log)
            self.bus.add_listener(self.writer)
            self.bus.start()

        self.ui = None
        self.live_state = None
        ui_port = getattr(cfg, "ui_port", None)
        # None or negative = off (the conf registry's -1 sentinel); 0 = bind
        # an ephemeral port
        if ui_port is not None and ui_port >= 0:
            from asyncframework_tpu.metrics.live import (
                LiveStateListener,
                LiveUIServer,
            )

            self.live_state = LiveStateListener(num_workers)
            self.bus.add_listener(self.live_state)
            self.bus.start()
            self.ui = LiveUIServer(self.live_state, port=ui_port).start()

        # distributed tracing: the single-process solvers' slice of the
        # lifecycle vocabulary (compute / merge.queue / merge.apply --
        # there is no wire here, so the pull/push stages are the DCN
        # path's).  Sampled spans go to the bus as TraceSpan events (->
        # event log / live UI) and a bus listener folds them into the
        # process-global aggregator (bench.py --trace-jsonl reads it).
        # EXPLICIT opt-in only (cfg.trace_sample / --trace-sample /
        # --conf async.trace.sample): the conf default governs the DCN
        # plane, where stages are network-dominated -- here the updater
        # thread IS the measured hot path, and even microsecond-scale
        # per-merge work (or the bus dispatch thread's GIL share)
        # measurably shifts marginal-stability engine runs.  None or 0 =
        # no tracer, zero per-merge work.
        self.tracer: Optional[trace_mod.TraceRecorder] = None
        _rate = getattr(cfg, "trace_sample", None)
        if _rate is not None and float(_rate) > 0:
            _rec = trace_mod.TraceRecorder(
                sample_rate=float(_rate), sink=self._fold_span,
            )
            if _rec.enabled:
                self.tracer = _rec
                # start the bus so the updater pays only a queue put;
                # span fan-out runs on the dispatch thread
                self.bus.add_listener(_GlobalTraceFold())
                self.bus.start()

        metrics_csv = getattr(cfg, "metrics_csv", None)
        metrics_jsonl = getattr(cfg, "metrics_jsonl", None)
        if metrics_csv or metrics_jsonl:
            self.metrics = MetricsSystem()
            if metrics_csv:
                self.metrics.add_sink(CsvSink(metrics_csv))
            if metrics_jsonl:
                self.metrics.add_sink(JsonlSink(metrics_jsonl))
            self._c_accepted = self.metrics.counter("updates.accepted")
            self._c_dropped = self.metrics.counter("updates.dropped")
            self._c_rounds = self.metrics.counter("rounds.submitted")
            self._h_staleness = self.metrics.histogram("staleness")
            self._h_task_ms = self.metrics.histogram("task.ms")
            self._g_updates_per_sec = self.metrics.gauge("updates.per_sec")
            self.metrics.start(getattr(cfg, "metrics_period_s", 1.0))

    # ----------------------------------------------------------------- time
    def now_ms(self) -> float:
        return (time.monotonic() - self._t0) * 1e3

    # ------------------------------------------------------------ run hooks
    def register_queue_depth(self, fn: Callable[[], int]) -> None:
        """Expose the result queue's depth as a polled metrics source."""
        if self.metrics is not None:
            self.metrics.register_source("queue", lambda: {"depth": fn()})
        if self.live_state is not None:
            self.live_state.register_queue_depth(fn)

    def on_round_submitted(
        self, round_idx: int, cohort, model_version: int
    ) -> None:
        self.bus.post(
            RoundSubmitted(self.now_ms(), round_idx, tuple(cohort), model_version)
        )
        if self.metrics is not None:
            self._c_rounds.inc()

    def _fold_span(self, span: "trace_mod.Span") -> None:
        # hot-thread cost: one non-blocking queue put (the bus is started
        # whenever the tracer is on); aggregation happens on the dispatch
        # thread via _GlobalTraceFold / LiveStateListener
        self.bus.post(trace_mod.span_event(span, self.now_ms()))

    def on_gradient_merged(
        self,
        worker_id: int,
        staleness: int,
        accepted: bool,
        iteration: int,
        batch_size: int = 0,
        task_ms: float = 0.0,
        queue_ms: float = 0.0,
        apply_ms: float = 0.0,
    ) -> None:
        self.bus.post(
            GradientMerged(
                self.now_ms(), worker_id, staleness, accepted, iteration,
                batch_size,
            )
        )
        if self.tracer is not None:
            ut = self.tracer.start_update(worker_id)
            if ut is not None:
                # the stages ran back-to-back and just ended: reconstruct
                # their starts from the measured durations
                ut.ctx.model_version = int(iteration)
                t_now = trace_mod.now_ms()
                t_apply0 = t_now - apply_ms
                t_queue0 = t_apply0 - queue_ms
                t_comp0 = t_queue0 - task_ms
                if task_ms:
                    ut.add(trace_mod.COMPUTE, t_comp0, t_queue0)
                if queue_ms:
                    ut.add(trace_mod.MERGE_QUEUE, t_queue0, t_apply0)
                # staleness in TIME: how old the worker's model basis was
                # at merge = its task wall-clock + result-queue wait
                ut.add(
                    trace_mod.MERGE_APPLY, t_apply0, t_now,
                    staleness=int(staleness),
                    staleness_ms=float(task_ms + queue_ms),
                    accepted=bool(accepted),
                )
        if self.metrics is not None:
            (self._c_accepted if accepted else self._c_dropped).inc()
            self._h_staleness.update(float(staleness))
            if task_ms:
                self._h_task_ms.update(task_ms)
            el = time.monotonic() - self._t0
            if el > 0:
                self._g_updates_per_sec.set(self._c_accepted.value / el)

    def on_worker_lost(self, worker_id: int, reason: str) -> None:
        with self._lock:
            self.workers_lost += 1
        self.bus.post(WorkerLost(self.now_ms(), worker_id, reason))

    def on_shard_moved(self, shard_id: int, new_owner: int, device) -> None:
        with self._lock:
            self.shards_moved += 1
        self.bus.post(
            ShardMoved(self.now_ms(), shard_id, new_owner, str(device))
        )

    def on_speculative_launch(self, job_id: int, worker_id: int) -> None:
        self.bus.post(SpeculativeLaunch(self.now_ms(), job_id, worker_id))

    def post(self, event: Event) -> None:
        self.bus.post(event)

    # ----------------------------------------------------------------- close
    def close(
        self, trajectory: Optional[List[Tuple[float, float]]] = None,
        printer_freq: int = 1,
    ) -> None:
        """Flush trajectory snapshots (objectives are evaluated post-hoc, so
        ``ModelSnapshot`` events are emitted at close) and stop everything.

        Idempotent: the solvers' ``finally`` blocks close WITHOUT a
        trajectory when an exception is unwinding (the event log must get
        its gzip footer exactly when the run crashed); the success path then
        skips its second close.
        """
        with self._lock:
            if getattr(self, "_closed", False):
                return
            self._closed = True
        if trajectory:
            for i, (t_ms, obj) in enumerate(trajectory):
                self.bus.post(
                    ModelSnapshot(t_ms, iteration=i * printer_freq,
                                  objective=float(obj))
                )
        if self.metrics is not None:
            self.metrics.report()  # final sample so short runs get >= 1 row
            self.metrics.stop()
        self.bus.stop()
        if self.ui is not None:
            self.ui.stop()
        if self.writer is not None:
            self.writer.close()

    # ---------------------------------------------------------------- extras
    def extras(self) -> Dict[str, object]:
        """Summary facts for ``TrainResult.extras``."""
        out: Dict[str, object] = {}
        with self._lock:
            if self.workers_lost:
                out["workers_lost"] = self.workers_lost
            if self.shards_moved:
                out["shards_moved"] = self.shards_moved
        if self.bus.dropped_events:
            out["dropped_events"] = self.bus.dropped_events
        if self.ui is not None:
            out["ui_port"] = self.ui.port
        return out


def log_trajectory(path, trajectory, printer_freq: int = 1) -> None:
    """Write a bare trajectory as ModelSnapshot events (for runs that have no
    per-task event stream, e.g. the fused-scan baseline); numbering matches
    :meth:`RunInstruments.close` so report tooling sees one convention."""
    from asyncframework_tpu.metrics.bus import ModelSnapshot
    from asyncframework_tpu.metrics.eventlog import EventLogWriter

    wr = EventLogWriter(path)
    try:
        for i, (t_ms, obj) in enumerate(trajectory):
            wr.on_event(
                ModelSnapshot(t_ms, iteration=i * printer_freq,
                              objective=float(obj))
            )
    finally:
        wr.close()


class FaultTolerantRun:
    """Heartbeat + executor replacement + shard re-homing for one run.

    Wires :class:`~asyncframework_tpu.engine.heartbeat.HeartbeatMonitor` to
    the scheduler's ``on_executor_lost`` (in-flight task resubmission on a
    fresh executor -- the transient-failure path) and, when the same worker
    slot keeps dying (``max_slot_failures``), re-homes its data shard onto a
    surviving worker's device via
    :class:`~asyncframework_tpu.engine.recovery.ShardRecovery` (the
    permanent-loss path; lineage-recomputation analog, SURVEY.md section 5).
    """

    def __init__(
        self,
        scheduler,
        recovery,
        instruments: RunInstruments,
        num_workers: int,
        heartbeat_timeout_ms: float = 2000.0,
        check_interval_s: float = 0.25,
        max_slot_failures: int = 2,
        on_moved=None,
    ):
        from asyncframework_tpu.engine.heartbeat import HeartbeatMonitor

        self._sched = scheduler
        self._recovery = recovery
        self._inst = instruments
        self._nw = num_workers
        self._max_slot_failures = max_slot_failures
        self._on_moved = on_moved  # callback(shard_id, moved_shard)
        self._losses: Dict[int, int] = {}
        self._lock = threading.Lock()
        self.monitor = HeartbeatMonitor(
            scheduler.pool,
            self._on_lost,
            timeout_ms=heartbeat_timeout_ms,
            check_interval_s=check_interval_s,
            on_sibling_lost=scheduler.on_sibling_lost,
        )

    def _on_lost(self, worker_id: int) -> None:
        with self._lock:
            n = self._losses.get(worker_id, 0) + 1
            self._losses[worker_id] = n
        self._inst.on_worker_lost(worker_id, f"heartbeat timeout (loss #{n})")
        # replacement executor + in-flight resubmission (DAGScheduler parity)
        self._sched.on_executor_lost(worker_id)
        if n >= self._max_slot_failures and self._recovery is not None:
            # repeated deaths: treat the slot's device home as suspect and
            # re-home the shard to the least-loaded surviving slot
            from asyncframework_tpu.engine.recovery import plan_reassignment

            survivors = [w for w in range(self._nw) if w != worker_id]
            if survivors:
                plan = plan_reassignment(range(self._nw), [worker_id])
                new_owner = plan.moves[worker_id]
                moved = self._recovery.move_shard(worker_id, new_owner)
                self._inst.on_shard_moved(
                    worker_id, new_owner, moved.device
                )
                if self._on_moved is not None:
                    self._on_moved(worker_id, moved)

    def start(self) -> None:
        self.monitor.start()

    def stop(self) -> None:
        self.monitor.stop()
