"""ASGD: asynchronous (and synchronous) stochastic gradient descent.

The TPU-native re-design of the reference's flagship drivers:

- async mode ~ ``SparkASGDThread.scala`` -- two driver threads (submitter +
  updater) around an :class:`AsyncContext`; per-worker gradients stream in and
  are applied under a staleness bound ``taw``; cohorts are selected by a
  partial barrier over worker availability; stragglers can be injected after a
  calibration phase.
- sync mode ~ ``SparkASGDSync.scala`` -- the same non-blocking submission
  machinery, but each round drains exactly ``num_workers`` results and applies
  one accumulated update (the "barrier in the driver").

TPU-first hot path: every array the algorithm touches stays in device HBM.
Worker tasks are one fused jit (mask + gradient) on the worker's device; the
updater's accept path is one fused jit (scaled axpy + on-device iteration
counter); the model and snapshots are immutable device handles (old handle ==
old model version -- the versioned-broadcast capability with zero copies).
The host moves only handles and Python ints, so per-update cost is two
dispatches, not two transfers.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncframework_tpu.broadcast import VersionedModelStore
from asyncframework_tpu.context import AsyncContext
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.engine.barrier import bucket_predicate, partial_barrier
from asyncframework_tpu.engine.recovery import ShardRecovery
from asyncframework_tpu.engine.scheduler import ASYNC, JobScheduler
from asyncframework_tpu.engine.speculation import SpeculationMonitor
from asyncframework_tpu.engine.straggler import DelayModel
from asyncframework_tpu.ops import steps
from asyncframework_tpu.solvers.base import (
    DelayCalibrator,
    FlopsAccountingMixin,
    make_allocation_manager,
    SolverCheckpointer,
    SolverConfig,
    TrainResult,
    WaitingTimeTable,
    check_hbm_plan,
    collect_checked,
    resolve_dataset,
)
from asyncframework_tpu.solvers.instrumentation import (
    FaultTolerantRun,
    RunInstruments,
)


# minimum drained-batch size for the stacked one-dispatch apply: below
# this, the stack copy costs more than the dispatches it saves.  Shared by
# the runtime drain and the warm-up gate so the pre-compile always covers
# the path the updater actually takes.
BATCH_DRAIN_MIN = 3


class ASGD(FlopsAccountingMixin):
    def __init__(
        self,
        X,
        y: Optional[np.ndarray],
        config: SolverConfig,
        devices: Optional[list] = None,
    ):
        """``X`` may be a host array (sharded here) or a pre-built
        :class:`ShardedDataset` (e.g. generated on device), with ``y=None``."""
        self.cfg = config
        self.devices = list(devices) if devices is not None else jax.devices()
        check_hbm_plan(X, config, self.devices, history_table=False)
        self.ds = resolve_dataset(X, y, config.num_workers, self.devices)
        self.driver_device = self.devices[0]
        self._sparse = bool(getattr(self.ds, "is_sparse", False))
        if self._sparse:
            if config.loss != "least_squares":
                raise ValueError(
                    "sparse shards currently support least_squares only"
                )
            self._step = steps.make_sparse_asgd_worker_step(
                config.batch_rate, self.ds.d
            )
            self._sparse_compact = True  # flops = compacted rows, not n_p
            self._eval = steps.make_sparse_trajectory_loss_eval()
        else:
            self._step = steps.make_asgd_worker_step(
                config.batch_rate, config.loss
            )
            # flops accounting mirrors the step's row compaction gate
            self._dense_compact = config.batch_rate <= 0.5
            self._eval = steps.make_trajectory_loss_eval(config.loss)
        self._apply = steps.make_asgd_apply(
            config.gamma, config.batch_rate, self.ds.n, config.num_workers
        )
        self._sync_apply = steps.make_sync_apply(
            config.gamma, config.batch_rate, self.ds.n
        )
        # all shard access routes through the recovery view so a re-homed
        # shard is transparently picked up by later rounds and by evaluation
        self._recovery = ShardRecovery(self.ds, self.devices)

    # ------------------------------------------------------------------ async
    def run(self) -> TrainResult:
        """Asynchronous mode (SparkASGDThread parity)."""
        cfg = self.cfg
        nw = cfg.num_workers
        ctx: AsyncContext = AsyncContext()
        sched = JobScheduler(num_workers=nw, devices=self.devices)
        sched.set_mode(ASYNC)
        self.scheduler = sched  # exposed for fault-injection tests/tools
        delay_model = DelayModel(cfg.coeff, nw, cfg.seed)
        calibrator = DelayCalibrator(cfg.effective_calibration_iters())
        waiting = WaitingTimeTable()
        inst = RunInstruments(cfg, nw)
        inst.register_queue_depth(ctx.size)
        ft = None
        if cfg.heartbeat:
            ft = FaultTolerantRun(
                sched, self._recovery, inst, nw,
                heartbeat_timeout_ms=cfg.heartbeat_timeout_ms,
                check_interval_s=cfg.heartbeat_interval_s,
                max_slot_failures=cfg.max_slot_failures,
            )
            ft.start()
        spec = None
        if cfg.speculation:
            spec = SpeculationMonitor(
                sched, quantile=cfg.speculation_quantile,
                multiplier=cfg.speculation_multiplier,
                min_time_ms=cfg.speculation_min_ms,
                on_launch=inst.on_speculative_launch,
            )
            spec.start()
        alloc = make_allocation_manager(cfg, sched)
        # stale-read experiment: workers read version (latest - offset)
        store = (
            VersionedModelStore(cfg.max_live_versions)
            if cfg.stale_read_offset is not None
            else None
        )

        d = self.ds.d
        ckpt = SolverCheckpointer(cfg, "asgd", d, self.ds.n)
        ck = ckpt.restore()
        if ck is not None:
            # Resume: model, accepted-update counter, logical clock, and every
            # worker's PRNG chain come back exactly where they stopped.
            k0 = int(ck["k"])
            ctx.set_current_time(int(ck["clock"]))
            w = jax.device_put(jnp.asarray(ck["w"]), self.driver_device)
            k_dev = jax.device_put(jnp.float32(k0), self.driver_device)
            worker_keys: Dict[int, jax.Array] = {
                wid: jax.device_put(jnp.asarray(key), self._shard_device(wid))
                for wid, key in ck["worker_keys"].items()
            }
        else:
            k0 = 0
            w = jax.device_put(jnp.zeros(d, jnp.float32), self.driver_device)
            k_dev = jax.device_put(jnp.float32(0.0), self.driver_device)
            # per-worker device-resident PRNG chains
            worker_keys = {
                wid: jax.device_put(
                    jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid),
                    self._shard_device(wid),
                )
                for wid in range(nw)
            }
        key_lock = threading.Lock()

        state = {
            "w": w,
            "k_dev": k_dev,
            "k": k0,
            "accepted": 0,
            "dropped": 0,
            "rounds": 0,
            "flops": 0.0,
        }
        state_lock = threading.Lock()
        stop = threading.Event()
        apply_batch = steps.make_asgd_apply_batch(
            cfg.gamma, cfg.batch_rate, self.ds.n, nw, cfg.drain_batch
        )
        self._warm_hot_path(apply_batch, max(cfg.drain_batch, 1))
        start_wall = time.monotonic()
        snapshots: List[Tuple[float, jax.Array]] = [(0.0, w)]

        def now_ms() -> float:
            return (time.monotonic() - start_wall) * 1e3

        # ---------------------------------------------------- updater thread
        def save_checkpoint(save_k: int, save_w) -> None:
            with key_lock:
                keys_h = {wid: np.asarray(kv) for wid, kv in worker_keys.items()}
            ckpt.save(
                save_k,
                w=np.asarray(save_w),
                clock=ctx.get_current_time(),
                worker_keys=keys_h,
            )

        # per-accepted-count mask cache: rebuilt host constants would cost
        # an extra transfer per drain on the latency-bound backends this
        # feature targets.  Short drains pad the gradient LIST with this
        # cached zero handle so the stacked G is always exactly
        # (max_drain, d) -- ONE stack shape, ONE compile (a per-mcount
        # stack/concat would compile a fresh executable for every distinct
        # drain size, and on a tunneled backend each compile blocks the
        # device for seconds -- measured 70x throughput loss)
        _mask_cache: Dict[int, jax.Array] = {}
        _zero_g = jax.device_put(
            jnp.zeros(d, jnp.float32), self.driver_device
        )

        def updater():
            max_drain = max(cfg.drain_batch, 1)
            while not stop.is_set():
                with state_lock:
                    if state["k"] >= cfg.num_iterations:
                        break
                try:
                    results = [ctx.collect_all(timeout=cfg.collect_timeout_s)]
                except queue.Empty:
                    continue
                # opportunistic drain: everything already queued, up to the
                # batch cap, folds into one device dispatch below
                while len(results) < max_drain:
                    try:
                        results.append(ctx.collect_all(timeout=0))
                    except queue.Empty:
                        break
                do_save = False
                # trace timings (metrics/trace.py): drained -> lock+filter
                # (merge.queue) -> device apply (merge.apply); only paid
                # when a tracer is sampling this run
                t_drained = now_ms() if inst.tracer is not None else 0.0
                t_apply0 = t_apply1 = t_drained
                with state_lock:
                    k = state["k"]
                    # never apply past the iteration budget: trim the batch
                    room = cfg.num_iterations - k
                    merged = []
                    accepted_g = []
                    for res in results:
                        state["flops"] += self._task_flops(res.worker_id)
                        task_ms = waiting.on_finish(res.worker_id, now_ms())
                        if res.staleness > cfg.taw:
                            state["dropped"] += 1
                            merged.append(
                                (res, False, task_ms, k + len(accepted_g))
                            )
                        elif len(accepted_g) < room:
                            g = res.data
                            if g.device != self.driver_device:
                                g = jax.device_put(g, self.driver_device)
                            accepted_g.append(g)
                            calibrator.record(
                                k + len(accepted_g) - 1, task_ms
                            )
                            merged.append(
                                (res, True, task_ms, k + len(accepted_g) - 1)
                            )
                        # else: beyond the iteration budget -- ignored, like
                        # the old per-result loop's break-at-limit
                    if inst.tracer is not None:
                        t_apply0 = now_ms()
                    if len(accepted_g) >= BATCH_DRAIN_MIN:
                        # stack+apply = 2 dispatches replacing m.  The list
                        # is padded with the cached zero handle to the fixed
                        # max_drain length and masked, so stack AND
                        # apply_batch each compile ONCE, never per drained
                        # batch size.
                        mcount = len(accepted_g)
                        padded = accepted_g + [_zero_g] * (
                            max_drain - mcount
                        )
                        G = jnp.stack(padded)
                        mask = _mask_cache.get(mcount)
                        if mask is None:
                            mask = jax.device_put(
                                jnp.asarray(
                                    [1.0] * mcount
                                    + [0.0] * (max_drain - mcount),
                                    jnp.float32,
                                ),
                                self.driver_device,
                            )
                            _mask_cache[mcount] = mask
                        state["w"], state["k_dev"] = apply_batch(
                            state["w"], G, mask, state["k_dev"]
                        )
                    else:
                        for g in accepted_g:
                            state["w"], state["k_dev"] = self._apply(
                                state["w"], g, state["k_dev"]
                            )
                    if inst.tracer is not None:
                        t_apply1 = now_ms()
                    if accepted_g:
                        k_new = k + len(accepted_g)
                        state["k"] = k_new
                        state["accepted"] += len(accepted_g)
                        # snapshot when the batch crossed a printer boundary
                        # (the single-apply path snapshotted at each
                        # k % printer_freq == 0; a batch may cover several)
                        if any(
                            (k + j) % cfg.printer_freq == 0
                            for j in range(len(accepted_g))
                        ):
                            snapshots.append((now_ms(), state["w"]))
                        # range check: a batch jumping over a checkpoint
                        # boundary must still save
                        do_save = ckpt.should_save_range(k, k_new)
                        save_k, save_w = state["k"], state["w"]
                q_ms = max(0.0, t_apply0 - t_drained)
                a_ms = (max(0.0, t_apply1 - t_apply0)
                        / max(1, len(accepted_g)))
                for res, accepted, task_ms, at_k in merged:
                    inst.on_gradient_merged(
                        res.worker_id, res.staleness, accepted, at_k,
                        batch_size=res.batch_size, task_ms=task_ms,
                        queue_ms=q_ms, apply_ms=a_ms if accepted else 0.0,
                    )
                if do_save:
                    save_checkpoint(save_k, save_w)
                if calibrator.maybe_finalize(state["k"]):
                    delay_model.calibrate(calibrator.avg_delay_ms)
            stop.set()

        upd = threading.Thread(target=updater, name="ps-updater", daemon=True)
        upd.start()

        # ---------------------------------------------------- submitter loop
        from collections import deque

        waiters: deque = deque(maxlen=4 * nw)  # recent jobs, failure check
        deadline = time.monotonic() + cfg.run_timeout_s
        run_ok = False
        try:
            while not stop.is_set() and time.monotonic() < deadline:
                failed = next((x.failed for x in waiters if x.failed), None)
                if failed is not None:
                    raise RuntimeError("async job aborted") from failed
                with state_lock:
                    if state["k"] >= cfg.num_iterations:
                        break
                # cold workers (no STAT entry) always selected; warm workers
                # only when the availability threshold is met (the reference's
                # wait loop + ASYNCbarrier combination)
                cohort = partial_barrier(
                    ctx, nw, bucket_predicate(ctx, nw, cfg.bucket_ratio)
                )
                if not cohort:
                    time.sleep(0.001)
                    continue
                with state_lock:
                    w_pub = state["w"]  # immutable handle = model version
                    model_version = state["k"]
                if store is not None:
                    # ASYNCbroadcast parity: publish this round's model as a
                    # new version, then point workers at (latest - offset).
                    # The version's device buffer is resolved HERE, at submit
                    # time: a straggling worker must not re-query the store
                    # later (the version may have been evicted by newer
                    # publishes); the captured handle keeps the array alive
                    # regardless of store eviction.
                    v = store.publish(np.asarray(w_pub))
                    live = store.live_versions()
                    tv = max(live[0], v - cfg.stale_read_offset)
                    w_pub = store.value(self.driver_device, version=tv)
                    model_version = v
                ts = ctx.get_current_time()
                ctx.set_last_time(ts)
                ctx.mark_busy(cohort)
                waiting.on_submit(cohort, now_ms())
                with key_lock:
                    keys = {wid: worker_keys[wid] for wid in cohort}
                fns = {
                    wid: self._make_task(wid, w_pub, keys[wid], delay_model)
                    for wid in cohort
                }
                with state_lock:
                    state["rounds"] += 1
                    round_idx = state["rounds"]
                # post BEFORE launching: a fast worker could otherwise merge
                # (and the live UI could observe accepted>0) before its
                # round's RoundSubmitted event exists
                inst.on_round_submitted(round_idx, cohort, model_version)
                waiter = sched.run_job(
                    fns, self._handler(ctx, ts, now_ms, worker_keys, key_lock)
                )
                waiters.append(waiter)
            run_ok = True
        finally:
            stop.set()
            upd.join(timeout=10)
            if ft is not None:
                ft.stop()
            if spec is not None:
                spec.stop()
            if alloc is not None:
                alloc.stop()
            sched.shutdown()
            if not run_ok:
                inst.close()  # crash path: flush/seal the event log now

        with state_lock:
            final_k, final_w_dev = state["k"], state["w"]
        # materialize BEFORE taking elapsed: np.asarray is the only fence
        # this backend honors unconditionally (block_until_ready has been
        # observed returning before execution on the tunneled platform), so
        # elapsed/updates_per_sec cover the work actually done, not merely
        # dispatched
        final_w = np.asarray(final_w_dev)
        elapsed = time.monotonic() - start_wall
        snapshots.append((elapsed * 1e3, final_w_dev))
        if ckpt.enabled:
            save_checkpoint(final_k, final_w_dev)
        traj = self._evaluate_trajectory(snapshots)
        extras = inst.extras()
        if spec is not None:
            extras["speculated"] = spec.speculated_count()
            extras["speculation_wins"] = sched.speculative_wins()
        if alloc is not None:
            extras["executors_added"], extras["executors_removed"] = (
                alloc.counts()
            )
        inst.close(traj, cfg.printer_freq)
        return TrainResult(
            final_w=final_w,
            trajectory=traj,
            elapsed_s=elapsed,
            accepted=state["accepted"],
            dropped=state["dropped"],
            rounds=state["rounds"],
            max_staleness=ctx.max_staleness(),
            avg_delay_ms=calibrator.avg_delay_ms,
            updates_per_sec=state["accepted"] / elapsed if elapsed > 0 else 0.0,
            total_flops=state["flops"],
            waiting_time_ms=waiting.snapshot(),
            extras=extras,
        )

    # ----------------------------------------------------------------- fused
    def run_fused(self) -> TrainResult:
        """Device-resident accept loop (VERDICT r3 item 2): the taw=inf
        full-wave recipe fused into ``lax.scan`` rounds -- zero host work
        per update, so the ~1 ms/update dispatch bound that capped every
        dataset's honest updates/s (BASELINE.md round 3) is gone.

        Scope guard: this is the fast path for exactly the reference's
        headline recipes (``taw = inf``, no straggler injection); anything
        needing the runtime -- finite taw, speculation, fault tolerance,
        dynamic allocation -- runs the engine path.  Dense and padded-ELL
        sparse shards both fuse.  See ``steps.make_fused_asgd_rounds`` for
        the semantics argument.
        """
        cfg = self.cfg
        nw = cfg.num_workers
        if cfg.taw < nw - 1:
            # the fused execution's staleness is bounded by nw-1 BY
            # CONSTRUCTION (one wave in flight, applied in order), so for
            # any taw >= nw-1 it is a valid bounded-staleness execution of
            # the recipe -- ASGD's `staleness <= taw` filter would never
            # fire.  That covers the reference's ASGD headline recipes
            # (taw 2e7 / inf, the reference repo's README.md:64 rows);
            # only genuinely tight bounds need the engine.
            raise ValueError(
                f"run_fused admits taw >= num_workers-1 = {nw - 1} (its "
                "wave staleness never exceeds that); a tighter taw needs "
                "the engine's tau filter -- use run()"
            )
        if cfg.coeff != 0.0:
            raise ValueError(
                "run_fused cannot inject stragglers (no host between "
                "updates); use run()"
            )
        d = self.ds.d
        drv = self.driver_device
        shards = []
        for wid in range(nw):
            shard = self._recovery.shard(wid)
            if self._sparse:
                parts = (shard.cols, shard.vals, shard.y)
            else:
                parts = (shard.X, shard.y)
            if parts[0].device != drv:  # all shards ride the PS device
                parts = tuple(jax.device_put(a, drv) for a in parts)
            shards.append(parts)
        sparse_d = d if self._sparse else None
        total_rounds = max(1, -(-cfg.num_iterations // nw))

        def make_runner(length):
            rr = steps.make_fused_asgd_rounds(
                cfg.gamma, cfg.batch_rate, self.ds.n, shards,
                loss=cfg.loss, rounds_per_call=length, sparse_d=sparse_d,
            )

            def run(carry):
                w, k, keys = carry
                w, k, keys, W_snap = rr(w, k, keys)
                return (w, k, keys), W_snap

            return run

        w = jax.device_put(jnp.zeros(d, jnp.float32), drv)
        k = jax.device_put(jnp.float32(0.0), drv)
        keys = jax.device_put(jnp.stack([
            jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid)
            for wid in range(nw)
        ]), drv)
        from asyncframework_tpu.solvers.base import run_fused_plan

        (w, k, keys), snapshots, start_wall, done_rounds = run_fused_plan(
            make_runner, (w, k, keys), total_rounds, nw, cfg.printer_freq,
            w_of=lambda c: c[0],
        )
        final_w = np.asarray(w)  # fence BEFORE elapsed (axon lazy-complete)
        elapsed = time.monotonic() - start_wall
        accepted = done_rounds * nw
        snapshots.append((elapsed * 1e3, w))
        traj = self._evaluate_trajectory(snapshots)
        flops = sum(
            self._task_flops(wid) for wid in range(nw)
        ) * done_rounds
        return TrainResult(
            final_w=final_w,
            trajectory=traj,
            elapsed_s=elapsed,
            accepted=accepted,
            dropped=0,
            rounds=done_rounds,
            max_staleness=nw - 1,  # by construction of the full wave
            avg_delay_ms=0.0,
            updates_per_sec=accepted / elapsed if elapsed > 0 else 0.0,
            total_flops=flops,
            waiting_time_ms={},
            extras={"fused": True,
                    "rounds_per_call": min(16, total_rounds)},
        )

    # ------------------------------------------------------------------ sync
    def run_sync(self) -> TrainResult:
        """SparkASGDSync parity: submit to all, drain all, one update/round."""
        cfg = self.cfg
        nw = cfg.num_workers
        ctx: AsyncContext = AsyncContext()
        sched = JobScheduler(num_workers=nw, devices=self.devices)
        sched.set_mode(ASYNC)  # non-blocking submit + driver-side drain
        self.scheduler = sched  # exposed for fault-injection tests/tools
        delay_model = DelayModel(cfg.coeff, nw, cfg.seed)
        # sync counts rounds, not accepted gradients: the reference's
        # k < 100*numPart window covers the first 100 full-drain rounds.
        # An explicit calibration_iters overrides (in rounds).
        calibrator = DelayCalibrator(
            cfg.calibration_iters if cfg.calibration_iters is not None else 100
        )
        waiting = WaitingTimeTable()
        inst = RunInstruments(cfg, nw)
        inst.register_queue_depth(ctx.size)
        ft = None
        if cfg.heartbeat:
            ft = FaultTolerantRun(
                sched, self._recovery, inst, nw,
                heartbeat_timeout_ms=cfg.heartbeat_timeout_ms,
                check_interval_s=cfg.heartbeat_interval_s,
                max_slot_failures=cfg.max_slot_failures,
            )
            ft.start()
        spec = None
        if cfg.speculation:
            # the reference runs speculation on its synchronous stages: the
            # full drain is exactly where one straggler stalls the round
            spec = SpeculationMonitor(
                sched, quantile=cfg.speculation_quantile,
                multiplier=cfg.speculation_multiplier,
                min_time_ms=cfg.speculation_min_ms,
                on_launch=inst.on_speculative_launch,
            )
            spec.start()
        alloc = make_allocation_manager(cfg, sched)

        w = jax.device_put(jnp.zeros(self.ds.d, jnp.float32), self.driver_device)
        k_dev = jax.device_put(jnp.float32(0.0), self.driver_device)
        worker_keys = {
            wid: jax.device_put(
                jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid),
                self._shard_device(wid),
            )
            for wid in range(nw)
        }
        self._warm_hot_path(sync=True)
        start_wall = time.monotonic()
        snapshots: List[Tuple[float, jax.Array]] = [(0.0, w)]

        def now_ms():
            return (time.monotonic() - start_wall) * 1e3

        rounds = 0
        flops = 0.0
        run_ok = False
        try:
            for k in range(cfg.num_iterations):
                cohort = list(range(nw))
                ts = ctx.get_current_time()
                ctx.mark_busy(cohort)
                waiting.on_submit(cohort, now_ms())
                key_lock = threading.Lock()
                fns = {
                    wid: self._make_task(wid, w, worker_keys[wid], delay_model)
                    for wid in cohort
                }
                inst.on_round_submitted(k, cohort, model_version=k)
                waiter = sched.run_job(
                    fns, self._handler(ctx, ts, now_ms, worker_keys, key_lock)
                )
                acc = None
                reported = set()
                for _ in range(nw):
                    res = self._collect_checked(
                        ctx, waiter, cfg.run_timeout_s,
                        pool=sched.pool, cohort=cohort, collected=reported,
                    )
                    reported.add(res.worker_id)
                    g = res.data
                    flops += self._task_flops(res.worker_id)
                    task_ms = waiting.on_finish(res.worker_id, now_ms())
                    calibrator.record(k, task_ms)
                    inst.on_gradient_merged(
                        res.worker_id, res.staleness, True, k,
                        batch_size=res.batch_size, task_ms=task_ms,
                    )
                    if g.device != self.driver_device:
                        g = jax.device_put(g, self.driver_device)
                    acc = g if acc is None else steps.add_grads(acc, g)
                w, k_dev = self._sync_apply(w, acc, k_dev)
                rounds += 1
                if k % cfg.printer_freq == 0:
                    snapshots.append((now_ms(), w))
                if calibrator.maybe_finalize(k):
                    delay_model.calibrate(calibrator.avg_delay_ms)
            run_ok = True
        finally:
            if ft is not None:
                ft.stop()
            if spec is not None:
                spec.stop()
            if alloc is not None:
                alloc.stop()
            sched.shutdown()
            if not run_ok:
                inst.close()  # crash path: flush/seal the event log now

        final_w = np.asarray(w)  # fence: see the async path's comment
        elapsed = time.monotonic() - start_wall
        snapshots.append((elapsed * 1e3, w))
        traj = self._evaluate_trajectory(snapshots)
        extras = inst.extras()
        if spec is not None:
            extras["speculated"] = spec.speculated_count()
            extras["speculation_wins"] = sched.speculative_wins()
        if alloc is not None:
            extras["executors_added"], extras["executors_removed"] = (
                alloc.counts()
            )
        inst.close(traj, cfg.printer_freq)
        return TrainResult(
            final_w=final_w,
            trajectory=traj,
            elapsed_s=elapsed,
            accepted=rounds * nw,
            rounds=rounds,
            max_staleness=ctx.max_staleness(),
            avg_delay_ms=calibrator.avg_delay_ms,
            updates_per_sec=rounds / elapsed if elapsed > 0 else 0.0,
            total_flops=flops,
            waiting_time_ms=waiting.snapshot(),
            extras=extras,
        )

    # ---------------------------------------------------------------- helpers
    def _collect_checked(self, ctx: AsyncContext, waiter, timeout_s: float,
                         pool=None, cohort=None, collected=None):
        """Shared fail-fast drain (solvers/base.py): surfaces job aborts,
        and -- given the pool -- aborts promptly with the per-worker
        liveness diagnostic when a cohort executor dies unreplaced,
        instead of hanging for the full run timeout."""
        grace = (
            4.0 * self.cfg.heartbeat_interval_s + 2.0
            if self.cfg.heartbeat else 0.5
        )
        return collect_checked(
            ctx, waiter, timeout_s, pool=pool, cohort=cohort,
            dead_grace_s=grace, collected=collected,
        )

    def _shard_device(self, wid: int):
        return self.devices[wid % len(self.devices)]

    def _warm_hot_path(
        self, apply_batch=None, max_drain: int = 0, sync: bool = False
    ) -> None:
        """Compile this mode's hot-path executables before the trajectory
        clock starts.

        Parity: the reference's first iteration always blocks precisely to
        warm Spark's caches (``DAGScheduler.scala:641-656`` ``first_iter``);
        the TPU analog is XLA compilation of the worker step, the accept
        path, and the batched drain, which would otherwise land inside the
        timed region on their first invocation (~1 s on a real chip).

        jit caches per input SHAPE, so every distinct shard shape is warmed
        (shards differ by one row when ``n % num_workers != 0``).  Async
        warms ``_apply`` + ``apply_batch``; sync warms ``_sync_apply`` +
        ``add_grads``.  All dummies are fresh device buffers, so donated
        arguments never touch live state.
        """
        d = self.ds.d
        drv = self.driver_device
        g = None
        seen = set()
        for wid in range(self.cfg.num_workers):
            shard = self._recovery.shard(wid)
            dev = shard.device
            # key on (shape, device): jit executables are cached per device
            # commitment, so equal-shaped shards on different chips each
            # need their own warm compile
            shape_key = (
                (shard.cols.shape if self._sparse else shard.X.shape), dev
            )
            if shape_key in seen:
                continue
            seen.add(shape_key)
            w0 = jax.device_put(jnp.zeros(d, jnp.float32), dev)
            key = jax.device_put(jax.random.PRNGKey(0), dev)
            if self._sparse:
                g, _ = self._step(shard.cols, shard.vals, shard.y, w0, key)
            else:
                g, _ = self._step(shard.X, shard.y, w0, key)
        if g.device != drv:
            g = jax.device_put(g, drv)
        wd = jax.device_put(jnp.zeros(d, jnp.float32), drv)
        kd = jax.device_put(jnp.float32(0.0), drv)
        if sync:
            acc = jax.device_put(jnp.zeros(d, jnp.float32), drv)
            acc = steps.add_grads(acc, g)
            wd, kd = self._sync_apply(wd, acc, kd)
        else:
            wd, kd = self._apply(wd, g, kd)
            if apply_batch is not None and max_drain >= BATCH_DRAIN_MIN:
                # stack of max_drain vectors, exactly like the drain path
                # builds G -- warms the stack executable too, not just
                # apply_batch
                zero = jax.device_put(jnp.zeros(d, jnp.float32), drv)
                G = jnp.stack([zero] * max_drain)
                mask = jax.device_put(
                    jnp.zeros((max_drain,), jnp.float32), drv
                )
                wd, kd = apply_batch(wd, G, mask, kd)
        wd.block_until_ready()

    def _make_task(self, wid: int, w_pub, key, delay_model: DelayModel):
        # recovery view: a re-homed shard is transparently computed on its
        # new device; w and the PRNG chain follow the shard's home
        shard = self._recovery.shard(wid)
        delay_ms = delay_model.delay_ms(wid)
        dev = shard.device
        step = self._step
        sparse = self._sparse
        # The injected delay models a slow *machine*: only the first body to
        # run it sleeps -- a speculative copy or a replacement executor is a
        # different (healthy) host path and must bypass the straggler.
        delay_fired = threading.Event()

        def fn():
            if delay_ms > 0 and not delay_fired.is_set():
                delay_fired.set()
                time.sleep(delay_ms / 1e3)
            w_local = w_pub
            if w_local.device != dev:
                w_local = jax.device_put(w_local, dev)
            key_local = key
            if key_local.device != dev:
                key_local = jax.device_put(key_local, dev)
            if sparse:
                g, new_key = step(shard.cols, shard.vals, shard.y, w_local, key_local)
            else:
                g, new_key = step(shard.X, shard.y, w_local, key_local)
            g.block_until_ready()  # completion only; data stays in HBM
            return g, new_key

        return fn

    def _handler(
        self, ctx: AsyncContext, submit_clock: int, now_ms, worker_keys, key_lock
    ):
        submit_wall = now_ms()
        par_recs = int(self.cfg.batch_rate * self.ds.n / self.cfg.num_workers)

        def handler(wid: int, result):
            g, new_key = result
            # The key slot MUST advance before merge_result flips the worker
            # available -- otherwise the spinning submitter can re-dispatch
            # this worker with its previous key and replay the same mask.
            with key_lock:
                worker_keys[wid] = new_key
            ctx.merge_result(
                wid,
                g,
                submit_clock=submit_clock,
                elapsed_ms=now_ms() - submit_wall,
                batch_size=par_recs,
            )

        return handler

    def _evaluate_trajectory(
        self, snapshots: List[Tuple[float, jax.Array]]
    ) -> List[Tuple[float, float]]:
        """One-pass objective evaluation for all snapshots (optVars parity):
        stack snapshots into (S, d); per shard one matmul gives (S,) losses."""
        W = jnp.stack([h for (_t, h) in snapshots])
        totals = np.zeros(len(snapshots), np.float64)
        for wid in range(self.cfg.num_workers):
            shard = self._recovery.shard(wid)  # follows re-homed shards
            Wd = W
            if Wd.device != shard.device:
                Wd = jax.device_put(W, shard.device)
            if self._sparse:
                part = self._eval(shard.cols, shard.vals, shard.y, Wd)
            else:
                part = self._eval(shard.X, shard.y, Wd)
            totals += np.asarray(part, np.float64)
        totals /= self.ds.n
        traj = [(t, float(l)) for (t, _), l in zip(snapshots, totals)]
        # continuous telemetry: the finished run's loss-vs-wallclock curve
        # lands in the process-global convergence history (the /api/status
        # `convergence` section the in-process live UI serves)
        from asyncframework_tpu.metrics import timeseries as _ts

        _ts.fold_trajectory(traj)
        return traj
