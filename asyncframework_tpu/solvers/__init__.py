from asyncframework_tpu.solvers.base import SolverConfig, TrainResult
from asyncframework_tpu.solvers.asgd import ASGD
from asyncframework_tpu.solvers.asaga import ASAGA
from asyncframework_tpu.solvers.minibatch_sgd import MiniBatchSGD

__all__ = ["SolverConfig", "TrainResult", "ASGD", "ASAGA", "MiniBatchSGD"]
