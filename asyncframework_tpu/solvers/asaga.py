"""ASAGA: asynchronous SAGA with a per-sample gradient-history table.

Parity targets: ``SparkASAGAThread.scala`` (async) / ``SparkASAGASync.scala``.
For least squares a per-sample gradient is ``scalar_i * x_i`` with
``scalar_i = x_i . w - y_i``, so the history compresses to one f32 per sample
(``ScalarMap``, ``SparkASAGAThread.scala:114``).

TPU re-design of the history table: the reference keeps a driver-side
``HashMap[Long, Double]`` and ships sampled entries to workers each round
(``sampledMap``, lines 280-294).  Here each worker's slice of the table is a
dense f32 array **resident in its device HBM** (8.1M samples == 32 MB total --
trivial), so the worker's history-corrected gradient needs *no* host traffic
at all: ``g = X^T (mask * (diff - alpha))`` reads the local slice.  Candidate
new scalars (``diff``) ride back as device handles; the updater *commits* them
into the worker's slice only for accepted (non-stale) results -- exactly the
reference's driver-controlled ScalarMap merge, as an on-device
``where(mask, diff, alpha)``.

Update rule on accept (``SparkASAGAThread.scala:210-213``):
``w -= gamma * (g/parRecs + alpha_bar)``; ``alpha_bar += g/N``.
Staleness filter quirk preserved: ASAGA accepts iff ``k - staleness <= taw``
(the ASGD driver tests ``staleness <= taw``) -- see the updater in
``SparkASAGAThread.scala:184``.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from asyncframework_tpu.context import AsyncContext
from asyncframework_tpu.data.sharded import ShardedDataset
from asyncframework_tpu.engine.barrier import bucket_predicate, partial_barrier
from asyncframework_tpu.engine.recovery import ShardRecovery
from asyncframework_tpu.engine.scheduler import ASYNC, JobScheduler
from asyncframework_tpu.engine.speculation import SpeculationMonitor
from asyncframework_tpu.engine.straggler import DelayModel
from asyncframework_tpu.ops import steps
from asyncframework_tpu.solvers.base import (
    DelayCalibrator,
    FlopsAccountingMixin,
    make_allocation_manager,
    SolverCheckpointer,
    SolverConfig,
    TrainResult,
    WaitingTimeTable,
    check_hbm_plan,
    collect_checked,
    resolve_dataset,
)
from asyncframework_tpu.solvers.instrumentation import (
    FaultTolerantRun,
    RunInstruments,
)


class ASAGA(FlopsAccountingMixin):
    def __init__(
        self,
        X,
        y: Optional[np.ndarray],
        config: SolverConfig,
        devices: Optional[list] = None,
    ):
        """``X`` may be a host array (sharded here) or a pre-built
        :class:`ShardedDataset` (e.g. generated on device), with ``y=None``."""
        if config.loss != "least_squares":
            raise ValueError(
                "ASAGA's scalar history compression requires least_squares "
                "(gradient = scalar * x); got " + config.loss
            )
        self.cfg = config
        self.devices = list(devices) if devices is not None else jax.devices()
        check_hbm_plan(X, config, self.devices, history_table=True)
        self.ds = resolve_dataset(X, y, config.num_workers, self.devices)
        self.driver_device = self.devices[0]
        self._sparse = bool(getattr(self.ds, "is_sparse", False))
        if self._sparse:
            self._step = steps.make_sparse_saga_worker_step(
                config.batch_rate, self.ds.d
            )
            self._sparse_compact = True  # flops = compacted rows, not n_p
            self._commit = steps.make_sparse_saga_commit()
            self._table_delta = steps.make_sparse_table_delta(self.ds.d)
            self._eval = steps.make_sparse_trajectory_loss_eval()
        else:
            self._step = steps.make_saga_worker_step(config.batch_rate)
            self._table_delta = steps.make_saga_table_delta()
            self._eval = steps.make_trajectory_loss_eval("least_squares")
        self._apply = steps.make_saga_apply(
            config.gamma, config.batch_rate, self.ds.n, config.num_workers
        )
        self._recovery = ShardRecovery(self.ds, self.devices)

    # ------------------------------------------------------------------ async
    def run(self) -> TrainResult:
        cfg = self.cfg
        nw = cfg.num_workers
        ctx: AsyncContext = AsyncContext()
        sched = JobScheduler(num_workers=nw, devices=self.devices)
        sched.set_mode(ASYNC)
        self.scheduler = sched  # exposed for fault-injection tests/tools
        delay_model = DelayModel(cfg.coeff, nw, cfg.seed)
        calibrator = DelayCalibrator(cfg.effective_calibration_iters())
        waiting = WaitingTimeTable()
        inst = RunInstruments(cfg, nw)
        inst.register_queue_depth(ctx.size)

        d = self.ds.d
        ckpt = SolverCheckpointer(cfg, "asaga", d, self.ds.n)
        ck = ckpt.restore()
        if ck is not None:
            # Resume: model, running history mean, the full per-worker history
            # table, the accepted counter, logical clock, and PRNG chains.
            k0 = int(ck["k"])
            ctx.set_current_time(int(ck["clock"]))
            w = jax.device_put(jnp.asarray(ck["w"]), self.driver_device)
            alpha_bar = jax.device_put(
                jnp.asarray(ck["alpha_bar"]), self.driver_device
            )
            alpha: Dict[int, jax.Array] = {
                wid: jax.device_put(jnp.asarray(a), self._shard_device(wid))
                for wid, a in ck["alpha"].items()
            }
            worker_keys: Dict[int, jax.Array] = {
                wid: jax.device_put(jnp.asarray(key), self._shard_device(wid))
                for wid, key in ck["worker_keys"].items()
            }
        else:
            k0 = 0
            w = jax.device_put(jnp.zeros(d, jnp.float32), self.driver_device)
            alpha_bar = jax.device_put(jnp.zeros(d, jnp.float32), self.driver_device)
            # the history table: one slice per worker, resident in its HBM
            alpha = {
                wid: jax.device_put(
                    jnp.zeros(self.ds.shard(wid).size, jnp.float32),
                    self._shard_device(wid),
                )
                for wid in range(nw)
            }
            worker_keys = {
                wid: jax.device_put(
                    jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid),
                    self._shard_device(wid),
                )
                for wid in range(nw)
            }
        hot_lock = threading.Lock()  # guards alpha/worker_keys handle slots

        def on_shard_moved(shard_id, moved):
            # the history slice and PRNG chain follow the shard's new home
            with hot_lock:
                alpha[shard_id] = jax.device_put(alpha[shard_id], moved.device)
                worker_keys[shard_id] = jax.device_put(
                    worker_keys[shard_id], moved.device
                )

        ft = None
        if cfg.heartbeat:
            ft = FaultTolerantRun(
                sched, self._recovery, inst, nw,
                heartbeat_timeout_ms=cfg.heartbeat_timeout_ms,
                check_interval_s=cfg.heartbeat_interval_s,
                max_slot_failures=cfg.max_slot_failures,
                on_moved=on_shard_moved,
            )
            ft.start()
        spec = None
        if cfg.speculation:
            spec = SpeculationMonitor(
                sched, quantile=cfg.speculation_quantile,
                multiplier=cfg.speculation_multiplier,
                min_time_ms=cfg.speculation_min_ms,
                on_launch=inst.on_speculative_launch,
            )
            spec.start()
        alloc = make_allocation_manager(cfg, sched)
        # stale-read experiment: the reference's ASAGA driver is the main
        # ASYNCbroadcast user (SparkASAGAThread.scala:268); workers read
        # model version (latest - offset)
        from asyncframework_tpu.broadcast import VersionedModelStore

        store = (
            VersionedModelStore(cfg.max_live_versions)
            if cfg.stale_read_offset is not None
            else None
        )

        state = {"w": w, "ab": alpha_bar, "k": k0, "accepted": 0, "dropped": 0,
                 "rounds": 0, "flops": 0.0}
        state_lock = threading.Lock()
        stop = threading.Event()
        self._warm_hot_path()
        start_wall = time.monotonic()
        snapshots: List[Tuple[float, jax.Array]] = [(0.0, w)]

        def now_ms():
            return (time.monotonic() - start_wall) * 1e3

        def save_checkpoint(save_k: int, save_w, save_ab) -> None:
            with hot_lock:
                keys_h = {wid: np.asarray(kv) for wid, kv in worker_keys.items()}
                alpha_h = {wid: np.asarray(a) for wid, a in alpha.items()}
            ckpt.save(
                save_k,
                w=np.asarray(save_w),
                alpha_bar=np.asarray(save_ab),
                alpha=alpha_h,
                clock=ctx.get_current_time(),
                worker_keys=keys_h,
            )

        def updater():
            while not stop.is_set():
                with state_lock:
                    if state["k"] >= cfg.num_iterations:
                        break
                try:
                    res = ctx.collect_all(timeout=cfg.collect_timeout_s)
                except queue.Empty:
                    continue
                g = res.data[0]
                task_ms = waiting.on_finish(res.worker_id, now_ms())
                do_save = False
                # trace timings (metrics/trace.py): collect -> lock
                # (merge.queue) -> history-corrected apply (merge.apply)
                t_drained = now_ms() if inst.tracer is not None else 0.0
                t_apply0 = t_apply1 = t_drained
                with state_lock:
                    if inst.tracer is not None:
                        t_apply0 = now_ms()
                    state["flops"] += self._task_flops(res.worker_id)
                    k = state["k"]
                    # ASAGA acceptance quirk: k - staleness <= taw
                    accepted = k - res.staleness <= cfg.taw
                    if accepted:
                        shard = self._recovery.shard(res.worker_id)
                        with hot_lock:
                            alpha_cur = alpha[res.worker_id]
                            # a shard re-homed while this result was in
                            # flight leaves the payload on the old device;
                            # normalize onto the slice's current home
                            home = alpha_cur.device
                            payload = tuple(
                                jax.device_put(a, home) if a.device != home
                                else a
                                for a in res.data[1:]
                            )
                            # exact table delta (see make_saga_table_delta)
                            if self._sparse:
                                diff, idx, valid, c_sel, v_sel = payload
                                delta = self._table_delta(
                                    c_sel, v_sel, diff, alpha_cur, idx
                                )
                                alpha[res.worker_id] = self._commit(
                                    alpha_cur, diff, idx, valid
                                )
                            else:
                                diff, mask = payload
                                delta = self._table_delta(
                                    shard.X, diff, mask, alpha_cur
                                )
                                alpha[res.worker_id] = (
                                    steps.saga_commit_history(
                                        alpha_cur, diff, mask
                                    )
                                )
                        if g.device != self.driver_device:
                            g = jax.device_put(g, self.driver_device)
                        if delta.device != self.driver_device:
                            delta = jax.device_put(delta, self.driver_device)
                        state["w"], state["ab"] = self._apply(
                            state["w"], state["ab"], g, delta
                        )
                        state["k"] = k + 1
                        state["accepted"] += 1
                        calibrator.record(k, task_ms)
                        if k % cfg.printer_freq == 0:
                            snapshots.append((now_ms(), state["w"]))
                        do_save = ckpt.should_save(state["k"])
                        save_k, save_w, save_ab = (
                            state["k"], state["w"], state["ab"]
                        )
                    else:
                        state["dropped"] += 1
                    if inst.tracer is not None:
                        t_apply1 = now_ms()
                inst.on_gradient_merged(
                    res.worker_id, res.staleness, accepted, k,
                    batch_size=res.batch_size, task_ms=task_ms,
                    queue_ms=max(0.0, t_apply0 - t_drained),
                    apply_ms=max(0.0, t_apply1 - t_apply0),
                )
                if do_save:
                    save_checkpoint(save_k, save_w, save_ab)
                if calibrator.maybe_finalize(state["k"]):
                    delay_model.calibrate(calibrator.avg_delay_ms)
            stop.set()

        upd = threading.Thread(target=updater, name="saga-updater", daemon=True)
        upd.start()

        from collections import deque

        waiters: deque = deque(maxlen=4 * nw)
        deadline = time.monotonic() + cfg.run_timeout_s
        run_ok = False
        try:
            while not stop.is_set() and time.monotonic() < deadline:
                failed = next((x.failed for x in waiters if x.failed), None)
                if failed is not None:
                    raise RuntimeError("async job aborted") from failed
                with state_lock:
                    if state["k"] >= cfg.num_iterations:
                        break
                cohort = partial_barrier(
                    ctx, nw, bucket_predicate(ctx, nw, cfg.bucket_ratio)
                )
                if not cohort:
                    time.sleep(0.001)
                    continue
                with state_lock:
                    w_pub = state["w"]
                    model_version = state["k"]
                if store is not None:
                    # version buffer resolved at submit time: eviction by
                    # later publishes cannot invalidate an in-flight read
                    v = store.publish(np.asarray(w_pub))
                    live = store.live_versions()
                    tv = max(live[0], v - cfg.stale_read_offset)
                    w_pub = store.value(self.driver_device, version=tv)
                    model_version = v
                ts = ctx.get_current_time()
                ctx.set_last_time(ts)
                ctx.mark_busy(cohort)
                waiting.on_submit(cohort, now_ms())
                with hot_lock:
                    captured = {
                        wid: (worker_keys[wid], alpha[wid]) for wid in cohort
                    }
                fns = {
                    wid: self._make_task(
                        wid, w_pub, captured[wid][0], captured[wid][1], delay_model
                    )
                    for wid in cohort
                }
                with state_lock:
                    state["rounds"] += 1
                    round_idx = state["rounds"]
                # post BEFORE launching: a fast worker could otherwise merge
                # before its round's RoundSubmitted event exists
                inst.on_round_submitted(round_idx, cohort, model_version)
                waiter = sched.run_job(
                    fns, self._handler(ctx, ts, now_ms, worker_keys, hot_lock)
                )
                waiters.append(waiter)
            run_ok = True
        finally:
            stop.set()
            upd.join(timeout=10)
            if ft is not None:
                ft.stop()
            if spec is not None:
                spec.stop()
            if alloc is not None:
                alloc.stop()
            sched.shutdown()
            if not run_ok:
                inst.close()  # crash path: flush/seal the event log now

        with state_lock:
            final_k, final_w_dev, final_ab = state["k"], state["w"], state["ab"]
        # materialize BEFORE taking elapsed: np.asarray is the only fence the
        # tunneled backend honors unconditionally, so elapsed covers work
        # actually done, not merely dispatched (see ASGD.run)
        final_w = np.asarray(final_w_dev)
        elapsed = time.monotonic() - start_wall
        snapshots.append((elapsed * 1e3, final_w_dev))
        if ckpt.enabled:
            save_checkpoint(final_k, final_w_dev, final_ab)
        traj = self._evaluate_trajectory(snapshots)
        run_extras = inst.extras()
        if spec is not None:
            run_extras["speculated"] = spec.speculated_count()
            run_extras["speculation_wins"] = sched.speculative_wins()
        if alloc is not None:
            (
                run_extras["executors_added"],
                run_extras["executors_removed"],
            ) = alloc.counts()
        inst.close(traj, cfg.printer_freq)
        return TrainResult(
            final_w=final_w,
            trajectory=traj,
            elapsed_s=elapsed,
            accepted=state["accepted"],
            dropped=state["dropped"],
            rounds=state["rounds"],
            max_staleness=ctx.max_staleness(),
            avg_delay_ms=calibrator.avg_delay_ms,
            updates_per_sec=state["accepted"] / elapsed if elapsed > 0 else 0.0,
            total_flops=state["flops"],
            waiting_time_ms=waiting.snapshot(),
            extras={
                "alpha": {wid: np.asarray(a) for wid, a in alpha.items()},
                "alpha_bar": np.asarray(state["ab"]),
                **run_extras,
            },
        )

    # ----------------------------------------------------------------- fused
    def run_fused(self) -> TrainResult:
        """Device-resident ASAGA (semantics in
        ``steps.make_fused_saga_rounds``, scope guards as in
        ``ASGD.run_fused`` plus the ASAGA taw quirk below).  Dense and
        padded-ELL sparse shards; the history slices live as scan carry,
        so the whole table stays in HBM across rounds."""
        cfg = self.cfg
        nw = cfg.num_workers
        if cfg.taw < cfg.num_iterations:
            # ASAGA's preserved acceptance quirk fires on the ITERATION
            # COUNT, not staleness: accept iff k - staleness <= taw
            # (SparkASAGAThread.scala:184; the updater at run()). A finite
            # taw therefore changes which of the k = 0..num_iterations-1
            # updates the engine accepts, and only taw >= num_iterations
            # guarantees the filter never fires -- unlike ASGD, whose
            # staleness-based filter is bounded by the wave (nw-1).
            raise ValueError(
                "fused ASAGA requires taw >= num_iterations (the ASAGA "
                "filter quirk `k - staleness <= taw` binds on iteration "
                "count); a tighter taw needs the engine's filter -- use "
                "run()"
            )
        if cfg.coeff != 0.0:
            raise ValueError(
                "run_fused cannot inject stragglers (no host between "
                "updates); use run()"
            )
        d = self.ds.d
        drv = self.driver_device
        shards = []
        for wid in range(nw):
            shard = self._recovery.shard(wid)
            if self._sparse:
                parts = (shard.cols, shard.vals, shard.y)
            else:
                parts = (shard.X, shard.y)
            if parts[0].device != drv:
                parts = tuple(jax.device_put(a, drv) for a in parts)
            shards.append(parts)
        sparse_d = d if self._sparse else None
        total_rounds = max(1, -(-cfg.num_iterations // nw))

        def make_runner(length):
            rr = steps.make_fused_saga_rounds(
                cfg.gamma, cfg.batch_rate, self.ds.n, shards,
                rounds_per_call=length, sparse_d=sparse_d,
            )

            def run(carry):
                w, ab, alphas, keys = carry
                w, ab, alphas, keys, W_snap = rr(w, ab, alphas, keys)
                return (w, ab, alphas, keys), W_snap

            return run

        w = jax.device_put(jnp.zeros(d, jnp.float32), drv)
        ab = jax.device_put(jnp.zeros(d, jnp.float32), drv)
        alphas = tuple(
            jax.device_put(
                jnp.zeros(parts[-1].shape[0], jnp.float32), drv
            )
            for parts in shards
        )
        keys = jax.device_put(jnp.stack([
            jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid)
            for wid in range(nw)
        ]), drv)
        from asyncframework_tpu.solvers.base import run_fused_plan

        ((w, ab, alphas, keys), snapshots, start_wall,
         done_rounds) = run_fused_plan(
            make_runner, (w, ab, alphas, keys), total_rounds, nw,
            cfg.printer_freq, w_of=lambda c: c[0],
        )
        final_w = np.asarray(w)  # fence BEFORE elapsed
        elapsed = time.monotonic() - start_wall
        accepted = done_rounds * nw
        snapshots.append((elapsed * 1e3, w))
        traj = self._evaluate_trajectory(snapshots)
        flops = sum(
            self._task_flops(wid) for wid in range(nw)
        ) * done_rounds
        return TrainResult(
            final_w=final_w,
            trajectory=traj,
            elapsed_s=elapsed,
            accepted=accepted,
            dropped=0,
            rounds=done_rounds,
            max_staleness=nw - 1,
            avg_delay_ms=0.0,
            updates_per_sec=accepted / elapsed if elapsed > 0 else 0.0,
            total_flops=flops,
            waiting_time_ms={},
            extras={
                "fused": True,
                "rounds_per_call": min(16, total_rounds),
                "alpha_bar": np.asarray(ab),
                # final history slices (engine parity: run() exposes
                # extras["alpha"]), and what the invariant test checks
                "alpha": {
                    wid: np.asarray(a) for wid, a in enumerate(alphas)
                },
            },
        )

    # ------------------------------------------------------------------- sync
    def run_sync(self) -> TrainResult:
        """SparkASAGASync parity: drain all workers per round, merge all
        histories, apply one accumulated update with ``parRecs = b*N``."""
        cfg = self.cfg
        nw = cfg.num_workers
        ctx: AsyncContext = AsyncContext()
        sched = JobScheduler(num_workers=nw, devices=self.devices)
        sched.set_mode(ASYNC)
        self.scheduler = sched  # exposed for fault-injection tests/tools
        delay_model = DelayModel(cfg.coeff, nw, cfg.seed)
        # rounds, not accepted gradients; explicit calibration_iters overrides
        calibrator = DelayCalibrator(
            cfg.calibration_iters if cfg.calibration_iters is not None else 100
        )
        waiting = WaitingTimeTable()
        inst = RunInstruments(cfg, nw)
        inst.register_queue_depth(ctx.size)
        sync_apply = steps.make_saga_apply(
            cfg.gamma, cfg.batch_rate, self.ds.n, 1,  # parRecs = b*N
            donate_g=False,  # the drain passes acc as both g and delta
        )

        w = jax.device_put(jnp.zeros(self.ds.d, jnp.float32), self.driver_device)
        alpha_bar = jax.device_put(jnp.zeros(self.ds.d, jnp.float32), self.driver_device)
        alpha = {
            wid: jax.device_put(
                jnp.zeros(self.ds.shard(wid).size, jnp.float32),
                self._shard_device(wid),
            )
            for wid in range(nw)
        }
        worker_keys = {
            wid: jax.device_put(
                jax.random.fold_in(jax.random.PRNGKey(cfg.seed), wid),
                self._shard_device(wid),
            )
            for wid in range(nw)
        }
        hot_lock = threading.Lock()  # guards alpha/worker_keys handle slots

        def on_shard_moved(shard_id, moved):
            # the history slice and PRNG chain follow the shard's new home
            # (same discipline as the async path)
            with hot_lock:
                alpha[shard_id] = jax.device_put(alpha[shard_id], moved.device)
                worker_keys[shard_id] = jax.device_put(
                    worker_keys[shard_id], moved.device
                )

        ft = None
        if cfg.heartbeat:
            ft = FaultTolerantRun(
                sched, self._recovery, inst, nw,
                heartbeat_timeout_ms=cfg.heartbeat_timeout_ms,
                check_interval_s=cfg.heartbeat_interval_s,
                max_slot_failures=cfg.max_slot_failures,
                on_moved=on_shard_moved,
            )
            ft.start()
        spec = None
        if cfg.speculation:
            spec = SpeculationMonitor(
                sched, quantile=cfg.speculation_quantile,
                multiplier=cfg.speculation_multiplier,
                min_time_ms=cfg.speculation_min_ms,
                on_launch=inst.on_speculative_launch,
            )
            spec.start()
        alloc = make_allocation_manager(cfg, sched)
        self._warm_hot_path(apply=sync_apply, sync=True)
        start_wall = time.monotonic()
        snapshots: List[Tuple[float, jax.Array]] = [(0.0, w)]

        def now_ms():
            return (time.monotonic() - start_wall) * 1e3

        rounds = 0
        flops = 0.0
        run_ok = False
        try:
            for k in range(cfg.num_iterations):
                cohort = list(range(nw))
                ts = ctx.get_current_time()
                ctx.mark_busy(cohort)
                waiting.on_submit(cohort, now_ms())
                with hot_lock:
                    captured = {
                        wid: (worker_keys[wid], alpha[wid]) for wid in cohort
                    }
                fns = {
                    wid: self._make_task(
                        wid, w, captured[wid][0], captured[wid][1], delay_model
                    )
                    for wid in cohort
                }
                inst.on_round_submitted(k, cohort, model_version=k)
                waiter = sched.run_job(
                    fns, self._handler(ctx, ts, now_ms, worker_keys, hot_lock)
                )
                acc = None
                reported = set()
                for _ in range(nw):
                    res = self._collect_checked(
                        ctx, waiter, cfg.run_timeout_s,
                        pool=sched.pool, cohort=cohort, collected=reported,
                    )
                    reported.add(res.worker_id)
                    g = res.data[0]
                    flops += self._task_flops(res.worker_id)
                    task_ms = waiting.on_finish(res.worker_id, now_ms())
                    calibrator.record(k, task_ms)
                    inst.on_gradient_merged(
                        res.worker_id, res.staleness, True, k,
                        batch_size=res.batch_size, task_ms=task_ms,
                    )
                    with hot_lock:
                        alpha_cur = alpha[res.worker_id]
                        # a shard re-homed mid-round leaves this result's
                        # payload on the old device; commit on the slice's
                        # current home.  The sync drain's commit needs only
                        # diff/idx/valid -- never transfer the (cap, K)
                        # c_sel/v_sel arrays it would just discard.
                        home = alpha_cur.device
                        needed = (
                            res.data[1:4] if self._sparse else res.data[1:]
                        )
                        payload = tuple(
                            jax.device_put(a, home) if a.device != home
                            else a
                            for a in needed
                        )
                        if self._sparse:
                            diff, idx, valid = payload
                            alpha[res.worker_id] = self._commit(
                                alpha_cur, diff, idx, valid
                            )
                        else:
                            diff, mask = payload
                            alpha[res.worker_id] = steps.saga_commit_history(
                                alpha_cur, diff, mask
                            )
                    if g.device != self.driver_device:
                        g = jax.device_put(g, self.driver_device)
                    acc = g if acc is None else steps.add_grads(acc, g)
                # sync drain has no dispatch overlap: table delta == g
                w, alpha_bar = sync_apply(w, alpha_bar, acc, acc)
                rounds += 1
                if k % cfg.printer_freq == 0:
                    snapshots.append((now_ms(), w))
                if calibrator.maybe_finalize(k):
                    delay_model.calibrate(calibrator.avg_delay_ms)
            run_ok = True
        finally:
            if ft is not None:
                ft.stop()
            if spec is not None:
                spec.stop()
            if alloc is not None:
                alloc.stop()
            sched.shutdown()
            if not run_ok:
                inst.close()  # crash path: flush/seal the event log now

        final_w = np.asarray(w)  # fence: see the async path's comment
        elapsed = time.monotonic() - start_wall
        snapshots.append((elapsed * 1e3, w))
        traj = self._evaluate_trajectory(snapshots)
        extras = inst.extras()
        if spec is not None:
            extras["speculated"] = spec.speculated_count()
            extras["speculation_wins"] = sched.speculative_wins()
        if alloc is not None:
            extras["executors_added"], extras["executors_removed"] = (
                alloc.counts()
            )
        inst.close(traj, cfg.printer_freq)
        return TrainResult(
            final_w=final_w,
            trajectory=traj,
            elapsed_s=elapsed,
            accepted=rounds * nw,
            rounds=rounds,
            max_staleness=ctx.max_staleness(),
            avg_delay_ms=calibrator.avg_delay_ms,
            updates_per_sec=rounds / elapsed if elapsed > 0 else 0.0,
            total_flops=flops,
            waiting_time_ms=waiting.snapshot(),
            extras=extras,
        )

    # ---------------------------------------------------------------- helpers
    def _shard_device(self, wid: int):
        return self.devices[wid % len(self.devices)]

    def _warm_hot_path(self, apply=None, sync: bool = False) -> None:
        """Compile this mode's hot-path executables before the trajectory
        clock starts (reference parity: the always-blocking first iteration,
        ``DAGScheduler.scala:641-656`` -- without this the first accepted
        gradient pays ~1 s of XLA compile inside the timed region on a real
        chip).

        jit caches per input SHAPE, so every distinct (shard shape, history
        slice size) pair is warmed -- shards differ by one row/sample when
        ``n % num_workers != 0``.  The async accept path uses the table
        delta; the sync drain instead accumulates with ``add_grads`` and
        passes ``acc`` as both g and delta -- each mode warms only what it
        runs.  Dummies are fresh buffers, so donated arguments never touch
        live state."""
        apply = apply if apply is not None else self._apply
        d = self.ds.d
        drv = self.driver_device
        g = delta = None
        seen = set()
        for wid in range(self.cfg.num_workers):
            shard = self._recovery.shard(wid)
            dev = shard.device
            # key on (shape, size, device): jit executables are cached per
            # device commitment, so equal-shaped shards on different chips
            # each need their own warm compile
            shape_key = (
                (shard.cols.shape if self._sparse else shard.X.shape),
                shard.size,
                dev,
            )
            if shape_key in seen:
                continue
            seen.add(shape_key)
            w0 = jax.device_put(jnp.zeros(d, jnp.float32), dev)
            a0 = jax.device_put(jnp.zeros(shard.size, jnp.float32), dev)
            key = jax.device_put(jax.random.PRNGKey(0), dev)
            if self._sparse:
                g, diff, idx, valid, c_sel, v_sel, _ = self._step(
                    shard.cols, shard.vals, shard.y, w0, a0, key
                )
                if not sync:
                    delta = self._table_delta(c_sel, v_sel, diff, a0, idx)
                self._commit(a0, diff, idx, valid)
            else:
                g, diff, mask, _ = self._step(shard.X, shard.y, w0, a0, key)
                if not sync:
                    delta = self._table_delta(shard.X, diff, mask, a0)
                steps.saga_commit_history(a0, diff, mask)
        if g.device != drv:
            g = jax.device_put(g, drv)
        wd = jax.device_put(jnp.zeros(d, jnp.float32), drv)
        ab = jax.device_put(jnp.zeros(d, jnp.float32), drv)
        if sync:
            acc = jax.device_put(jnp.zeros(d, jnp.float32), drv)
            acc = steps.add_grads(acc, g)
            wd, ab = apply(wd, ab, acc, acc)
        else:
            if delta.device != drv:
                delta = jax.device_put(delta, drv)
            wd, ab = apply(wd, ab, g, delta)
        wd.block_until_ready()

    def _make_task(self, wid, w_pub, key, alpha_slice, delay_model: DelayModel):
        shard = self._recovery.shard(wid)  # follows re-homed shards
        delay_ms = delay_model.delay_ms(wid)
        dev = shard.device
        step = self._step
        sparse = self._sparse
        # injected delay fires once: a speculative copy / replacement
        # executor is a healthy host path and bypasses the straggler
        delay_fired = threading.Event()

        def fn():
            if delay_ms > 0 and not delay_fired.is_set():
                delay_fired.set()
                time.sleep(delay_ms / 1e3)
            w_local = w_pub
            if w_local.device != dev:
                w_local = jax.device_put(w_local, dev)
            # a slice/key captured around a concurrent shard re-home may
            # still live on the old device; normalize onto the shard's home
            a_local = alpha_slice
            if a_local.device != dev:
                a_local = jax.device_put(a_local, dev)
            key_local = key
            if key_local.device != dev:
                key_local = jax.device_put(key_local, dev)
            if sparse:
                out = step(
                    shard.cols, shard.vals, shard.y, w_local, a_local, key_local
                )
            else:
                out = step(shard.X, shard.y, w_local, a_local, key_local)
            out[0].block_until_ready()
            # (g, ...payload..., new_key) -- the payload arity differs
            # between the dense (diff, mask) and compacted sparse
            # (diff_sel, idx, valid, c_sel, v_sel) steps
            return out

        return fn

    def _collect_checked(self, ctx: AsyncContext, waiter, timeout_s: float,
                         pool=None, cohort=None, collected=None):
        """Shared fail-fast drain (solvers/base.py): surfaces job aborts,
        and -- given the pool -- aborts promptly with the per-worker
        liveness diagnostic when a cohort executor dies unreplaced,
        instead of hanging for the full run timeout."""
        grace = (
            4.0 * self.cfg.heartbeat_interval_s + 2.0
            if self.cfg.heartbeat else 0.5
        )
        return collect_checked(
            ctx, waiter, timeout_s, pool=pool, cohort=cohort,
            dead_grace_s=grace, collected=collected,
        )

    def _handler(
        self, ctx: AsyncContext, submit_clock: int, now_ms, worker_keys, key_lock
    ):
        submit_wall = now_ms()
        par_recs = int(self.cfg.batch_rate * self.ds.n / self.cfg.num_workers)

        def handler(wid: int, result):
            *data, new_key = result
            # advance the key slot before merge_result marks the worker
            # available (see ASGD._handler for why)
            with key_lock:
                worker_keys[wid] = new_key
            ctx.merge_result(
                wid,
                tuple(data),
                submit_clock=submit_clock,
                elapsed_ms=now_ms() - submit_wall,
                batch_size=par_recs,
            )

        return handler

    def _evaluate_trajectory(self, snapshots):
        W = jnp.stack([h for (_t, h) in snapshots])
        totals = np.zeros(len(snapshots), np.float64)
        for wid in range(self.cfg.num_workers):
            shard = self._recovery.shard(wid)  # follows re-homed shards
            Wd = W
            if Wd.device != shard.device:
                Wd = jax.device_put(W, shard.device)
            if self._sparse:
                part = self._eval(shard.cols, shard.vals, shard.y, Wd)
            else:
                part = self._eval(shard.X, shard.y, Wd)
            totals += np.asarray(part, np.float64)
        totals /= self.ds.n
        traj = [(t, float(l)) for (t, _), l in zip(snapshots, totals)]
        # continuous telemetry: fold the run's loss-vs-wallclock curve
        # into the process-global convergence history (see asgd.py)
        from asyncframework_tpu.metrics import timeseries as _ts

        _ts.fold_trajectory(traj)
        return traj
