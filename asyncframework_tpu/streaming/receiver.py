"""Input receivers: push-based stream sources.

Parity: ``streaming/.../receiver/Receiver.scala`` + ``scheduler/
ReceiverTracker.scala:105`` -- a receiver is a long-running component that
ingests external data and ``store()``s blocks, which the batch interval then
slices into per-interval batches; ``socketTextStream`` is the reference's
canonical example receiver.

TPU re-design: a receiver is a daemon thread feeding a buffered
:class:`ReceiverStream` (one buffer drain per interval -- the block
generator's role); reliability rides the existing WAL (pass ``wal=`` and
every drained batch is persisted before processing, the
write-ahead-log-enabled receiver mode).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, List, Optional

from asyncframework_tpu.streaming.dstream import DStream, EMPTY


class ReceiverStream(DStream):
    """Base input stream fed by a background receiver thread.

    Subclasses (or callers via :meth:`store`) push elements; each interval's
    ``compute`` drains everything buffered since the previous interval into
    one batch (list of elements), or EMPTY when nothing arrived.

    Backpressure (``PIDRateEstimator.scala:48`` + bounded block-generator
    buffer): ``max_buffer`` bounds the in-flight element count -- a producer
    faster than the consumer then either *blocks* in :meth:`store` (default;
    TCP pushback for socket sources) or *drops* (``overflow="drop"``).
    ``backpressure=True`` additionally runs a PID estimator over completed
    batches and ramps the admitted ingest rate to what the pipeline
    sustains; ``max_rate`` seeds/caps it (``spark.streaming.receiver.
    maxRate`` analog).  All control is host-side; :meth:`store` never
    deadlocks on shutdown (it polls ``stopped``).
    """

    def __init__(self, ssc, wal=None, max_buffer: Optional[int] = None,
                 overflow: str = "block", backpressure: Optional[bool] = None,
                 max_rate: Optional[float] = None):
        super().__init__(ssc)
        if overflow not in ("block", "drop"):
            raise ValueError(f"overflow must be 'block' or 'drop', got {overflow!r}")
        # unset kwargs fall back to the registered config entries (set via
        # --conf overlays installed as the global conf, or ASYNCTPU_* env
        # -- the spark.streaming.* analogs)
        from asyncframework_tpu import conf as _conf

        _c = _conf.global_conf()
        if max_buffer is None:
            max_buffer = _c.get(_conf.RECEIVER_MAX_BUFFER) or None
        if max_rate is None:
            max_rate = _c.get(_conf.RECEIVER_MAX_RATE) or None
        if backpressure is None:
            backpressure = bool(_c.get(_conf.BACKPRESSURE))
        self._buf: List[Any] = []
        self._buf_lock = threading.Condition()
        self._wal = wal
        self._started = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._max_buffer = max_buffer
        self._overflow = overflow
        self.dropped = 0          # elements rejected by buffer/rate policy
        self.peak_buffer = 0      # high-water mark (test/metrics hook)
        self._last_drain = 0      # size of the most recent drained batch
        from asyncframework_tpu.streaming.rate import (
            PIDRateEstimator,
            RateLimiter,
        )

        self._limiter = RateLimiter(max_rate)
        self._estimator = (
            PIDRateEstimator(ssc.batch_interval_ms, min_rate=10.0)
            if backpressure
            else None
        )
        self._max_rate = max_rate
        ssc._register_receiver(self)

    # ------------------------------------------------------------- receiver
    def store(self, element: Any) -> bool:
        """Called by the receiver thread for each ingested element.

        Returns False when the element was NOT admitted (dropped, or the
        receiver stopped while blocked) -- reliable sources use this to
        hold their ack.
        """
        if self._overflow == "drop":
            if not self._limiter.try_acquire():
                self.dropped += 1
                return False
        elif not self._limiter.acquire(stop_check=self._stop.is_set):
            return False  # stopped while blocked on the rate
        with self._buf_lock:
            while (
                self._max_buffer is not None
                and len(self._buf) >= self._max_buffer
            ):
                if self._overflow == "drop":
                    self.dropped += 1
                    return False
                if self._stop.is_set():
                    return False
                self._buf_lock.wait(timeout=0.05)
            self._buf.append(element)
            self.peak_buffer = max(self.peak_buffer, len(self._buf))
        return True

    # ------------------------------------------------------- rate feedback
    def on_batch_completed(
        self,
        time_ms: float,
        processing_delay_ms: float,
        scheduling_delay_ms: float,
    ) -> None:
        """Fed by the job generator after each interval; updates the
        admitted ingest rate from the PID estimate (capped at max_rate)."""
        if self._estimator is None:
            return
        rate = self._estimator.compute(
            time_ms, self._last_drain, processing_delay_ms,
            scheduling_delay_ms,
        )
        if rate is not None:
            if self._max_rate is not None:
                rate = min(rate, self._max_rate)
            self._limiter.set_rate(rate)

    @property
    def current_rate(self) -> Optional[float]:
        return self._limiter.rate

    def on_start(self) -> None:  # pragma: no cover - subclass hook
        """Receiver body; runs on the receiver thread until ``stopped``."""

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self.on_start, name=type(self).__name__, daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # --------------------------------------------------------------- stream
    def compute(self, time_ms: int) -> Any:
        with self._buf_lock:
            if not self._buf:
                self._last_drain = 0
                return EMPTY
            batch, self._buf = self._buf, []
            self._last_drain = len(batch)
            self._buf_lock.notify_all()  # blocked producers may proceed
        if self._wal is not None:
            self._wal.append(time_ms, batch)
        return batch


class TextFileStream(ReceiverStream):
    """``ssc.textFileStream(dir)`` analog: watch a directory; each interval's
    batch is the lines of files that APPEARED since the last interval.

    Parity: ``streaming/.../dstream/FileInputDStream.scala`` -- files are
    selected by presence (new path not seen before), read once, and never
    re-read on modification (the reference's rename-into-place contract:
    writers must move complete files in).  Hidden/partial conventions
    honored: names starting with ``.`` or ending in ``.tmp`` are ignored.
    """

    def __init__(self, ssc, directory, wal=None):
        # a polled source, not a push receiver: the buffer/rate-limit
        # machinery does not apply (compute() reads the filesystem
        # directly), so those kwargs are deliberately not accepted
        super().__init__(ssc, wal=wal)
        import os

        self.directory = str(directory)
        self._seen: set = set()
        # files already present at stream construction belong to the past
        # (FileInputDStream ignores pre-existing files by mod-time window;
        # presence-at-start is the equivalent contract here)
        if os.path.isdir(self.directory):
            self._seen.update(os.listdir(self.directory))

    def compute(self, time_ms: int) -> Any:
        import os

        batch: List[Any] = []
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            # directory missing/replaced/forbidden this interval: an empty
            # batch, never a dead job-generator thread
            names = []
        for name in names:
            if name in self._seen:
                continue
            if name.startswith(".") or name.endswith(".tmp"):
                continue
            path = os.path.join(self.directory, name)
            if not os.path.isfile(path):
                continue
            try:
                # utf-8 with replacement, like SocketTextStream: a stray
                # undecodable byte must not kill the stream
                with open(path, encoding="utf-8", errors="replace") as f:
                    lines = [line.rstrip("\n") for line in f]
            except OSError:
                continue  # transient (perms/NFS): retried next interval
            # mark seen only AFTER a successful read -- a transient open
            # failure must not permanently drop the file's data
            self._seen.add(name)
            batch.extend(lines)
        # remember-window analog: names no longer present cannot recur
        # except as NEW files (the rename-into-place contract), so prune
        # them -- _seen stays bounded by the directory's live population
        self._seen.intersection_update(names)
        if not batch:
            return EMPTY
        if self._wal is not None:
            self._wal.append(time_ms, batch)
        return batch


class SocketTextStream(ReceiverStream):
    """``ssc.socketTextStream(host, port)`` analog: newline-delimited UTF-8
    lines from a TCP connection; each interval's batch is the list of lines
    received during it.  Reconnects are the caller's concern (parity with
    the reference's restart-on-error receiver supervisor is scoped to one
    connection here)."""

    def __init__(self, ssc, host: str, port: int, wal=None,
                 connect_timeout: float = 10.0):
        super().__init__(ssc, wal=wal)
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout

    def on_start(self) -> None:
        with socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout
        ) as sock:
            sock.settimeout(0.2)  # poll the stop flag between reads
            pending = b""
            while not self.stopped:
                try:
                    chunk = sock.recv(4096)
                except socket.timeout:
                    continue
                if not chunk:
                    return  # peer closed
                pending += chunk
                while b"\n" in pending:
                    line, pending = pending.split(b"\n", 1)
                    self.store(line.decode("utf-8", "replace"))
