"""Input receivers: push-based stream sources.

Parity: ``streaming/.../receiver/Receiver.scala`` + ``scheduler/
ReceiverTracker.scala:105`` -- a receiver is a long-running component that
ingests external data and ``store()``s blocks, which the batch interval then
slices into per-interval batches; ``socketTextStream`` is the reference's
canonical example receiver.

TPU re-design: a receiver is a daemon thread feeding a buffered
:class:`ReceiverStream` (one buffer drain per interval -- the block
generator's role); reliability rides the existing WAL (pass ``wal=`` and
every drained batch is persisted before processing, the
write-ahead-log-enabled receiver mode).
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, List, Optional

from asyncframework_tpu.streaming.dstream import DStream, EMPTY


class ReceiverStream(DStream):
    """Base input stream fed by a background receiver thread.

    Subclasses (or callers via :meth:`store`) push elements; each interval's
    ``compute`` drains everything buffered since the previous interval into
    one batch (list of elements), or EMPTY when nothing arrived.
    """

    def __init__(self, ssc, wal=None):
        super().__init__(ssc)
        self._buf: List[Any] = []
        self._buf_lock = threading.Lock()
        self._wal = wal
        self._started = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- receiver
    def store(self, element: Any) -> None:
        """Called by the receiver thread for each ingested element."""
        with self._buf_lock:
            self._buf.append(element)

    def on_start(self) -> None:  # pragma: no cover - subclass hook
        """Receiver body; runs on the receiver thread until ``stopped``."""

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self.on_start, name=type(self).__name__, daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # --------------------------------------------------------------- stream
    def compute(self, time_ms: int) -> Any:
        with self._buf_lock:
            if not self._buf:
                return EMPTY
            batch, self._buf = self._buf, []
        if self._wal is not None:
            self._wal.append(time_ms, batch)
        return batch


class SocketTextStream(ReceiverStream):
    """``ssc.socketTextStream(host, port)`` analog: newline-delimited UTF-8
    lines from a TCP connection; each interval's batch is the list of lines
    received during it.  Reconnects are the caller's concern (parity with
    the reference's restart-on-error receiver supervisor is scoped to one
    connection here)."""

    def __init__(self, ssc, host: str, port: int, wal=None,
                 connect_timeout: float = 10.0):
        super().__init__(ssc, wal=wal)
        self.host = host
        self.port = port
        self.connect_timeout = connect_timeout

    def on_start(self) -> None:
        with socket.create_connection(
            (self.host, self.port), timeout=self.connect_timeout
        ) as sock:
            sock.settimeout(0.2)  # poll the stop flag between reads
            pending = b""
            while not self.stopped:
                try:
                    chunk = sock.recv(4096)
                except socket.timeout:
                    continue
                if not chunk:
                    return  # peer closed
                pending += chunk
                while b"\n" in pending:
                    line, pending = pending.split(b"\n", 1)
                    self.store(line.decode("utf-8", "replace"))
