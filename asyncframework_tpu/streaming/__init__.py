from asyncframework_tpu.streaming.dstream import DStream
from asyncframework_tpu.streaming.context import StreamingContext
from asyncframework_tpu.streaming.receiver import (
    ReceiverStream,
    SocketTextStream,
    TextFileStream,
)
from asyncframework_tpu.streaming.log import DirectLogStream, LogTopic
from asyncframework_tpu.streaming.log_net import LogTopicServer, RemoteLogTopic
from asyncframework_tpu.streaming.wal import WriteAheadLog

__all__ = [
    "DStream", "StreamingContext", "ReceiverStream", "SocketTextStream",
    "TextFileStream",
    "WriteAheadLog", "LogTopic", "DirectLogStream",
    "LogTopicServer", "RemoteLogTopic",
]
