"""Discretized streams: a lazy per-batch transform graph.

Parity: ``streaming/.../dstream/DStream.scala:62`` -- a DStream is a graph of
per-interval computations over parent streams; transformations are lazy,
output operations (``foreachRDD``/``print``) register the stream with the
context; windowing re-uses parent batches across overlapping windows.

TPU re-design: a "batch" here is an array (numpy or jax) or any Python
value; ``map_batch`` functions are typically jitted XLA callables so the
per-interval work is one device dispatch (the reference's per-batch Spark
job).  Structural simplifications: generation is pull-based with per-time
memoization (the reference's ``getOrCompute`` cache) driven by the context's
job generator; there is no lineage/persistence tier because batches are
either consumed in-interval or retained by an explicit window.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

EMPTY = object()  # sentinel: no batch this interval


class DStream:
    """One node in the stream graph.  Subclasses define :meth:`compute`."""

    def __init__(self, ssc, parents: Optional[List["DStream"]] = None):
        self.ssc = ssc
        self.parents = parents or []
        self._cache: "OrderedDict[int, Any]" = OrderedDict()
        self._cache_keep = 1  # raised by windowed children
        self._lock = threading.Lock()

    # ------------------------------------------------------------- generation
    def compute(self, time_ms: int) -> Any:
        raise NotImplementedError

    def get_or_compute(self, time_ms: int) -> Any:
        """Per-interval memoized compute (``DStream.getOrCompute`` parity);
        lets overlapping windows share one evaluation of the parent.

        The lock is held ACROSS compute: check-then-compute without it would
        let two threads evaluate the same interval twice (a QueueStream
        source would pop two batches for one tick).  Safe because the stream
        graph is a DAG and each node locks only itself while recursing into
        parents.
        """
        with self._lock:
            if time_ms in self._cache:
                return self._cache[time_ms]
            value = self.compute(time_ms)
            self._cache[time_ms] = value
            while len(self._cache) > self._cache_keep:
                self._cache.popitem(last=False)
            return value

    def _retain(self, n: int) -> None:
        """A child needs the last ``n`` intervals of this stream."""
        self._cache_keep = max(self._cache_keep, n)

    # ---------------------------------------------------------- transformations
    def map_batch(self, fn: Callable[[Any], Any]) -> "DStream":
        """Apply ``fn`` to each interval's batch (jit-friendly: one call per
        interval, not per element)."""
        return _Transformed(self.ssc, self, lambda t, b: fn(b))

    def transform(self, fn: Callable[[int, Any], Any]) -> "DStream":
        """Like :meth:`map_batch` with the batch time as first argument."""
        return _Transformed(self.ssc, self, fn)

    def filter_batch(self, pred: Callable[[Any], bool]) -> "DStream":
        """Drop intervals whose batch fails ``pred``."""
        return _Transformed(
            self.ssc, self, lambda t, b: b if pred(b) else EMPTY
        )

    def window(self, length: int, slide: int = 1) -> "DStream":
        """Concatenate the batches of the last ``length`` intervals, emitted
        every ``slide`` intervals (counted in batch intervals, like the
        reference's duration-multiples)."""
        return _Windowed(self.ssc, self, length, slide)

    def reduce_by_window(
        self, fn: Callable[[Any, Any], Any], length: int, slide: int = 1
    ) -> "DStream":
        win = self.window(length, slide)
        def red(t, batches):
            if batches is EMPTY or not batches:
                return EMPTY
            acc = batches[0]
            for b in batches[1:]:
                acc = fn(acc, b)
            return acc
        return _Transformed(self.ssc, win, red)

    def count(self) -> "DStream":
        def cnt(t, b):
            if b is EMPTY:
                return 0
            try:
                return len(b)
            except TypeError:
                return 1
        return _Transformed(self.ssc, self, cnt)

    def union(self, other: "DStream") -> "DStream":
        return _Union(self.ssc, [self, other])

    def reduce_by_key_batch(
        self, fn: Callable[[Any, Any], Any]
    ) -> "DStream":
        """Per-interval keyed reduce over a batch of (key, value) pairs
        (``PairDStreamFunctions.reduceByKey`` parity)."""

        def red(_t, b):
            acc: Dict[Any, Any] = {}
            for k, v in b:
                acc[k] = fn(acc[k], v) if k in acc else v
            return list(acc.items())

        return _Transformed(self.ssc, self, red)

    def reduce_by_key_and_window(
        self,
        fn: Callable[[Any, Any], Any],
        length: int,
        slide: int = 1,
        inv_fn: Optional[Callable[[Any, Any], Any]] = None,
        filter_fn: Optional[Callable[[Any, Any], bool]] = None,
    ) -> "DStream":
        """Keyed reduce over the last ``length`` intervals, every ``slide``.

        Parity: ``PairDStreamFunctions.reduceByKeyAndWindow`` -- without
        ``inv_fn`` each emission recombines the window's per-interval
        partials; with ``inv_fn`` the previous window result is updated
        incrementally (add entering intervals, invert leaving ones), the
        reference's O(slide) formulation.  ``filter_fn(key, value)`` prunes
        keys (the reference's filterFunc; without it, inverse-mode keys
        linger at their neutral value, exactly like Spark).
        """
        per = self.reduce_by_key_batch(fn)
        if inv_fn is None:
            win = per.window(length, slide)

            def combine(_t, batches):
                acc: Dict[Any, Any] = {}
                for b in batches:
                    for k, v in b:
                        acc[k] = fn(acc[k], v) if k in acc else v
                out = list(acc.items())
                if filter_fn is not None:
                    out = [(k, v) for k, v in out if filter_fn(k, v)]
                return out if out else EMPTY

            return _Transformed(self.ssc, win, combine)
        return _InvWindowReduce(
            self.ssc, per, fn, inv_fn, length, slide, filter_fn
        )

    def join(self, other: "DStream") -> "DStream":
        """Per-interval inner join of two keyed streams
        (``PairDStreamFunctions.join`` parity): emits ``(k, (v, w))`` for
        every pairing of the interval's left and right values of ``k``."""
        return _BinaryKeyed(self.ssc, self, other, how="inner")

    def left_outer_join(self, other: "DStream") -> "DStream":
        """``leftOuterJoin`` parity: unmatched left keys emit
        ``(k, (v, None))``."""
        return _BinaryKeyed(self.ssc, self, other, how="left")

    def cogroup(self, other: "DStream") -> "DStream":
        """``cogroup`` parity: ``(k, ([left values], [right values]))`` for
        every key present on either side this interval."""
        return _BinaryKeyed(self.ssc, self, other, how="cogroup")

    def update_state_by_key(
        self,
        update_fn: Callable[[List[Any], Optional[Any]], Optional[Any]],
    ) -> "StatefulDStream":
        """Keyed running state across intervals.

        Parity: ``streaming/.../dstream/PairDStreamFunctions.scala``
        ``updateStateByKey`` -- batches are iterables of ``(key, value)``
        pairs; every interval, ``update_fn(new_values, prev_state)`` runs for
        EVERY key that has new values or existing state (the reference's
        cogroup-with-state semantics); returning ``None`` drops the key.  The
        emitted batch is the full ``[(key, state), ...]`` snapshot.
        """
        return StatefulDStream(self.ssc, self, update_fn)

    # ---------------------------------------------------------------- outputs
    def foreach_batch(self, fn: Callable[[int, Any], None]) -> "DStream":
        """Register an output operation (``foreachRDD`` parity): ``fn(time_ms,
        batch)`` runs for every non-empty interval.  Returns self."""
        self.ssc._register_output(self, fn)
        return self


class _Transformed(DStream):
    def __init__(self, ssc, parent: DStream, fn: Callable[[int, Any], Any]):
        super().__init__(ssc, [parent])
        self._fn = fn

    def compute(self, time_ms: int) -> Any:
        b = self.parents[0].get_or_compute(time_ms)
        if b is EMPTY:
            return EMPTY
        return self._fn(time_ms, b)


class _Windowed(DStream):
    """Emits the list of the last ``length`` non-empty parent batches."""

    def __init__(self, ssc, parent: DStream, length: int, slide: int):
        if length < 1 or slide < 1:
            raise ValueError("window length and slide must be >= 1")
        super().__init__(ssc, [parent])
        self.length = length
        self.slide = slide
        parent._retain(length)

    def compute(self, time_ms: int) -> Any:
        interval = self.ssc.batch_interval_ms
        idx = time_ms // interval
        if idx % self.slide != 0:
            return EMPTY
        batches = []
        for i in range(self.length - 1, -1, -1):
            t = time_ms - i * interval
            if t <= 0:
                continue  # before the first interval (generation is 1-based)
            b = self.parents[0].get_or_compute(t)
            if b is not EMPTY:
                batches.append(b)
        return batches if batches else EMPTY


class _BinaryKeyed(DStream):
    """Two-parent keyed combine: join / left_outer_join / cogroup.

    Both parents' interval batches are iterables of (key, value) pairs; a
    missing batch on one side is an empty side (EMPTY only when both
    parents are silent, so a left join still emits for a silent right).
    """

    def __init__(self, ssc, left: DStream, right: DStream, how: str):
        super().__init__(ssc, [left, right])
        self._how = how

    def compute(self, time_ms: int) -> Any:
        lb = self.parents[0].get_or_compute(time_ms)
        rb = self.parents[1].get_or_compute(time_ms)
        if lb is EMPTY and rb is EMPTY:
            return EMPTY
        lgroups: Dict[Any, List[Any]] = {}
        rgroups: Dict[Any, List[Any]] = {}
        for groups, batch in ((lgroups, lb), (rgroups, rb)):
            if batch is EMPTY:
                continue
            for k, v in batch:
                groups.setdefault(k, []).append(v)
        out: List[Tuple[Any, Any]] = []
        if self._how == "cogroup":
            for k in {**lgroups, **rgroups}:
                out.append((k, (lgroups.get(k, []), rgroups.get(k, []))))
        elif self._how == "inner":
            for k, lvs in lgroups.items():
                for lv in lvs:
                    for rv in rgroups.get(k, []):
                        out.append((k, (lv, rv)))
        else:  # left
            for k, lvs in lgroups.items():
                rvs = rgroups.get(k)
                for lv in lvs:
                    if rvs:
                        out.extend((k, (lv, rv)) for rv in rvs)
                    else:
                        out.append((k, (lv, None)))
        return out if out else EMPTY


class _Union(DStream):
    def __init__(self, ssc, parents: List[DStream]):
        super().__init__(ssc, parents)

    def compute(self, time_ms: int) -> Any:
        out = []
        for p in self.parents:
            b = p.get_or_compute(time_ms)
            if b is not EMPTY:
                out.append(b)
        if not out:
            return EMPTY
        return out[0] if len(out) == 1 else _concat(out)


def _concat(batches: List[Any]) -> Any:
    """Concatenate heterogeneous batches: arrays stack, lists extend."""
    first = batches[0]
    if hasattr(first, "shape"):
        import numpy as np

        return np.concatenate([np.asarray(b) for b in batches])
    out: List[Any] = []
    for b in batches:
        out.extend(b)
    return out


class _InvWindowReduce(DStream):
    """Incremental windowed keyed reduce (the ``invReduceFunc`` path).

    Carries the previous window's keyed result; each slide adds the
    entering intervals' partials with ``fn`` and removes the leaving ones
    with ``inv_fn``.  The parent (per-interval partials) retains enough
    intervals for both edges of the window.
    """

    def __init__(self, ssc, parent, fn, inv_fn, length, slide, filter_fn):
        if length < 1 or slide < 1:
            raise ValueError("window length and slide must be >= 1")
        super().__init__(ssc, [parent])
        self._fn = fn
        self._inv = inv_fn
        self._filter = filter_fn
        self.length = length
        self.slide = slide
        parent._retain(length + slide)
        self._state: Dict[Any, Any] = {}
        self._state_time = 0

    def _fold(self, acc, t, invert: bool) -> None:
        b = self.parents[0].get_or_compute(t)
        if b is EMPTY:
            return
        for k, v in b:
            if invert:
                acc[k] = self._inv(acc[k], v)  # key must exist: it entered
            else:
                acc[k] = self._fn(acc[k], v) if k in acc else v

    def _window_keys(self, time_ms: int, interval: int) -> set:
        """Keys present in any interval of the window ending at time_ms --
        the only keys a FUTURE leaving interval can invert."""
        keys = set()
        for t in range(
            max(time_ms - (self.length - 1) * interval, interval),
            time_ms + 1,
            interval,
        ):
            b = self.parents[0].get_or_compute(t)
            if b is not EMPTY:
                keys.update(k for k, _v in b)
        return keys

    def _recompute(self, time_ms: int) -> Any:
        """Full recombination of one (possibly past) window -- the stale
        re-read path must not leak the CURRENT state under an old label."""
        interval = self.ssc.batch_interval_ms
        acc: Dict[Any, Any] = {}
        for t in range(
            max(time_ms - (self.length - 1) * interval, interval),
            time_ms + 1,
            interval,
        ):
            self._fold(acc, t, invert=False)
        out = list(acc.items())
        if self._filter is not None:
            out = [(k, v) for k, v in out if self._filter(k, v)]
        return out if out else EMPTY

    def compute(self, time_ms: int) -> Any:
        interval = self.ssc.batch_interval_ms
        idx = time_ms // interval
        if idx % self.slide != 0:
            return EMPTY
        if time_ms <= self._state_time:
            # re-read of a past window (cache miss): recompute that window
            # rather than mislabel the current state
            return self._recompute(time_ms)
        acc = dict(self._state)
        # entering intervals: those in the new window, after the old one
        enter_from = max(
            time_ms - (self.length - 1) * interval,
            self._state_time + interval if self._state_time else interval,
        )
        for t in range(enter_from, time_ms + 1, interval):
            self._fold(acc, t, invert=False)
        # leaving intervals: in the old window, before the new one
        if self._state_time:
            old_start = self._state_time - (self.length - 1) * interval
            new_start = time_ms - (self.length - 1) * interval
            for t in range(max(old_start, interval), min(new_start, self._state_time + interval), interval):
                self._fold(acc, t, invert=True)
        if self._filter is not None:
            # prune carried state too (the reference's filterFunc exists to
            # bound it) -- but only keys no future leaving interval can
            # invert, i.e. keys absent from the current window's partials
            live = self._window_keys(time_ms, interval)
            acc = {
                k: v for k, v in acc.items()
                if k in live or self._filter(k, v)
            }
        self._state = acc
        self._state_time = time_ms
        out = list(acc.items())
        if self._filter is not None:
            out = [(k, v) for k, v in out if self._filter(k, v)]
        return out if out else EMPTY


class StatefulDStream(DStream):
    """``updateStateByKey`` node: per-key state carried across intervals.

    State advances exactly once per interval (the context's job generator
    visits intervals in order; ``get_or_compute`` memoization absorbs
    re-reads of the current interval).  ``snapshot_state`` / ``restore``
    expose the state for the streaming checkpoint
    (``streaming/.../Checkpoint.scala:55`` parity via ``checkpoint.py``).
    """

    def __init__(self, ssc, parent: DStream, update_fn):
        super().__init__(ssc, [parent])
        self._update = update_fn
        self._state: Dict[Any, Any] = {}
        self._state_time = 0  # last interval folded into the state
        ssc._register_stateful(self)

    def compute(self, time_ms: int) -> Any:
        if time_ms <= self._state_time:
            # interval predates the restored/advanced state (e.g. WAL replay
            # overlapping a checkpoint): state already includes it
            return [(k, v) for k, v in self._state.items()]
        b = self.parents[0].get_or_compute(time_ms)
        grouped: Dict[Any, List[Any]] = {}
        if b is not EMPTY:
            for k, v in b:
                grouped.setdefault(k, []).append(v)
        # the update runs for every key with new values OR existing state
        next_state: Dict[Any, Any] = {}
        for k in set(grouped) | set(self._state):
            s = self._update(grouped.get(k, []), self._state.get(k))
            if s is not None:
                next_state[k] = s
        self._state = next_state
        self._state_time = time_ms
        return [(k, v) for k, v in next_state.items()]

    # -------------------------------------------------------------- checkpoint
    def snapshot_state(self):
        """(state_time_ms, [(key, state), ...]) for the checkpointer."""
        return self._state_time, list(self._state.items())

    def restore(self, state_time: int, items) -> None:
        """Install checkpointed state.  ``_state_time`` resets to 0: a
        rebuilt context restarts interval numbering, and batches already
        folded into this state are excluded at the source instead
        (``recovered_stream(..., after_ms=state_time)``)."""
        del state_time  # recorded in the checkpoint for the source filter
        self._state = dict(items)
        self._state_time = 0


class QueueStream(DStream):
    """Input stream fed from an in-memory queue of batches (the reference's
    ``queueStream`` test utility, the canonical deterministic source)."""

    def __init__(self, ssc, batches: Optional[List[Any]] = None,
                 wal: Optional["object"] = None):
        super().__init__(ssc)
        self._pending: List[Any] = list(batches or [])
        self._qlock = threading.Lock()
        self._wal = wal

    def push(self, batch: Any) -> None:
        with self._qlock:
            self._pending.append(batch)

    def compute(self, time_ms: int) -> Any:
        with self._qlock:
            if not self._pending:
                return EMPTY
            batch = self._pending.pop(0)
        if self._wal is not None:
            self._wal.append(time_ms, batch)
        return batch
