"""Network-attached LogTopic: broker-less streaming source over the DCN
framing.

Parity (studied, not copied): the reference's modern streaming connector
consumes a REMOTE broker service --
``external/kafka-0-10/.../DirectKafkaInputDStream.scala`` talks the Kafka
wire protocol to fetch offset ranges and commit group offsets.  The TPU
build's :class:`~asyncframework_tpu.streaming.log.LogTopic` already gives
the direct-stream capability (offset-addressed replayable log,
commit-after-output) but only same-filesystem; this module serves it over
the framework's OWN length-prefixed TCP framing (the same channel the
parameter server and the deploy daemons use -- ``parallel/ps_dcn.py``), so
producers and consumers run on other hosts with no external broker
dependency:

- :class:`LogTopicServer` -- one process owning the on-disk topics (the
  single-writer-per-partition discipline the file-backed class documents
  becomes a *server guarantee*); serves APPEND / READ / END / COMMIT /
  COMMITTED over TCP, one handler thread per connection.
- :class:`RemoteLogTopic` -- a client with the LogTopic consumer/producer
  surface (``read``/``end_offset``/``append_many``/``commit_offset``/
  ``committed_offset``), so :class:`DirectLogStream` drives it unchanged:
  offsets commit server-side strictly after outputs, and a restarted
  consumer (even in a new process) replays from the server's last commit.

Record payloads remain JSON -- replay never executes code (the WAL's trust
posture), and the wire never carries pickles.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Iterable, List, Optional, Tuple

from asyncframework_tpu.net import (
    ClientSession,
    DedupWindow,
    RetryError,
    RetryPolicy,
)
from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import protocol as _protocol
from asyncframework_tpu.net.frame import recv_msg as _recv_msg
from asyncframework_tpu.net.frame import send_msg as _send_msg
from asyncframework_tpu.streaming.log import LogTopic
from asyncframework_tpu.utils.threads import guarded

#: ops that mutate server state and therefore ride the (sid, seq) dedup
#: window -- a retried APPEND must never append twice (round-5 ADVICE
#: bug).  Derived from the declared wire-protocol table (net/protocol.py)
#: so the obligation lives in ONE place; bin/async-lint checks this.
_MUTATING_OPS = _protocol.dedup_gated_ops(_protocol.TOPIC)


class LogTopicServer:
    """Serve a directory of :class:`LogTopic` logs over TCP.

    Topics are auto-created on first reference (``<root>/<name>/``).  All
    appends for a topic funnel through this process's single LogTopic
    instance, which serializes them -- remote producers get the
    single-writer discipline for free.
    """

    def __init__(self, root: str, host: str = "0.0.0.0", port: int = 0,
                 segment_bytes: int = 64 * 1024 * 1024):
        self.root = root
        self.segment_bytes = segment_bytes
        self._topics: dict = {}
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.host, self.port = self._srv.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        from asyncframework_tpu.conf import NET_DEDUP_WINDOW, global_conf

        self._dedup = DedupWindow(window=global_conf().get(NET_DEDUP_WINDOW))

    @property
    def dedup_hits(self) -> int:
        """Retried mutating ops answered from cache (each one is a record
        that would have been appended twice before net/session.py)."""
        return self._dedup.hits

    # ------------------------------------------------------------- lifecycle
    def start(self) -> Tuple[str, int]:
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="log-topic-accept", daemon=True
        )
        self._accept_thread.start()
        return self.host, self.port

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def serve_forever(self) -> None:
        self.start()
        while not self._stop.is_set():
            time.sleep(0.2)

    # -------------------------------------------------------------- serving
    def _topic(self, name: str) -> LogTopic:
        if not name or "/" in name or name.startswith("."):
            raise ValueError(f"bad topic name {name!r}")
        with self._lock:
            t = self._topics.get(name)
            if t is None:
                import os

                t = LogTopic(os.path.join(self.root, name),
                             segment_bytes=self.segment_bytes)
                self._topics[name] = t
            return t

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._srv.accept()
            except OSError:
                return  # socket closed by stop()
            threading.Thread(
                target=guarded(self._handle, "log-topic-conn"),
                args=(conn,),
                name="log-topic-conn", daemon=True,
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            while True:
                header, payload = _recv_msg(conn)
                if header.get("op") in _MUTATING_OPS:
                    cached = self._dedup.check(header)
                    if cached is not None:
                        # duplicate of an applied op (reply was lost):
                        # re-send the cached reply, touch no topic
                        _send_msg(conn, cached[0], cached[1])
                        continue
                try:
                    reply, body = self._dispatch(header, payload)
                except Exception as e:  # a bad request must not kill the
                    reply, body = (     # connection, let alone the server
                        {"op": "ERR",
                         "error": f"{type(e).__name__}: {e}"}, b"",
                    )
                if (header.get("op") in _MUTATING_OPS
                        and reply.get("op") != "ERR"):
                    # record BEFORE sending: a reply lost mid-send must
                    # already count as applied for the retry
                    self._dedup.record(header, reply, body)
                _send_msg(conn, reply, body)
        except (ConnectionError, OSError):
            pass  # client went away; its offsets are on disk
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, header: dict, payload: bytes
                  ) -> Tuple[dict, bytes]:
        op = header.get("op")
        if op == "APPEND":
            topic = self._topic(header["topic"])
            values = json.loads(payload.decode("utf-8"))
            first, nxt = topic.append_many(values)
            return {"op": "APPENDED", "first": first, "next": nxt}, b""
        if op == "READ":
            topic = self._topic(header["topic"])
            records, nxt = topic.read(
                int(header["offset"]), header.get("max")
            )
            body = json.dumps(records).encode("utf-8")
            return {"op": "RECORDS", "next": nxt}, body
        if op == "END":
            topic = self._topic(header["topic"])
            return {"op": "END", "end": topic.end_offset()}, b""
        if op == "COMMIT":
            topic = self._topic(header["topic"])
            topic.commit_offset(header["group"], int(header["offset"]))
            return {"op": "COMMITTED", "ok": True}, b""
        if op == "COMMITTED":
            topic = self._topic(header["topic"])
            off = topic.committed_offset(header["group"])
            return {"op": "OFFSET", "offset": off}, b""
        raise ValueError(f"unknown op {op!r}")


class RemoteLogTopic:
    """Client-side LogTopic surface over the topic server's TCP protocol.

    Offers the subset :class:`DirectLogStream` and producers use --
    ``read``/``end_offset``/``append``/``append_many``/``commit_offset``/
    ``committed_offset``.  Transport faults route through the shared
    :class:`~asyncframework_tpu.net.RetryPolicy` (backoff + jitter +
    per-endpoint breaker), and mutating ops carry this client's session
    ``(sid, seq)`` -- the server's dedup window makes a retried APPEND
    exactly-once-applied while the server lives (the round-5
    duplicate-record bug closed structurally).  The window is in-memory:
    a retry that straddles a server RESTART is at-least-once again, the
    same edge the pre-dedup client always had."""

    def __init__(self, host: str, port: int, topic: str,
                 connect_timeout_s: float = 10.0, retries: int = 5,
                 retry: Optional[RetryPolicy] = None,
                 session: Optional[ClientSession] = None):
        self.host, self.port, self.topic = host, int(port), topic
        self.connect_timeout_s = connect_timeout_s
        self.retries = retries
        self.endpoint = f"{host}:{int(port)}"
        # legacy knobs map onto the policy: ``retries`` bounds attempts,
        # ``connect_timeout_s`` bounds the overall deadline (the old
        # _connect loop's deadline role)
        self.retry = retry if retry is not None else RetryPolicy.from_conf(
            max_attempts=max(1, int(retries)),
            deadline_s=float(connect_timeout_s) + 60.0,
            attempt_timeout_s=60.0,
        )
        self.session = session if session is not None else ClientSession()
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------- transport
    def _drop_sock(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, header: dict, payload: bytes = b""
              ) -> Tuple[dict, bytes]:
        if header.get("op") in _MUTATING_OPS:
            # stamp once per logical op; every retry re-sends this header
            header = self.session.stamp(header)

        def attempt() -> Tuple[dict, bytes]:
            try:
                if self._sock is None:
                    s = _frame.connect((self.host, self.port), timeout=10.0)
                    s.settimeout(self.retry.attempt_timeout_s)
                    self._sock = s
                _send_msg(self._sock, header, payload)
                reply, body = _recv_msg(self._sock)
            except OSError:
                self._drop_sock()  # server restarted: reconnect on retry
                raise
            if reply.get("op") == "ERR":
                # protocol error: deterministic, NOT retryable
                raise RuntimeError(f"topic server: {reply.get('error')}")
            return reply, body

        with self._lock:
            try:
                return self.retry.call(attempt, endpoint=self.endpoint)
            except RetryError as e:
                raise ConnectionError(
                    f"topic server {self.host}:{self.port} unreachable"
                ) from e

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    # ------------------------------------------------------------ producing
    def append(self, value: Any) -> int:
        return self.append_many([value])[0]

    def append_many(self, values: Iterable[Any]) -> Tuple[int, int]:
        body = json.dumps(list(values)).encode("utf-8")
        reply, _ = self._call({"op": "APPEND", "topic": self.topic}, body)
        return reply["first"], reply["next"]

    # ------------------------------------------------------------ consuming
    def end_offset(self) -> int:
        reply, _ = self._call({"op": "END", "topic": self.topic})
        return reply["end"]

    def read(self, offset: int, max_records: Optional[int] = None
             ) -> Tuple[List[Any], int]:
        reply, body = self._call({
            "op": "READ", "topic": self.topic,
            "offset": int(offset), "max": max_records,
        })
        return json.loads(body.decode("utf-8")), reply["next"]

    def committed_offset(self, group: str) -> int:
        reply, _ = self._call({
            "op": "COMMITTED", "topic": self.topic, "group": group,
        })
        return reply["offset"]

    def commit_offset(self, group: str, offset: int) -> None:
        self._call({
            "op": "COMMIT", "topic": self.topic,
            "group": group, "offset": int(offset),
        })


def main(argv: Optional[List[str]] = None) -> None:
    """``python -m asyncframework_tpu.streaming.log_net --root DIR
    [--host H] [--port P]`` -- run a topic server (prints
    ``LISTENING host port`` once bound, the daemons' handshake line)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(description="LogTopic network server")
    ap.add_argument("--root", required=True)
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--segment-bytes", type=int, default=64 * 1024 * 1024)
    args = ap.parse_args(argv)
    from asyncframework_tpu.net import faults

    faults.maybe_install_from_conf()  # chaos runs configure daemons by env
    srv = LogTopicServer(args.root, host=args.host, port=args.port,
                         segment_bytes=args.segment_bytes)
    host, port = srv.start()
    print(f"LISTENING {host} {port}", flush=True)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        srv.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
