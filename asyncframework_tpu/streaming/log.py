"""File-backed replayable log: the durable-connector capability.

Parity (studied, not copied): the reference ships Kafka/Flume/Kinesis
connectors under ``external/`` (~12.9k LoC); its modern Kafka path is the
DIRECT stream (``external/kafka-0-10/.../DirectKafkaInputDStream.scala``):
no receiver, no WAL -- the consumer tracks OFFSETS into a replayable log,
reads each interval's range on demand, and commits offsets only after the
batch's outputs ran, so a crashed interval replays from the last commit.

TPU-first re-design: the *capability* is exactly-once-ish ingest from a
durable, replayable, offset-addressed log -- not the Kafka wire protocol.
:class:`LogTopic` is that log as an on-disk segmented append-only file
(producers on the same machine/filesystem append; segments roll at a size
bound), and :class:`DirectLogStream` is the direct consumer: per-interval
ranged reads, per-group committed offsets (atomic rename), commit strictly
AFTER the interval's outputs fired.  A raised output aborts the commit and
the interval replays on restart -- at-least-once delivery, exactly-once
when outputs are idempotent (the same contract the reference documents for
its direct stream).

Record payloads are JSON (one framed record per value): replay never
executes code -- the WAL's trust posture (``streaming/wal.py``).
"""

from __future__ import annotations

import json
import os
import struct
import threading
from typing import Any, Iterable, List, Optional, Tuple

from asyncframework_tpu.streaming.dstream import DStream, EMPTY

_LEN = struct.Struct("!I")


class LogTopic:
    """Segmented append-only log; offsets are record indices.

    Layout: ``<dir>/<start_offset:012d>.log`` segments of length-prefixed
    JSON records; ``<dir>/consumer-<group>.offset`` commit files.  Appends
    are serialized per-:class:`LogTopic` instance; multiple producer
    processes need one instance each and an external append discipline
    (same single-writer-per-partition stance as a Kafka partition).
    """

    def __init__(self, path: str, segment_bytes: int = 64 * 1024 * 1024,
                 fsync: bool = False):
        self.path = path
        self.segment_bytes = int(segment_bytes)
        self.fsync = fsync
        os.makedirs(path, exist_ok=True)
        self._lock = threading.Lock()
        self._segments = self._scan_segments()   # [(start_offset, path)]
        if not self._segments:
            self._segments = [(0, self._segment_path(0))]
            open(self._segments[0][1], "ab").close()
        # position index per segment, built/extended by incremental scans:
        # seg path -> [file pos]; _scanned tracks how far each file has
        # been indexed so a LIVE TAIL (another producer instance/process
        # appending concurrently) is picked up by the next read()
        self._index: dict = {}
        self._scanned: dict = {}
        last_start, last_path = self._segments[-1]
        self._end = last_start + len(self._positions(last_path))

    # -------------------------------------------------------------- layout
    def _segment_path(self, start: int) -> str:
        return os.path.join(self.path, f"{start:012d}.log")

    def _scan_segments(self) -> List[Tuple[int, str]]:
        segs = []
        for name in sorted(os.listdir(self.path)):
            if name.endswith(".log"):
                segs.append((int(name[:-4]), os.path.join(self.path, name)))
        return segs

    def _positions(self, seg_path: str) -> List[int]:
        """File positions of each record in a segment, extended by an
        INCREMENTAL scan from the last indexed byte -- records appended by
        another instance/process since the previous call are picked up,
        never re-scanning what is already indexed."""
        pos = self._index.setdefault(seg_path, [])
        off = self._scanned.get(seg_path, 0)
        try:
            size = os.path.getsize(seg_path)
        except OSError:
            return pos
        if off >= size:
            return pos
        with open(seg_path, "rb") as f:
            while off < size:
                f.seek(off)
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    break  # torn concurrent write: index up to it only
                (n,) = _LEN.unpack(head)
                if off + _LEN.size + n > size:
                    break
                pos.append(off)
                off += _LEN.size + n
        self._scanned[seg_path] = off
        return pos

    def _refresh(self) -> None:
        """Pick up segments/records appended by other instances (live
        tail); caller holds the lock.  Indexes EVERY segment (incremental:
        already-scanned bytes are never re-read), so readers outside the
        lock only consult prebuilt indexes."""
        known = {p for (_s, p) in self._segments}
        for start, path in self._scan_segments():
            if path not in known:
                self._segments.append((start, path))
        self._segments.sort()
        for _start, path in self._segments:
            self._positions(path)
        last_start, last_path = self._segments[-1]
        self._end = last_start + len(self._index[last_path])

    # ------------------------------------------------------------ producing
    def append(self, value: Any) -> int:
        """Append one record; returns its offset."""
        return self.append_many([value])[0]

    def append_many(self, values: Iterable[Any]) -> Tuple[int, int]:
        """Append a batch; returns (first_offset, next_offset)."""
        blobs = [json.dumps(v).encode("utf-8") for v in values]
        with self._lock:
            first = self._end
            start, seg_path = self._segments[-1]
            f = open(seg_path, "ab")
            try:
                for blob in blobs:
                    if (
                        f.tell() >= self.segment_bytes
                        and self._end > start
                    ):
                        # roll the segment at the bound
                        f.close()
                        start, seg_path = (
                            self._end, self._segment_path(self._end)
                        )
                        self._segments.append((start, seg_path))
                        f = open(seg_path, "ab")
                    self._positions(seg_path).append(f.tell())
                    f.write(_LEN.pack(len(blob)) + blob)
                    # our own append is already indexed: advance the scan
                    # watermark past it or the next incremental scan would
                    # double-index the record
                    self._scanned[seg_path] = f.tell()
                    self._end += 1
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            finally:
                f.close()
            return first, self._end

    # ------------------------------------------------------------ consuming
    def end_offset(self) -> int:
        with self._lock:
            self._refresh()
            return self._end

    def read(self, offset: int, max_records: Optional[int] = None
             ) -> Tuple[List[Any], int]:
        """Records from ``offset`` (up to ``max_records``) and the next
        offset.  Reading past the end returns ([], end).  Each read
        refreshes the tail, so records appended by OTHER producer
        instances/processes since the last call are visible."""
        out: List[Any] = []
        with self._lock:
            self._refresh()
            end = self._end
            segments = list(self._segments)
        offset = max(0, offset)
        budget = max_records if max_records is not None else end - offset
        while offset < end and len(out) < budget:
            # segment containing `offset`: last one starting at or before
            seg_i = 0
            for i, (s, _p) in enumerate(segments):
                if s <= offset:
                    seg_i = i
                else:
                    break
            start, seg_path = segments[seg_i]
            # no scanning outside the lock: everything below `end` was
            # indexed by the locked _refresh above, and an unlocked
            # incremental scan could race another reader's
            pos = self._index.get(seg_path, [])
            with open(seg_path, "rb") as f:
                while offset < end and len(out) < budget:
                    rel = offset - start
                    if rel >= len(pos):
                        break  # continue in the next segment
                    f.seek(pos[rel])
                    (n,) = _LEN.unpack(f.read(_LEN.size))
                    out.append(json.loads(f.read(n).decode("utf-8")))
                    offset += 1
        return out, offset

    # ------------------------------------------------------ consumer groups
    def _offset_path(self, group: str) -> str:
        return os.path.join(self.path, f"consumer-{group}.offset")

    def committed_offset(self, group: str) -> int:
        try:
            with open(self._offset_path(group)) as f:
                return int(json.load(f)["offset"])
        except (OSError, ValueError, KeyError):
            return 0

    def commit_offset(self, group: str, offset: int) -> None:
        """Atomic (write + rename) per-group commit."""
        path = self._offset_path(group)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"offset": int(offset)}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)


class DirectLogStream(DStream):
    """Direct (offset-tracked) consumer of a :class:`LogTopic`.

    Each interval reads from the last committed/consumed offset (bounded by
    ``max_per_batch``); the consumed offset is COMMITTED in
    ``on_batch_completed`` -- after every output fired -- so a failed
    interval replays from the previous commit on restart.
    """

    def __init__(self, ssc, topic, group: str = "default",
                 max_per_batch: Optional[int] = None):
        super().__init__(ssc)
        # a string is a local topic directory; anything else (LogTopic,
        # RemoteLogTopic, ...) just needs the read/commit surface
        self.topic = LogTopic(topic) if isinstance(topic, str) else topic
        self.group = group
        self.max_per_batch = max_per_batch
        self._next = self.topic.committed_offset(group)
        self._pending: Optional[int] = None
        ssc._register_receiver(self)  # for the commit hook

    def compute(self, time_ms: int) -> Any:
        records, nxt = self.topic.read(self._next, self.max_per_batch)
        self._pending = nxt
        if not records:
            return EMPTY
        return records

    def on_batch_completed(self, time_ms: float, processing_delay_ms: float,
                           scheduling_delay_ms: float) -> None:
        """Commit point: runs only when the whole interval's outputs
        succeeded (a raised output propagates out of generate_batch and
        skips this)."""
        if self._pending is not None and self._pending != self._next:
            self.topic.commit_offset(self.group, self._pending)
            self._next = self._pending
        self._pending = None

    # receiver-API compatibility no-ops (the context treats registered
    # receivers uniformly; a direct stream has no push buffer or rate loop)
    def current_rate(self) -> Optional[float]:
        return None
