"""The streaming context: batch clocking and job generation.

Parity: ``streaming/.../StreamingContext`` + ``scheduler/JobGenerator.scala:42``
-- a timer fires every batch interval; each tick generates one job per
registered output operation over that interval's data, executed in order;
``stop(graceful)`` drains pending intervals before shutdown.  Determinism
parity with the reference's suites comes from the injected clock: with a
:class:`~asyncframework_tpu.utils.clock.ManualClock`, tests advance virtual
time and every generated batch is exactly reproducible (SURVEY.md section 4).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple

from asyncframework_tpu.streaming.dstream import DStream, EMPTY, QueueStream
from asyncframework_tpu.streaming.wal import WriteAheadLog
from asyncframework_tpu.utils.clock import Clock, SystemClock


class StreamingContext:
    def __init__(
        self,
        batch_interval_ms: int = 1000,
        clock: Optional[Clock] = None,
    ):
        if batch_interval_ms < 1:
            raise ValueError("batch_interval_ms must be >= 1")
        self.batch_interval_ms = int(batch_interval_ms)
        self.clock = clock or SystemClock()
        self._outputs: List[Tuple[DStream, Callable[[int, Any], None]]] = []
        self._statefuls: List = []  # StatefulDStream registration order = id
        self._receivers: List = []  # ReceiverStreams (rate-control feedback)
        self._ckpt_mgr = None
        self._ckpt_every = 0
        self._pending_restore = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started = False
        self._processed_batches = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ registration
    def _register_output(self, ds: DStream, fn) -> None:
        if self._started:
            raise RuntimeError("cannot add outputs after start()")
        self._outputs.append((ds, fn))

    def _register_receiver(self, ds) -> None:
        self._receivers.append(ds)

    def _register_stateful(self, ds) -> None:
        idx = len(self._statefuls)
        self._statefuls.append(ds)
        # a restore_state() that ran before the graph was rebuilt parks the
        # checkpoint here; hand each stateful its slice as it registers
        if self._pending_restore is not None:
            self._apply_restore(idx, ds)

    # ------------------------------------------------------------- checkpoint
    def enable_state_checkpoint(
        self, directory, every_n_intervals: int = 5, keep: int = 3
    ) -> None:
        """Periodic snapshot of every stateful stream's keyed state.

        Parity: streaming metadata checkpoints
        (``streaming/.../Checkpoint.scala:55``); rides
        :class:`~asyncframework_tpu.checkpoint.CheckpointManager` (atomic
        rename + fsync + GC).  Keys/states must be JSON-serializable (same
        trust posture as the WAL: replay never executes code).
        """
        from asyncframework_tpu.checkpoint import CheckpointManager

        if every_n_intervals < 1:
            raise ValueError("every_n_intervals must be >= 1")
        self._ckpt_mgr = CheckpointManager(directory, keep)
        self._ckpt_every = int(every_n_intervals)

    def _maybe_checkpoint(self, interval_idx: int) -> None:
        if self._ckpt_mgr is None or interval_idx % self._ckpt_every != 0:
            return
        import json

        import numpy as np

        state = {}
        for i, ds in enumerate(self._statefuls):
            t, items = ds.snapshot_state()
            blob = json.dumps([t, items]).encode("utf-8")
            state[f"stream_{i}"] = np.frombuffer(blob, np.uint8)
        self._ckpt_mgr.save(interval_idx, state)

    @staticmethod
    def _freeze(k):
        """JSON turns tuple keys into lists; re-freeze so restored keys hash
        identically to the keys the update function will produce."""
        return tuple(StreamingContext._freeze(x) for x in k) if isinstance(
            k, list
        ) else k

    def _apply_restore(self, idx: int, ds) -> None:
        import json

        blob = self._pending_restore.get(f"stream_{idx}")
        if blob is None:
            return
        t, items = json.loads(bytes(bytearray(blob)).decode("utf-8"))
        ds.restore(t, [(self._freeze(k), v) for k, v in items])

    def restore_state(self) -> Optional[int]:
        """Load the latest state checkpoint.  May be called before OR after
        the stream graph is rebuilt: state is handed to stateful streams as
        they register, matched by registration order (the rebuilt graph must
        register its stateful streams in the same order).  Returns the
        checkpoint's newest state time in ms (use it as
        ``recovered_stream(..., after_ms=...)`` to skip WAL batches already
        folded into the state), or None when there is no checkpoint."""
        if self._ckpt_mgr is None:
            raise RuntimeError("enable_state_checkpoint first")
        ck = self._ckpt_mgr.restore_latest_or_none()
        if ck is None:
            return None
        import json

        self._pending_restore = ck
        last_t = 0
        for i, ds in enumerate(self._statefuls):
            self._apply_restore(i, ds)
        for key, blob in ck.items():
            if key.startswith("stream_"):
                t, _items = json.loads(bytes(bytearray(blob)).decode("utf-8"))
                last_t = max(last_t, int(t))
        return last_t

    # ----------------------------------------------------------------- sources
    def queue_stream(self, batches=None, wal: Optional[WriteAheadLog] = None
                     ) -> QueueStream:
        return QueueStream(self, batches, wal=wal)

    def recovered_stream(
        self, wal: WriteAheadLog, after_ms: Optional[int] = None
    ) -> QueueStream:
        """Re-emit batches recorded in a write-ahead log (restart recovery:
        the reference replays WAL-backed blocks after driver failure).
        ``after_ms`` skips batches already folded into a restored state
        checkpoint (pass ``restore_state()``'s return value; ``None`` -- a
        cold start -- replays everything, including a t=0 batch)."""
        if after_ms is None:
            return QueueStream(self, [b for (_t, b) in wal.replay()])
        return QueueStream(
            self, [b for (t, b) in wal.replay() if t > after_ms]
        )

    # ------------------------------------------------------------ job generation
    def generate_batch(self, time_ms: int, scheduled_at_ms=None) -> int:
        """Run one interval synchronously; returns #outputs that fired.

        Exposed for deterministic tests (JobGenerator tick parity).
        ``scheduled_at_ms``: the interval's target time on the CONTEXT
        clock (absolute); the generator loop passes it so receivers see a
        real scheduling delay -- PIDRateEstimator.scala's integral input.
        """
        t_start = self.clock.now_ms()
        scheduling_delay = (
            max(0.0, t_start - scheduled_at_ms)
            if scheduled_at_ms is not None
            else 0.0
        )
        fired = 0
        for ds, fn in self._outputs:
            batch = ds.get_or_compute(time_ms)
            if batch is not EMPTY:
                fn(time_ms, batch)
                fired += 1
        processing_delay = max(self.clock.now_ms() - t_start, 0.0)
        for rec in self._receivers:
            rec.on_batch_completed(time_ms, processing_delay, scheduling_delay)
        with self._lock:
            self._processed_batches += 1
        self._maybe_checkpoint(time_ms // self.batch_interval_ms)
        return fired

    @property
    def processed_intervals(self) -> int:
        with self._lock:
            return self._processed_batches

    # ------------------------------------------------------------------ control
    def start(self) -> None:
        if self._started:
            raise RuntimeError("context already started")
        if not self._outputs:
            raise RuntimeError("no output operations registered")
        self._started = True
        t0 = self.clock.now_ms()

        def loop() -> None:
            n = 1
            while not self._stop.is_set():
                target = t0 + n * self.batch_interval_ms
                while self.clock.now_ms() < target:
                    if self.clock.wait_for(self._stop, 0.01):
                        return
                self.generate_batch(
                    n * self.batch_interval_ms, scheduled_at_ms=target
                )
                n += 1

        self._thread = threading.Thread(
            target=loop, name="stream-job-generator", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def await_intervals(self, n: int, timeout_s: float = 10.0) -> None:
        """Block until ``n`` intervals have been processed (test helper)."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while self.processed_intervals < n:
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {self.processed_intervals}/{n} intervals processed"
                )
            _time.sleep(0.005)
