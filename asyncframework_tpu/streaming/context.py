"""The streaming context: batch clocking and job generation.

Parity: ``streaming/.../StreamingContext`` + ``scheduler/JobGenerator.scala:42``
-- a timer fires every batch interval; each tick generates one job per
registered output operation over that interval's data, executed in order;
``stop(graceful)`` drains pending intervals before shutdown.  Determinism
parity with the reference's suites comes from the injected clock: with a
:class:`~asyncframework_tpu.utils.clock.ManualClock`, tests advance virtual
time and every generated batch is exactly reproducible (SURVEY.md section 4).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Tuple

from asyncframework_tpu.streaming.dstream import DStream, EMPTY, QueueStream
from asyncframework_tpu.streaming.wal import WriteAheadLog
from asyncframework_tpu.utils.clock import Clock, SystemClock


class StreamingContext:
    def __init__(
        self,
        batch_interval_ms: int = 1000,
        clock: Optional[Clock] = None,
    ):
        if batch_interval_ms < 1:
            raise ValueError("batch_interval_ms must be >= 1")
        self.batch_interval_ms = int(batch_interval_ms)
        self.clock = clock or SystemClock()
        self._outputs: List[Tuple[DStream, Callable[[int, Any], None]]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started = False
        self._processed_batches = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------ registration
    def _register_output(self, ds: DStream, fn) -> None:
        if self._started:
            raise RuntimeError("cannot add outputs after start()")
        self._outputs.append((ds, fn))

    # ----------------------------------------------------------------- sources
    def queue_stream(self, batches=None, wal: Optional[WriteAheadLog] = None
                     ) -> QueueStream:
        return QueueStream(self, batches, wal=wal)

    def recovered_stream(self, wal: WriteAheadLog) -> QueueStream:
        """Re-emit every batch recorded in a write-ahead log (restart
        recovery: the reference replays WAL-backed blocks after driver
        failure)."""
        return QueueStream(self, [b for (_t, b) in wal.replay()])

    # ------------------------------------------------------------ job generation
    def generate_batch(self, time_ms: int) -> int:
        """Run one interval synchronously; returns #outputs that fired.

        Exposed for deterministic tests (JobGenerator tick parity).
        """
        fired = 0
        for ds, fn in self._outputs:
            batch = ds.get_or_compute(time_ms)
            if batch is not EMPTY:
                fn(time_ms, batch)
                fired += 1
        with self._lock:
            self._processed_batches += 1
        return fired

    @property
    def processed_intervals(self) -> int:
        with self._lock:
            return self._processed_batches

    # ------------------------------------------------------------------ control
    def start(self) -> None:
        if self._started:
            raise RuntimeError("context already started")
        if not self._outputs:
            raise RuntimeError("no output operations registered")
        self._started = True
        t0 = self.clock.now_ms()

        def loop() -> None:
            n = 1
            while not self._stop.is_set():
                target = t0 + n * self.batch_interval_ms
                while self.clock.now_ms() < target:
                    if self.clock.wait_for(self._stop, 0.01):
                        return
                self.generate_batch(n * self.batch_interval_ms)
                n += 1

        self._thread = threading.Thread(
            target=loop, name="stream-job-generator", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    def await_intervals(self, n: int, timeout_s: float = 10.0) -> None:
        """Block until ``n`` intervals have been processed (test helper)."""
        import time as _time

        deadline = _time.monotonic() + timeout_s
        while self.processed_intervals < n:
            if _time.monotonic() > deadline:
                raise TimeoutError(
                    f"only {self.processed_intervals}/{n} intervals processed"
                )
            _time.sleep(0.005)
