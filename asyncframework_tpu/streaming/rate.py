"""Receiver rate control: PID estimation + token-bucket limiting.

Parity: ``streaming/.../scheduler/rate/PIDRateEstimator.scala:48`` (the
estimator: a textbook PID loop on processing rate, with scheduling delay as
the integral term) and ``receiver/RateLimiter.scala`` (the enforcement side:
the block generator's guava RateLimiter).  Together they are Spark
Streaming's backpressure: when batches take longer than the interval, the
receiver's permitted ingest rate ramps down until the pipeline keeps up.

TPU build note: ingestion is host-side (receivers feed host buffers that the
interval clock drains), so this subsystem is pure host logic -- but without
it a fast producer OOMs the host while the chip is busy, which is exactly
the failure the reference built backpressure for.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class PIDRateEstimator:
    """Estimate the ingest rate (elements/sec) the pipeline can sustain.

    ``compute`` is fed one observation per completed batch:
    (completion time, batch size, processing delay, scheduling delay).
    Semantics follow ``PIDRateEstimator.scala:48``:

    - error            = latest_rate - processing_rate
    - historical_error = scheduling_delay * processing_rate / batch_interval
      (elements queued *behind* schedule, expressed as a rate)
    - d_error          = (error - latest_error) / delta_t

    new_rate = max(latest_rate - Kp*error - Ki*historical_error - Kd*d_error,
                   min_rate); returns None until it has two observations or
    when the observation is degenerate (empty batch / zero delay).
    """

    def __init__(
        self,
        batch_interval_ms: float,
        proportional: float = 1.0,
        integral: float = 0.2,
        derivative: float = 0.0,
        min_rate: float = 100.0,
    ):
        if batch_interval_ms <= 0:
            raise ValueError("batch_interval_ms must be > 0")
        if min(proportional, integral, derivative) < 0 or min_rate <= 0:
            raise ValueError("PID gains must be >= 0 and min_rate > 0")
        self.batch_interval_s = batch_interval_ms / 1e3
        self.kp = proportional
        self.ki = integral
        self.kd = derivative
        self.min_rate = min_rate
        self._latest_time_ms: Optional[float] = None
        self._latest_rate: Optional[float] = None
        self._latest_error = 0.0
        self._lock = threading.Lock()

    def compute(
        self,
        time_ms: float,
        num_elements: int,
        processing_delay_ms: float,
        scheduling_delay_ms: float,
    ) -> Optional[float]:
        with self._lock:
            valid = (
                num_elements > 0
                and processing_delay_ms > 0
                and (self._latest_time_ms is None
                     or time_ms > self._latest_time_ms)
            )
            if not valid:
                return None
            processing_rate = num_elements / (processing_delay_ms / 1e3)
            if self._latest_rate is None:
                # first observation seeds the loop at the measured rate
                self._latest_time_ms = time_ms
                self._latest_rate = processing_rate
                self._latest_error = 0.0
                return None
            delta_s = (time_ms - self._latest_time_ms) / 1e3
            error = self._latest_rate - processing_rate
            historical = (
                (scheduling_delay_ms / 1e3) * processing_rate
                / self.batch_interval_s
            )
            d_error = (error - self._latest_error) / max(delta_s, 1e-9)
            new_rate = max(
                self._latest_rate
                - self.kp * error
                - self.ki * historical
                - self.kd * d_error,
                self.min_rate,
            )
            self._latest_time_ms = time_ms
            self._latest_rate = new_rate
            self._latest_error = error
            return new_rate


class RateLimiter:
    """Blocking token bucket: ``acquire()`` admits one element, waiting
    when the current second's allowance is spent (RateLimiter.scala role).

    ``set_rate`` is thread-safe and takes effect immediately -- the
    estimator calls it from the batch-completion path while the receiver
    thread sits in ``acquire``.
    """

    def __init__(self, rate: Optional[float] = None, burst_s: float = 0.1):
        self._rate = rate  # None = unlimited
        self._burst_s = burst_s  # bucket depth in seconds of allowance
        self._tokens = 0.0
        self._stamp = time.monotonic()
        self._cv = threading.Condition()

    @property
    def rate(self) -> Optional[float]:
        with self._cv:
            return self._rate

    def set_rate(self, rate: Optional[float]) -> None:
        with self._cv:
            self._rate = rate
            self._cv.notify_all()

    def _refill_locked(self) -> None:
        now = time.monotonic()
        if self._rate is not None:
            cap = self._rate * self._burst_s
            self._tokens = min(cap, self._tokens + (now - self._stamp) * self._rate)
        self._stamp = now

    def try_acquire(self) -> bool:
        """Non-blocking: True = admitted (drop policies use this)."""
        with self._cv:
            if self._rate is None:
                return True
            self._refill_locked()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def acquire(self, stop_check=None, poll_s: float = 0.01) -> bool:
        """Block until admitted; returns False if ``stop_check()`` turned
        true first (receiver shutdown must never deadlock in the limiter)."""
        while True:
            with self._cv:
                if self._rate is None:
                    return True
                self._refill_locked()
                if self._tokens >= 1.0:
                    self._tokens -= 1.0
                    return True
                need_s = (1.0 - self._tokens) / self._rate
            if stop_check is not None and stop_check():
                return False
            time.sleep(min(need_s, poll_s))
