"""Write-ahead log for received stream batches.

Parity: ``streaming/src/main/.../util/WriteAheadLog`` -- received data is
persisted before processing so a driver restart can replay unprocessed
batches (tested by the reference's ``WriteAheadLogSuite`` with a ManualClock).

Format: one file per log, records framed as
``[u32 len][npz bytes]`` where the npz holds the batch (array payloads) plus
its arrival time -- the same serialization the checkpoint module uses, so any
batch a solver can checkpoint, the WAL can persist.  Torn tails (crash
mid-append) are truncated on open, like ``storage/kvstore``.
"""

from __future__ import annotations

import io
import os
import struct
import threading
from pathlib import Path
from typing import Any, Iterator, List, Tuple

import numpy as np


_COMPRESSED_FLAG = 0x80000000  # high bit of the record length


class WriteAheadLog:
    def __init__(self, path, compress: bool = False):
        """``compress=True`` writes each record as an AZ1 block
        (``utils/codec.py`` -- the native-codec analog of the reference
        compressing its WAL/event bytes through lz4); the flag rides the
        high bit of the length word, so compressed and plain records can
        coexist in one log and replay handles both."""
        self.path = Path(path)
        self.compress = compress
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        if self.path.exists():
            end = self._scan_valid_end()
            with open(self.path, "r+b") as f:
                f.truncate(end)
        self._f = open(self.path, "ab")

    def _scan_valid_end(self) -> int:
        with open(self.path, "rb") as f:
            while True:
                start = f.tell()
                hdr = f.read(4)
                if len(hdr) < 4:
                    return start  # clean end (0 bytes) or torn header
                (n,) = struct.unpack("<I", hdr)
                n &= ~_COMPRESSED_FLAG
                blob = f.read(n)
                if len(blob) < n:
                    return start  # torn record

    def append(self, time_ms: int, batch: Any) -> None:
        buf = io.BytesIO()
        arr = np.asarray(batch) if hasattr(batch, "shape") else None
        if arr is not None:
            np.savez(buf, t=np.int64(time_ms), kind=np.uint8(0), batch=arr)
        else:
            # Non-array batches ride as JSON payloads.  JSON (not pickle) on
            # purpose: a WAL may be replayed after a restart or copied across
            # hosts, and replay of untrusted bytes must never execute code.
            # Payloads are therefore restricted to JSON-safe structures
            # (dict/list/str/int/float/bool/None; tuples come back as lists).
            np.savez(
                buf,
                t=np.int64(time_ms),
                kind=np.uint8(2),  # 2 = JSON (1 was the old pickle format)
                batch=np.frombuffer(_to_json(batch), np.uint8),
            )
        blob = buf.getvalue()
        flag = 0
        if self.compress:
            from asyncframework_tpu.utils.codec import compress as az1

            blob = az1(blob)
            flag = _COMPRESSED_FLAG
        with self._lock:
            self._f.write(struct.pack("<I", len(blob) | flag))
            self._f.write(blob)
            self._f.flush()
            os.fsync(self._f.fileno())

    def replay(self) -> Iterator[Tuple[int, Any]]:
        with self._lock:
            self._f.flush()
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return
                (n,) = struct.unpack("<I", hdr)
                compressed = bool(n & _COMPRESSED_FLAG)
                n &= ~_COMPRESSED_FLAG
                blob = f.read(n)
                if len(blob) < n:
                    return
                if compressed:
                    from asyncframework_tpu.utils.codec import decompress

                    blob = decompress(blob)
                with np.load(io.BytesIO(blob), allow_pickle=False) as z:
                    t = int(z["t"])
                    kind = int(z["kind"])
                    if kind == 0:
                        yield t, z["batch"]
                    elif kind == 2:
                        yield t, _from_json(z["batch"].tobytes())
                    else:
                        raise ValueError(
                            f"{self.path}: record kind={kind} is an "
                            "unsupported legacy WAL payload (pre-JSON "
                            "pickle format); re-create the log"
                        )

    def clear(self) -> None:
        """Truncate the log (after a successful checkpoint: processed batches
        no longer need replay)."""
        with self._lock:
            self._f.close()
            self._f = open(self.path, "wb")

    def close(self) -> None:
        with self._lock:
            self._f.close()

    def __enter__(self) -> "WriteAheadLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _to_json(obj: Any) -> bytes:
    import json

    try:
        return json.dumps(obj).encode("utf-8")
    except TypeError as e:
        raise TypeError(
            "WAL batches must be arrays or JSON-serializable structures "
            f"(dict/list/str/number/bool/None); got {type(obj).__name__}"
        ) from e


def _from_json(b: bytes) -> Any:
    import json

    return json.loads(b.decode("utf-8"))
