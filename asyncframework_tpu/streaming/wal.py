"""Write-ahead log for received stream batches.

Parity: ``streaming/src/main/.../util/WriteAheadLog`` -- received data is
persisted before processing so a driver restart can replay unprocessed
batches (tested by the reference's ``WriteAheadLogSuite`` with a ManualClock).

Format: one file per log, records framed as
``[u32 len][npz bytes]`` where the npz holds the batch (array payloads) plus
its arrival time -- the same serialization the checkpoint module uses, so any
batch a solver can checkpoint, the WAL can persist.  Torn tails (crash
mid-append) are truncated on open, like ``storage/kvstore``.
"""

from __future__ import annotations

import io
import os
import struct
import threading
from pathlib import Path
from typing import Any, Iterator, List, Tuple

import numpy as np


class WriteAheadLog:
    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        if self.path.exists():
            end = self._scan_valid_end()
            with open(self.path, "r+b") as f:
                f.truncate(end)
        self._f = open(self.path, "ab")

    def _scan_valid_end(self) -> int:
        with open(self.path, "rb") as f:
            while True:
                start = f.tell()
                hdr = f.read(4)
                if len(hdr) < 4:
                    return start  # clean end (0 bytes) or torn header
                (n,) = struct.unpack("<I", hdr)
                blob = f.read(n)
                if len(blob) < n:
                    return start  # torn record

    def append(self, time_ms: int, batch: Any) -> None:
        buf = io.BytesIO()
        arr = np.asarray(batch) if hasattr(batch, "shape") else None
        if arr is not None:
            np.savez(buf, t=np.int64(time_ms), kind=np.uint8(0), batch=arr)
        else:
            # non-array batches ride as object payloads via pickle-in-npz
            np.savez(
                buf,
                t=np.int64(time_ms),
                kind=np.uint8(1),
                batch=np.frombuffer(_pickle(batch), np.uint8),
            )
        blob = buf.getvalue()
        with self._lock:
            self._f.write(struct.pack("<I", len(blob)))
            self._f.write(blob)
            self._f.flush()
            os.fsync(self._f.fileno())

    def replay(self) -> Iterator[Tuple[int, Any]]:
        with self._lock:
            self._f.flush()
        with open(self.path, "rb") as f:
            while True:
                hdr = f.read(4)
                if len(hdr) < 4:
                    return
                (n,) = struct.unpack("<I", hdr)
                blob = f.read(n)
                if len(blob) < n:
                    return
                with np.load(io.BytesIO(blob), allow_pickle=False) as z:
                    t = int(z["t"])
                    if int(z["kind"]) == 0:
                        yield t, z["batch"]
                    else:
                        yield t, _unpickle(z["batch"].tobytes())

    def clear(self) -> None:
        """Truncate the log (after a successful checkpoint: processed batches
        no longer need replay)."""
        with self._lock:
            self._f.close()
            self._f = open(self.path, "wb")

    def close(self) -> None:
        with self._lock:
            self._f.close()

    def __enter__(self) -> "WriteAheadLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _pickle(obj: Any) -> bytes:
    import pickle

    return pickle.dumps(obj, protocol=4)


def _unpickle(b: bytes) -> Any:
    import pickle

    return pickle.loads(b)
