"""Multi-host process-group bring-up over DCN.

Parity: the reference's cluster bring-up is standalone Master/Worker
registration over its Netty RPC (``deploy/master/Master.scala:41``,
``deploy/worker/Worker.scala:43``, executor registration in
``CoarseGrainedSchedulerBackend``).  The TPU-native equivalent is
``jax.distributed``: one coordinator, N host processes, after which
``jax.devices()`` spans every host and the SAME mesh/pjit code rides ICI
within a slice and DCN across slices -- there is no separate "cluster mode"
code path, which is the point of the SPMD design.

This module is a thin, honest wrapper: env-driven initialization, a host
barrier built from a device collective, and helpers to build global meshes.
Single-process usage is a no-op (``ensure_initialized`` returns False), so
every call site works unchanged on one host.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import jax
import numpy as np

_initialized = False


def _jax_distributed_active() -> bool:
    """True when jax.distributed was initialized (by us or by a launcher
    calling ``jax.distributed.initialize()`` directly)."""
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:  # noqa: BLE001 - internals moved; assume inactive
        return False


def is_initialized() -> bool:
    return _initialized or _jax_distributed_active()


def ensure_initialized(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    auto: bool = False,
) -> bool:
    """Initialize ``jax.distributed`` when multi-host args/env are present.

    Resolution order: explicit args > ``ASYNCTPU_COORDINATOR`` /
    ``ASYNCTPU_NUM_PROCESSES`` / ``ASYNCTPU_PROCESS_ID`` env vars.  With
    neither, this is a single-process no-op unless ``auto=True``, which
    hands off to ``jax.distributed.initialize()``'s own cloud environment
    detection (an explicit opt-in: auto-detection can block waiting for a
    coordinator on non-cluster machines).  Returns True when running
    multi-process, False for single-process.  Idempotent, including when a
    launcher already called ``jax.distributed.initialize()`` itself.
    """
    global _initialized
    if _initialized or _jax_distributed_active():
        _initialized = True
        return jax.process_count() > 1
    coordinator_address = coordinator_address or os.environ.get(
        "ASYNCTPU_COORDINATOR"
    )
    env_np = os.environ.get("ASYNCTPU_NUM_PROCESSES")
    env_pid = os.environ.get("ASYNCTPU_PROCESS_ID")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None and num_processes is None and not auto:
        return False  # single-process: nothing to do
    if num_processes is not None and num_processes <= 1:
        # an explicit 1-process "cluster" (e.g. a master-scheduled
        # single-executor placement) is just a single process: spinning up
        # the distributed service would bind the coordinator port and buy
        # nothing
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return jax.process_count() > 1


def process_info() -> Tuple[int, int]:
    """(process_id, process_count) -- (0, 1) when single-process."""
    return jax.process_index(), jax.process_count()


def sync_hosts(name: str = "barrier") -> None:
    """Block until every host reaches this point.

    Built from a tiny all-reduce over all devices (a psum is a barrier:
    no host can observe its result before every host contributed), which is
    how SPMD programs fence hosts without a separate RPC service.
    """
    device_count = jax.device_count()
    x = jax.numpy.ones((jax.local_device_count(),))
    total = jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x)
    got = int(np.asarray(total)[0])
    if got != device_count:
        raise RuntimeError(
            f"{name}: barrier saw {got} devices, expected {device_count}"
        )


def global_mesh(axis_names=("dp",), axis_sizes=None):
    """A mesh over every device of every host (ICI within a slice, DCN
    across); defaults to one data-parallel axis over all devices."""
    from asyncframework_tpu.parallel.mesh import make_mesh

    return make_mesh(
        n_devices=jax.device_count(),
        axis_names=tuple(axis_names),
        axis_sizes=axis_sizes,
        devices=jax.devices(),
    )
