from asyncframework_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    batch_sharding,
    replicated_sharding,
    shard_batch,
)
from asyncframework_tpu.parallel.ring import (  # noqa: F401
    reference_attention,
    ring_attention,
    ulysses_attention,
)
from asyncframework_tpu.parallel.supervisor import (  # noqa: F401
    ElasticSupervisor,
    recovery_totals,
)
from asyncframework_tpu.parallel.shardgroup import (  # noqa: F401
    ShardGroup,
    ShardMap,
    ShardedPSClient,
    ShardedSubscriber,
    shard_ranges,
    shard_totals,
)
