"""Elastic training supervisor: process-level membership for the DCN plane.

PR 1 made transport blips survivable (retry + exactly-once sessions); this
module makes *process death* survivable.  The paper's whole argument (ASYNC,
arXiv:1907.08526) is that a bounded-staleness run keeps converging when
workers are slow or absent -- ASAP (arXiv:1612.08608) goes further and
treats membership change itself as just another source of staleness.  The
supervisor is that idea applied to ``parallel/ps_dcn.py``'s multi-process
path: the PS-side authority on *who is alive and who owns which shard*.

Mechanism (all of it piggybacked on the existing PULL/PUSH protocol -- no
new control channel, no extra RTTs):

- worker processes ``HELLO`` once with a process token, their logical
  worker ids, and their pid/host; every PULL/PUSH carries the token and
  refreshes per-worker last-contact.
- a monitor thread declares a worker **dead** on process exit (local pid
  probe -- immediate) or silence past ``dead_after_s`` (the remote /
  wedged case).
- dead workers' shards are re-homed with the SAME policy the in-process
  engine uses (``engine/recovery.plan_reassignment``, least-loaded-first,
  deterministic), except the survivors are *processes*: the PS piggybacks
  **adoption orders** on the adopter's next PULL reply, and the adopter
  materializes the orphan shard locally (``shard_factory``) and starts
  pulling for it.  The run completes with full data coverage at a
  degraded cohort size -- the partial barrier ``b`` is clamped to live
  membership so waves keep flowing without waiting on the starvation
  fallback.
- a **rejoining** worker (same shards, fresh process + session) HELLOs,
  takes its shards back, and the adopter's surrogate loop is told
  ``RELEASED`` on its next pull -- membership rebalances with no
  double-serving window: ownership is checked on every PULL *and* PUSH,
  so a push from a deposed owner is membership-stale and dropped.

The supervisor is deliberately jax-free and transport-free: it sees only
(token, wid, pid, clock) events, so it unit-tests with a ``ManualClock``
and the live UI can import its counters without dragging the device stack.
"""

from __future__ import annotations

import socket
import threading
import weakref
from typing import Dict, List, Optional, Sequence, Set

from asyncframework_tpu.metrics import flightrec as _flight
from asyncframework_tpu.utils.clock import Clock, SystemClock

# states a logical worker (shard slot) moves through
UNKNOWN = "unknown"   # never heard from (counts as live for cohort sizing)
LIVE = "live"
SUSPECT = "suspect"   # missed lease renewal / latency outlier; still live
DEAD = "dead"         # lease expired / process exited; under replacement

_totals_lock = threading.Lock()
_totals: Dict[str, int] = {
    "workers_lost": 0,     # wids declared dead (exit or lease expiry)
    "shards_adopted": 0,   # adoption orders issued to survivors
    "rejoins": 0,          # wids reclaimed by a re-registered process
    "releases": 0,         # surrogate loops told to stand down
    "ps_resumes": 0,       # ParameterServer restarts from checkpoint
    "suspicions": 0,       # members marked SUSPECT (silence or RTT)
    "lease_expiries": 0,   # deaths declared by lease expiry (not exit)
    "epoch_bumps": 0,      # fencing epochs minted before replacements
    "fenced_rejects": 0,   # stale-epoch ops servers answered REJECT_FENCED
}


def recovery_totals() -> Dict[str, int]:
    """Process-wide elastic-recovery counters (live UI, next to net/)."""
    with _totals_lock:
        return dict(_totals)


def reset_recovery_totals() -> None:
    """Zero the process-wide counters (per-run isolation; see
    ``asyncframework_tpu.metrics.reset_totals``)."""
    with _totals_lock:
        for k in _totals:
            _totals[k] = 0


def bump_total(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] = _totals.get(key, 0) + n


#: weak registry of running supervisors in this process: the cluster
#: observer (metrics/observer.py) walks it to discover worker-role
#: scrape targets from membership (HELLO ``mport``) without holding any
#: supervisor alive past its own stop()
_active_lock = threading.Lock()
_active_sups: "List[weakref.ref]" = []


def active_supervisors() -> List["ElasticSupervisor"]:
    with _active_lock:
        out = [ref() for ref in _active_sups]
        return [s for s in out if s is not None]


def _pid_alive(pid) -> bool:
    """checkpoint.py's pid probe, hardened against junk pids from the
    wire (one probe implementation for the whole repo)."""
    from asyncframework_tpu.checkpoint import _pid_alive as _probe

    try:
        return _probe(int(pid))
    except (OverflowError, ValueError):
        return True


def proc_start_time(pid) -> Optional[float]:
    """The process's kernel start time (``/proc/<pid>/stat`` field 22, in
    clock ticks since boot) -- the disambiguator that makes a pid probe
    honest: pids are recycled, and "pid N is alive" says nothing about
    WHICH process holds it.  A member records its own start time at HELLO
    (``pstart``); the probe treats a live pid whose start time no longer
    matches as exited (the member died and an unrelated process reused
    its pid).  None on platforms without /proc or on any read failure --
    callers fall back to the bare pid probe."""
    try:
        with open(f"/proc/{int(pid)}/stat", "rb") as f:
            data = f.read()
        # comm (field 2) may contain spaces and parens: split AFTER the
        # last ')' -- tail[0] is field 3 (state), starttime is field 22
        tail = data.rsplit(b")", 1)[1].split()
        return float(tail[19])
    except (OSError, ValueError, IndexError):
        return None


class _ProcRecord:
    __slots__ = ("token", "pid", "pid_is_local", "pid_start",
                 "registered_ms", "last_contact_ms", "exited",
                 "host", "mport")

    def __init__(self, token: str, now_ms: float, pid: Optional[int] = None,
                 host: Optional[str] = None,
                 pid_start: Optional[float] = None,
                 mport: Optional[int] = None):
        self.token = token
        self.pid = pid
        self.host = host
        # the member's telemetry endpoint (HELLO ``mport``): the cluster
        # observer discovers per-worker scrape targets from membership
        self.mport = int(mport) if mport else None
        # a pid is only probeable when the peer runs on THIS host; trusting
        # a remote pid would test an unrelated local process
        self.pid_is_local = (
            pid is not None
            and host is not None
            and host == socket.gethostname()
        )
        # proc start time pins WHICH process the pid names: supplied by
        # the member itself (HELLO pstart -- it read its own /proc/self),
        # else read here at registration (the member just contacted us,
        # so the pid is still overwhelmingly likely to be it)
        if pid_start is None and self.pid_is_local:
            pid_start = proc_start_time(pid)
        self.pid_start = pid_start
        self.registered_ms = now_ms
        self.last_contact_ms = now_ms
        self.exited = False

    def pid_gone(self) -> bool:
        """Local-pid death probe with pid-reuse protection: dead when the
        pid is gone, OR alive-but-not-ours (start time mismatch)."""
        if not self.pid_is_local:
            return False
        if not _pid_alive(self.pid):
            return True
        if self.pid_start is not None:
            cur = proc_start_time(self.pid)
            if cur is not None and cur != self.pid_start:
                return True  # pid recycled by an unrelated process
        return False


class ElasticSupervisor:
    """PS-side membership, death detection, and shard adoption orders.

    The :class:`~asyncframework_tpu.parallel.ps_dcn.ParameterServer` calls
    :meth:`register` (HELLO), :meth:`touch` + :meth:`owns` (every PULL and
    PUSH), :meth:`orders_for` (PULL replies), and
    :meth:`live_worker_count` (cohort clamp).  ``check_once`` is the
    monitor scan, exposed for deterministic tests.
    """

    def __init__(self, num_workers: int, dead_after_s: float = 5.0,
                 check_interval_s: float = 0.5, boot_grace_s: float = 10.0,
                 clock: Optional[Clock] = None, adopt: bool = True,
                 lease_s: Optional[float] = None,
                 suspect_after_s: Optional[float] = None,
                 fence: Optional[bool] = None):
        #: ``adopt=False`` is the serving-frontend mode
        #: (serving/frontend.py): the same HELLO registration, pid-probe +
        #: silence death detection, and rejoin revival -- but the slots
        #: are predict replicas, not shard servers, so dead slots are
        #: simply taken out of rotation (no adoption planning, no
        #: unclaimed-slot handout, and no process-global recovery-counter
        #: bumps -- the serving plane keeps its own counters).
        self._adopt = bool(adopt)
        self.num_workers = int(num_workers)
        # the membership LEASE: granted at register (HELLO), renewed by
        # any op (touch).  ``lease_s`` names what ``dead_after_s`` always
        # was -- silence past it expires the lease and declares death;
        # when given it overrides dead_after_s outright.
        if lease_s is not None and float(lease_s) > 0:
            dead_after_s = float(lease_s)
        self.dead_after_ms = float(dead_after_s) * 1e3
        self.lease_ms = self.dead_after_ms
        # the SUSPECT threshold: silence past this (default: half the
        # lease) marks the member suspected -- surfaced in membership and
        # routing, but no replacement is launched until the lease itself
        # expires.  A partitioned-but-alive member spends the partition
        # here instead of being double-served by a hasty replacement.
        self.suspect_after_ms = (
            float(suspect_after_s) * 1e3
            if suspect_after_s is not None and float(suspect_after_s) > 0
            else self.dead_after_ms / 2.0
        )
        self.check_interval_s = float(check_interval_s)
        self.boot_grace_ms = float(boot_grace_s) * 1e3
        # epoch fencing gate: epochs are only MINTED (and counted) when
        # fencing is on -- a fence-off run must not report fencing
        # activity its wire never carried.  None = conf-derived.
        if fence is None:
            from asyncframework_tpu.conf import FENCE_ENABLED, global_conf

            fence = bool(global_conf().get(FENCE_ENABLED))
        self.fence = bool(fence)
        self._clock = clock or SystemClock()
        # membership lock feeds the lock-order race detector when the
        # watchdog is armed (net/lockwatch.py named_lock); the
        # supervisor never does wire I/O under it, so watching it is
        # side-effect-free
        from asyncframework_tpu.net import lockwatch as _lockwatch

        self._lock = _lockwatch.named_lock("supervisor.members")
        self._t0 = self._clock.now_ms()
        self._owner: Dict[int, Optional[str]] = {
            w: None for w in range(self.num_workers)
        }
        self._state: Dict[int, str] = {
            w: UNKNOWN for w in range(self.num_workers)
        }
        self._contact_ms: Dict[int, Optional[float]] = {
            w: None for w in range(self.num_workers)
        }
        self._procs: Dict[str, _ProcRecord] = {}
        # adopter -> {orphan wid: order-issued ms}.  The timestamp bounds
        # how long an unacked order may sit with one adopter before the
        # orphan returns to the re-plan pool (an adopter whose
        # shard_factory keeps failing, or a classic client that ignores
        # orders, must not strand the shard forever)
        self._pending: Dict[str, Dict[int, float]] = {}
        # fencing epochs, one per slot: bumped BEFORE any replacement is
        # launched for a dead member, so the replacement's minted epoch
        # strictly dominates anything the deposed incarnation ever
        # stamped (parallel/ps_dcn.py REJECT_FENCED admission)
        self._epochs: Dict[int, int] = {}
        # latency suspicion overlay (net/health.py feeds it): advisory --
        # an RTT-suspect member keeps renewing its lease, so it is never
        # killed on latency alone, but membership/routing see SUSPECT
        self._rtt_suspect: Dict[int, str] = {}
        self.workers_lost = 0
        self.shards_adopted = 0
        self.rejoins = 0
        self.releases = 0
        self.suspicions = 0
        self.lease_expiries = 0
        self.leases_granted = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # set when the run completes: membership is frozen -- workers
        # legitimately go silent after DONE (evaluation phase, teardown)
        # and must not be declared dead / trigger pointless adoptions
        self._frozen = threading.Event()
        self._frozen_live_procs: Optional[List[str]] = None

    @classmethod
    def from_conf(cls, num_workers: int, conf=None) -> "ElasticSupervisor":
        from asyncframework_tpu.conf import (
            ELASTIC_BOOT_GRACE_S,
            ELASTIC_CHECK_INTERVAL_S,
            ELASTIC_DEAD_AFTER_S,
            LEASE_S,
            SUSPECT_AFTER_S,
            global_conf,
        )

        conf = conf if conf is not None else global_conf()
        return cls(
            num_workers,
            dead_after_s=conf.get(ELASTIC_DEAD_AFTER_S),
            check_interval_s=conf.get(ELASTIC_CHECK_INTERVAL_S),
            boot_grace_s=conf.get(ELASTIC_BOOT_GRACE_S),
            lease_s=conf.get(LEASE_S) or None,
            suspect_after_s=conf.get(SUSPECT_AFTER_S) or None,
        )

    # -------------------------------------------------------------- lifecycle
    def start(self) -> "ElasticSupervisor":
        with _active_lock:
            _active_sups.append(weakref.ref(self))
        self._thread = threading.Thread(
            target=self._run, name="elastic-supervisor", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        with _active_lock:
            _active_sups[:] = [r for r in _active_sups
                               if r() is not None and r() is not self]
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            self.check_once()

    # ------------------------------------------------------------ membership
    def register(self, proc: str, wids: Sequence[int],
                 pid: Optional[int] = None, host: Optional[str] = None,
                 pid_start: Optional[float] = None,
                 mport: Optional[int] = None) -> None:
        """HELLO: ``proc`` claims ``wids`` and is GRANTED a lease (renewed
        by any op via :meth:`touch`; expiry past ``lease_s`` of silence
        declares death).  A claim over a wid someone else currently
        serves is a REJOIN -- the old server's surrogate loop is deposed
        (it learns via RELEASED on its next pull).  ``pid_start`` is the
        member's own /proc start time (pid-reuse protection); ``mport``
        its telemetry port (observer discovery)."""
        now = self._clock.now_ms()
        with self._lock:
            self._procs[proc] = _ProcRecord(proc, now, pid=pid, host=host,
                                            pid_start=pid_start,
                                            mport=mport)
            self.leases_granted += 1
            for wid in wids:
                wid = int(wid)
                if wid not in self._owner:
                    continue
                prev = self._owner.get(wid)
                rejoined = (prev not in (None, proc)
                            or self._state.get(wid) == DEAD)
                self._owner[wid] = proc
                if prev not in (None, proc):
                    self.releases += 1
                    if self._adopt:
                        bump_total("releases")
                    pend = self._pending.get(prev)
                    if pend is not None:
                        pend.pop(wid, None)
                if rejoined:
                    self.rejoins += 1
                    if self._adopt:
                        bump_total("rejoins")
                self._state[wid] = LIVE
                self._contact_ms[wid] = now
                # the claim supersedes any in-flight adoption order
                for pend in self._pending.values():
                    pend.pop(wid, None)

    def touch(self, wid: int, proc: Optional[str] = None) -> None:
        """Contact from ``proc`` serving ``wid`` (every PULL/PUSH): the
        lease renewal.  Clears silence-suspicion (the member answered);
        latency suspicion (:meth:`suspect`) survives contact by design --
        a gray member's whole signature is that it keeps answering."""
        now = self._clock.now_ms()
        with self._lock:
            if wid in self._state:
                self._contact_ms[wid] = now
                # contact from the CURRENT owner revives the slot (covers
                # the adopter's first pull for a dead wid); contact from a
                # deposed process must not resurrect it
                if (self._state[wid] != DEAD
                        or proc is None
                        or self._owner.get(wid) in (None, proc)):
                    self._state[wid] = LIVE
            if proc is not None:
                rec = self._procs.get(proc)
                if rec is None:
                    # implicit registration: a restarted PS rebuilds its
                    # membership from live traffic (workers never re-HELLO
                    # a server they do not know restarted)
                    rec = _ProcRecord(proc, now)
                    self._procs[proc] = rec
                rec.last_contact_ms = now
                rec.exited = False

    def owns(self, proc: Optional[str], wid: int) -> bool:
        """Is ``proc`` the current server of ``wid``?  Unowned wids are
        claimed on first contact (restart recovery); a claim against a
        dead/vanished owner succeeds; a claim against a live owner fails
        -- the caller answers RELEASED and the surrogate stands down."""
        if proc is None:
            return True  # unelastic client: no membership discipline
        now = self._clock.now_ms()
        with self._lock:
            if wid not in self._owner:
                return True
            owner = self._owner.get(wid)
            if owner is None or owner == proc:
                self._owner[wid] = proc
                return True
            rec = self._procs.get(owner)
            owner_dead = (
                rec is None
                or rec.exited
                or rec.pid_gone()
                or now - max(rec.last_contact_ms, rec.registered_ms)
                > self.dead_after_ms
            )
            if owner_dead:
                self._owner[wid] = proc
                if self._state.get(wid) == DEAD:
                    self._state[wid] = LIVE
                return True
            return False

    def orders_for(self, proc: Optional[str]) -> List[int]:
        """Orphan wids ``proc`` has been assigned to adopt.  Re-delivered
        on every pull until the adopter's first pull FOR the orphan lands
        (``ack_adoption`` below) -- adoption must survive a lost reply."""
        if proc is None:
            return []
        with self._lock:
            return sorted(self._pending.get(proc, ()))

    def ack_adoption(self, proc: Optional[str], wid: int) -> None:
        """The adopter is now serving ``wid`` (its first pull arrived)."""
        if proc is None:
            return
        with self._lock:
            pend = self._pending.get(proc)
            if pend is not None:
                pend.pop(wid, None)

    # ------------------------------------------------------------- suspicion
    def suspect(self, wid: int, reason: str = "rtt") -> None:
        """External suspicion input (gray-failure detection,
        net/health.py): mark ``wid`` SUSPECT without touching its lease.
        Advisory -- routing demotes it, membership surfaces it, but only
        lease expiry or process exit escalates to DEAD."""
        with self._lock:
            if wid not in self._state or self._state.get(wid) == DEAD:
                return
            if wid not in self._rtt_suspect:
                self._rtt_suspect[wid] = str(reason)
                self.suspicions += 1
                if self._adopt:
                    bump_total("suspicions")

    def unsuspect(self, wid: int) -> None:
        """The latency normalized: clear the external suspicion."""
        with self._lock:
            self._rtt_suspect.pop(wid, None)

    def state_of(self, wid: int) -> str:
        """The slot's effective state: DEAD dominates, then any
        suspicion (silence-based or latency-based), then the base
        state."""
        with self._lock:
            return self._state_of_locked(wid)

    def _state_of_locked(self, wid: int) -> str:
        base = self._state.get(wid, UNKNOWN)
        if base == DEAD:
            return DEAD
        if wid in self._rtt_suspect:
            return SUSPECT
        return base

    # ---------------------------------------------------------------- epochs
    def epoch_of(self, wid: int) -> int:
        """Fencing-epoch bumps minted for this slot (0 = never fenced).
        A replacement for slot ``wid`` runs at base_epoch + epoch_of(wid);
        see parallel/shardgroup.py / parallel/ps_dcn.py."""
        with self._lock:
            return self._epochs.get(int(wid), 0)

    def _live_procs_locked(self, now: float) -> List[str]:
        return [
            p for p, rec in self._procs.items()
            if not rec.exited
            and now - max(rec.last_contact_ms, rec.registered_ms)
            <= self.dead_after_ms
        ]

    def freeze(self) -> None:
        """The run is DONE: pin the live-process set and stop declaring
        deaths.  Post-done silence (evaluation, teardown) is normal."""
        now = self._clock.now_ms()
        with self._lock:
            if self._frozen_live_procs is None:
                self._frozen_live_procs = self._live_procs_locked(now)
        self._frozen.set()

    def live_proc_count(self) -> int:
        """Worker processes currently considered alive (frozen at DONE).
        Bounds how many end-of-run EVAL results can still arrive."""
        now = self._clock.now_ms()
        with self._lock:
            if self._frozen_live_procs is not None:
                return len(self._frozen_live_procs)
            return len(self._live_procs_locked(now))

    # ------------------------------------------------------------- liveness
    def live_worker_count(self) -> int:
        """Workers not currently declared dead (UNKNOWN counts live so the
        first waves are not artificially small)."""
        with self._lock:
            return sum(1 for s in self._state.values() if s != DEAD)

    def check_once(self) -> List[int]:
        """One monitor scan; returns newly-dead wids (test-friendly)."""
        if self._frozen.is_set():
            return []
        now = self._clock.now_ms()
        newly_dead: List[int] = []
        expired: List[int] = []
        with self._lock:
            # 1. process-exit detection (local pids only): immediate
            # death, no silence window.  pid_gone() also catches a
            # recycled pid -- alive, but not the process that registered.
            for rec in self._procs.values():
                if not rec.exited and rec.pid_gone():
                    rec.exited = True
            live_procs = self._live_procs_locked(now)
            # 2. per-worker death: owner exited, or the LEASE expired
            # (silence past the bound).  Silence past the suspect
            # threshold but inside the lease marks SUSPECT -- surfaced,
            # demoted in routing, but no replacement yet: a partitioned
            # member that heals inside its lease rejoins with nothing to
            # undo.
            for wid in range(self.num_workers):
                if self._state[wid] == DEAD:
                    continue
                owner = self._owner.get(wid)
                contact = self._contact_ms.get(wid)
                if owner is not None:
                    rec = self._procs.get(owner)
                    base = contact if contact is not None else (
                        rec.registered_ms if rec is not None else self._t0
                    )
                    exited = rec is not None and rec.exited
                    if exited or now - base > self.dead_after_ms:
                        newly_dead.append(wid)
                        if not exited:
                            expired.append(wid)
                    elif (self._state[wid] == LIVE
                          and now - base > self.suspect_after_ms):
                        self._state[wid] = SUSPECT
                        self.suspicions += 1
                        if self._adopt:
                            bump_total("suspicions")
                else:
                    # unclaimed slot: nobody ever served this shard.  After
                    # the boot grace (and once there IS someone to adopt
                    # it), hand it out rather than strand its data.  In
                    # serving mode (adopt=False) unclaimed slots are just
                    # unused registration capacity -- never "dead".
                    if (self._adopt and live_procs
                            and now - self._t0 > max(self.boot_grace_ms,
                                                     self.dead_after_ms)):
                        newly_dead.append(wid)
            for wid in newly_dead:
                self._state[wid] = DEAD
                self._rtt_suspect.pop(wid, None)
                self.workers_lost += 1
                if self.fence:
                    # mint the fencing epoch BEFORE any replacement
                    # exists: whatever the deposed incarnation stamped
                    # is now, by construction, a stale epoch its
                    # successor's admission rejects (REJECT_FENCED).
                    # The process-global epoch_bumps COUNTER is bumped
                    # only where a minted epoch actually reaches the
                    # wire (shardgroup.ShardGroup's fenced relaunch) --
                    # worker/replica slots keep their ledger here in
                    # membership() without inflating the metric.
                    self._epochs[wid] = self._epochs.get(wid, 0) + 1
                if wid in expired:
                    self.lease_expiries += 1
                if self._adopt:
                    bump_total("workers_lost")
                    if wid in expired:
                        bump_total("lease_expiries")
            # 3. (re-)plan adoption for every dead wid lacking a live,
            # FRESH pending adopter -- covers adopters that died
            # mid-adoption AND adopters that never act on an order (a
            # failing shard_factory, a classic client ignoring orders):
            # an order older than the expiry returns to the pool
            order_expiry_ms = 2.0 * self.dead_after_ms
            pending_live: Set[int] = set()
            for p, pend in self._pending.items():
                for w, issued in list(pend.items()):
                    if p in live_procs and now - issued <= order_expiry_ms:
                        pending_live.add(w)
                    else:
                        pend.pop(w)  # expired/dead adopter: replan below
            orphans = [
                wid for wid in range(self.num_workers)
                if self._state[wid] == DEAD and wid not in pending_live
            ]
            if orphans and live_procs and self._adopt:
                from asyncframework_tpu.engine.recovery import (
                    plan_reassignment,
                )

                owned: Dict[str, int] = {p: 0 for p in live_procs}
                for wid, owner in self._owner.items():
                    if owner in owned and self._state[wid] != DEAD:
                        owned[owner] += 1
                plan = plan_reassignment(live_procs, orphans, load=owned)
                for wid, adopter in plan.moves.items():
                    self._owner[wid] = adopter
                    self._pending.setdefault(adopter, {})[wid] = now
                    self.shards_adopted += 1
                    bump_total("shards_adopted")
        for wid in newly_dead:
            # flight-recorder breadcrumb, outside the membership lock: a
            # post-mortem dump shows WHO this process declared dead and
            # when (no-op when no recorder is installed)
            _flight.note("member_dead", wid=int(wid),
                         adopt=bool(self._adopt))
        return newly_dead

    # ----------------------------------------------------------- diagnostics
    def counters(self) -> Dict[str, int]:
        with self._lock:
            return {
                "workers_lost": self.workers_lost,
                "shards_adopted": self.shards_adopted,
                "rejoins": self.rejoins,
                "releases": self.releases,
                "suspicions": self.suspicions,
                "lease_expiries": self.lease_expiries,
                "leases_granted": self.leases_granted,
            }

    def proc_records(self) -> List[Dict]:
        """Per-registered-process view (observer discovery): token, pid,
        host, telemetry port, exit flag."""
        with self._lock:
            return [
                {"proc": rec.token, "pid": rec.pid, "host": rec.host,
                 "mport": rec.mport, "exited": rec.exited}
                for rec in self._procs.values()
            ]

    def membership(self) -> Dict[int, Dict]:
        """Per-worker view for the PS's wait_done diagnostic: effective
        state (suspicion overlaid), owner, silence, remaining lease, and
        the slot's fencing epoch."""
        now = self._clock.now_ms()
        with self._lock:
            out = {}
            for wid in range(self.num_workers):
                contact = self._contact_ms.get(wid)
                out[wid] = {
                    "state": self._state_of_locked(wid),
                    "owner": self._owner.get(wid),
                    "silence_ms": (
                        None if contact is None else round(now - contact, 1)
                    ),
                    "lease_left_ms": (
                        None if contact is None
                        else round(self.lease_ms - (now - contact), 1)
                    ),
                    "epoch": self._epochs.get(wid, 0),
                }
            return out
