"""Adaptive asynchrony controller: close the loop from telemetry to knobs.

Twelve PRs of instrumentation measure everything about an async run --
per-worker staleness in versions AND ms, per-stage trace percentiles,
per-endpoint RTT EWMAs, prefetch-hit/stall counters, merge-queue depth,
and (PR 14) the cluster-wide observer view -- yet every
performance-critical knob (`b`, `async.pipeline.depth`,
`async.push.merge`, step size) was static conf, hand-tuned per
deployment.  This module is the ASYNC paper's second pillar (*history*:
staleness-aware updates, arXiv:1907.08526) made actionable, with the
delay-adaptive step sizes of "Faster Asynchronous SGD" (arXiv:1601.04033)
as the damping law.

One :class:`AsyncController` runs on the primary PS.  Every tick it
reads the observed signals and re-evaluates four knob targets:

- **step damping** (``async.step.size`` tunable): installs the bounded
  ``1/(1 + tau - free)`` law the PS drain applies per accepted push
  (exact and per-item -- the damp factor rides the merge kernel's mask
  slot, so dedup/replay semantics are untouched), plus per-worker extra
  damp factors for observer-flagged stragglers;
- **cohort size** (``async.bucket.ratio`` tunable): re-clamps the
  partial-barrier ``b`` between the declared floor/ceiling from the
  observed straggler spread, so one DELAYed worker stops gating every
  wave;
- **pipeline depth** (``async.pipeline.depth`` tunable): auto-sizes the
  live in-flight window from measured pull/push RTT vs compute time,
  nudged by the PR 5 prefetch-hit and stall counters;
- **push-merge budget** (``async.push.merge`` tunable): resizes the
  fused-drain budget from merge-queue depth vs push rate (never past
  the compiled bound).

Decisions are guarded twice -- a relative HYSTERESIS dead-band plus a
per-knob cooldown, and an oscillation guard that freezes a knob whose
direction reverses too often -- then propagate through the existing
SETMAP/WELCOME control path as a CTRL payload next to the shard map and
epoch vector (fence-stamped: a deposed controller's decision is refused
by a promoted member).  With ``async.control.enabled`` off nothing here
runs and the wire is byte-identical to the knob being absent.

The controller may only actuate DECLARED tunables: every knob in
:data:`CONTROLLER_TUNABLES` must be a registered ``ConfigEntry`` with
``tunable=True`` and floor/ceiling bounds, and every ``_actuate`` call
names one -- async-lint's ``conf-tunable`` rule enforces both statically
(mutation-tested: undeclaring a tunable or actuating an undeclared key
fails lint), and :meth:`AsyncController._actuate` enforces it at
runtime.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from asyncframework_tpu.metrics import flightrec as _flight
from asyncframework_tpu.utils.threads import guarded

#: the declared actuation surface: tunable conf key -> the CTRL wire
#: field the decision lands in.  async-lint cross-checks every key here
#: (and every ``_actuate`` literal) against conf.py's tunable registry.
CONTROLLER_TUNABLES: Dict[str, str] = {
    "async.step.size": "damp",
    "async.bucket.ratio": "b",
    "async.pipeline.depth": "depth",
    "async.push.merge": "merge",
}

# ------------------------------------------------------------- counters
_TOTALS_LOCK = threading.Lock()
_TOTALS: Dict[str, int] = {}
_KEYS = ("ticks", "decisions", "changes", "clamps", "osc_trips",
         "stale_rejects", "wdamp_set")


def control_totals() -> Dict[str, int]:
    """Process-global controller counters (the ``control`` counter
    family): ticks run, decisions evaluated, knob CHANGES shipped (the
    ``controller_converged`` SLO watches their rate), targets clamped
    at a bound, oscillation-guard trips, stale CTRL installs refused,
    per-worker damp table updates."""
    with _TOTALS_LOCK:
        return {k: _TOTALS.get(k, 0) for k in _KEYS}


def reset_control_totals() -> None:
    with _TOTALS_LOCK:
        _TOTALS.clear()


def _bump(key: str, n: int = 1) -> None:
    with _TOTALS_LOCK:
        _TOTALS[key] = _TOTALS.get(key, 0) + n


# ------------------------------------------------------------ ctrl wire
def ctrl_seq(wire: Optional[dict]) -> Tuple[int, int]:
    """(epoch, seq) ordering key of a CTRL payload; (0, -1) for None."""
    if not wire:
        return (0, -1)
    return (int(wire.get("ep", 0) or 0), int(wire.get("seq", -1)))


class ControlSink:
    """Client-side CTRL receiver (one per worker process).

    The PS attaches the current CTRL payload to a PULL reply whenever
    the request's ``cs`` stamp is older than the newest decision;
    :meth:`install` folds it monotonically by (epoch, seq) -- a stale
    payload from a lagging shard can never roll a newer decision back.
    The pipelined worker loop reads :meth:`depth` each iteration to
    size its live in-flight window."""

    def __init__(self, wire: Optional[dict] = None):
        self._lock = threading.Lock()
        self._wire: Optional[dict] = None
        if wire:
            self.install(wire)

    def install(self, wire: dict) -> bool:
        with self._lock:
            if ctrl_seq(wire) <= ctrl_seq(self._wire):
                return False
            self._wire = dict(wire)
            return True

    @property
    def seq(self) -> int:
        with self._lock:
            return int((self._wire or {}).get("seq", -1))

    @property
    def stamp(self) -> list:
        """The installed decision stamp as ``[epoch, seq]`` -- what PULL
        requests carry as ``cs``.  Both halves matter: a restarted
        controller under a freshly minted epoch starts seq over, and a
        bare-seq compare would never re-deliver its decisions."""
        with self._lock:
            return [int((self._wire or {}).get("ep", 0) or 0),
                    int((self._wire or {}).get("seq", -1))]

    def depth(self, configured: int) -> int:
        """Effective pipeline depth: the controller's target clamped to
        [1, configured].  The loop SHAPE (serial vs pipelined) is chosen
        at worker start, so a 0/absent target keeps the configured
        depth and the controller never flips a loop serial<->pipelined
        mid-run."""
        with self._lock:
            d = int((self._wire or {}).get("depth", 0) or 0)
        if d <= 0:
            return configured
        return max(1, min(configured, d))

    def wire(self) -> Optional[dict]:
        with self._lock:
            return dict(self._wire) if self._wire else None


# ----------------------------------------------------------- controller
class _Knob:
    """Per-knob actuation state: current value, hysteresis/cooldown
    bookkeeping, and the oscillation guard (direction-reversal counting
    within a sliding freeze window)."""

    def __init__(self, name: str, value: float):
        self.name = name
        self.value = value
        self.last_change_t: Optional[float] = None
        self.last_dir = 0
        self.reversals: List[float] = []  # times of direction reversals
        self.frozen_until: Optional[float] = None
        self.changes = 0

    def frozen(self, now: float) -> bool:
        if self.frozen_until is None:
            return False
        if now >= self.frozen_until:
            self.frozen_until = None
            self.reversals.clear()
            self.last_dir = 0
            return False
        return True


class AsyncController:
    """The closed loop: signals -> decisions -> CTRL actuation.

    ``ps`` is the primary :class:`~asyncframework_tpu.parallel.ps_dcn.
    ParameterServer` (decisions install locally via ``set_control``),
    ``group`` an optional ShardGroup (decisions re-SETMAP to every
    member, surviving shard relaunches and standby promotions),
    ``observer`` an optional ClusterObserver whose derived straggler
    scores refine the per-worker damp table.  ``now_fn`` makes every
    guard ManualClock-testable."""

    def __init__(self, ps, conf=None, group=None, observer=None,
                 now_fn: Callable[[], float] = time.monotonic):
        from asyncframework_tpu.conf import (
            CONTROL_COOLDOWN_S,
            CONTROL_DAMP_FREE,
            CONTROL_HYSTERESIS,
            CONTROL_INTERVAL_S,
            CONTROL_OSC_FREEZE_S,
            CONTROL_OSC_REVERSALS,
            OBSERVER_STRAGGLER_FACTOR,
            global_conf,
            registry,
        )

        conf = conf if conf is not None else global_conf()
        self.ps = ps
        self.group = group
        self.observer = observer
        self._now = now_fn
        self.cfg = ps.cfg
        self.interval_s = float(conf.get(CONTROL_INTERVAL_S))
        self.hysteresis = max(0.0, float(conf.get(CONTROL_HYSTERESIS)))
        self.cooldown_s = max(0.0, float(conf.get(CONTROL_COOLDOWN_S)))
        self.osc_reversals = max(2, int(conf.get(CONTROL_OSC_REVERSALS)))
        self.osc_freeze_s = max(0.0, float(conf.get(CONTROL_OSC_FREEZE_S)))
        self.straggler_factor = max(
            1.0, float(conf.get(OBSERVER_STRAGGLER_FACTOR)))
        #: declared bounds, read off the tunable ConfigEntries -- the
        #: ONE place floor/ceiling live (async-lint pins their presence)
        reg = registry()
        self._bounds: Dict[str, Tuple[float, float]] = {}
        for key in CONTROLLER_TUNABLES:
            entry = reg.get(key)
            if entry is None or not getattr(entry, "tunable", False) \
                    or entry.floor is None or entry.ceiling is None:
                raise ValueError(
                    f"controller tunable {key!r} is not a declared "
                    f"tunable ConfigEntry with floor/ceiling bounds")
            self._bounds[key] = (float(entry.floor), float(entry.ceiling))
        self.damp_floor = self._bounds["async.step.size"][0]
        # configured baselines: the ceilings actuation can restore to
        self.b_conf = max(1, int(self.cfg.bucket_threshold))
        pd = getattr(self.cfg, "pipeline_depth", None)
        if pd is None:
            from asyncframework_tpu.conf import PIPELINE_DEPTH

            pd = conf.get(PIPELINE_DEPTH)
        self.depth_conf = max(0, int(pd))
        # damping law constants (installed once, per-item application
        # happens in the PS drain): free staleness slack defaults to
        # P + depth + 2 -- steady-state async staleness is ~P-1 PLUS
        # the pipelined in-flight window, and damping the healthy
        # steady state just slows convergence at a fixed iteration
        # budget; only ABNORMAL delay should damp
        free = float(conf.get(CONTROL_DAMP_FREE))
        self.damp_free = (
            float(self.cfg.num_workers + self.depth_conf + 2)
            if free < 0 else free)
        self.merge_conf = max(1, int(getattr(ps, "_merge_max", 1)))
        # knob state (started at the configured/static values)
        now = self._now()
        self._knobs: Dict[str, _Knob] = {
            "b": _Knob("b", float(self.b_conf)),
            "depth": _Knob("depth", float(self.depth_conf)),
            "merge": _Knob("merge", float(self.merge_conf)),
            # guard state for the per-worker damp TABLE: value tracks
            # the table size; the cooldown/oscillation machinery is
            # what matters (a score hovering at the flag threshold must
            # not emit a decision per tick)
            "wdamp": _Knob("wdamp", 0.0),
        }
        self._wdamp: Dict[int, float] = {}
        self._seq = 0
        self._t0 = now
        self._queue_ewma: Optional[float] = None
        self._last_decision: Optional[Dict[str, object]] = None
        # bounded decision trace (bench.py --dcn adaptive arm records
        # it; the flight recorder gets per-change breadcrumbs too)
        self._decisions: List[Dict[str, object]] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ts_source = None
        self._status_section = None

    # ------------------------------------------------------------ wiring
    def start(self) -> "AsyncController":
        """Install the initial CTRL (damping law active from tick 0),
        register the ``control`` telemetry source + status section, and
        start the decision loop."""
        self._install(reason="controller start")
        from asyncframework_tpu.metrics import live as _live
        from asyncframework_tpu.metrics import timeseries as _ts

        self._ts_source = self._telemetry_source
        _ts.register_source("control", self._ts_source)
        self._status_section = self.status
        _live.register_status_section("control", self._status_section)
        _ts.ensure_started()
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=guarded(self._loop), name="async-controller",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        from asyncframework_tpu.metrics import live as _live
        from asyncframework_tpu.metrics import timeseries as _ts

        if self._ts_source is not None:
            _ts.unregister_source("control", self._ts_source)
        if self._status_section is not None:
            _live.unregister_status_section("control",
                                            self._status_section)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 -- a bad tick must never
                pass           # kill the control loop; next tick retries

    # ----------------------------------------------------------- signals
    def _signals(self) -> Dict[str, object]:
        """One coherent read of the input surface: PS-local per-worker
        stats + scalars, process-global pipeline counters, and the
        observer's derived fleet signals when one is attached."""
        from asyncframework_tpu.parallel import ps_dcn as _ps_mod

        sig: Dict[str, object] = {
            "workers": self.ps.worker_stats(),
            "ps": self.ps.control_signals(),
            "pipeline": _ps_mod.pipeline_totals(),
        }
        sup = getattr(self.ps, "supervisor", None)
        if sup is not None:
            # partition-tolerant membership (PR 9): a SUSPECT worker
            # (missed lease renewal, gray-RTT outlier) is a straggler
            # the moment the supervisor says so -- no need to wait for
            # its inter-arrival EWMA to drift
            try:
                from asyncframework_tpu.parallel import (
                    supervisor as _sup_mod,
                )

                sig["suspects"] = [
                    w for w, m in sup.membership().items()
                    if m.get("state") == _sup_mod.SUSPECT
                ]
            except Exception:  # noqa: BLE001 -- telemetry only
                pass
        if self.observer is not None:
            try:
                sig["observer"] = self.observer.derived()
                sig["stragglers"] = self.observer.stragglers()
            except Exception:  # noqa: BLE001 -- observer optional
                pass
        return sig

    @staticmethod
    def _median(vals: List[float]) -> Optional[float]:
        if not vals:
            return None
        import statistics

        return float(statistics.median(vals))

    # --------------------------------------------------------- decisions
    def tick(self) -> Dict[str, object]:
        """One decision pass: read signals, re-evaluate every knob
        target through hysteresis/cooldown/oscillation guards, install
        a new CTRL payload if anything changed.  Returns the decision
        record (what changed and why; empty ``changed`` = no-op tick)."""
        _bump("ticks")
        sig = self._signals()
        now = self._now()
        changed: List[Dict[str, object]] = []
        with self._lock:
            changed += self._decide_b(sig, now)
            changed += self._decide_depth(sig, now)
            changed += self._decide_merge(sig, now)
            changed += self._decide_wdamp(sig, now)
            record: Dict[str, object] = {
                "t": round(now - self._t0, 3),
                "changed": changed,
                "knobs": {n: k.value for n, k in self._knobs.items()},
            }
            if changed:
                self._last_decision = {
                    **changed[-1], "t": record["t"],
                }
                for c in changed:
                    self._decisions.append({**c, "t": record["t"]})
                del self._decisions[:-256]
        if changed:
            _bump("changes", len(changed))
            reason = "; ".join(str(c["reason"]) for c in changed)
            self._install(reason=reason)
            for c in changed:
                _flight.note("control", knob=c["knob"], frm=c["from"],
                             to=c["to"], reason=c["reason"])
        return record

    def _actuate(self, key: str, knob: _Knob, target: float, now: float,
                 reason: str, lo: float, hi: float,
                 band: Optional[float] = None
                 ) -> List[Dict[str, object]]:
        """The ONE choke point every knob change goes through: clamp to
        the declared bounds, apply the hysteresis dead-band and
        cooldown, run the oscillation guard, then commit.  ``key`` must
        name a declared tunable (async-lint checks the literals at this
        call's sites; this check is the runtime backstop).

        ``band`` overrides the dead-band: multiplicative knobs (merge,
        depth) default to ``max(1, cur * hysteresis)`` so noise-scale
        drifts never actuate; the cohort passes ``band=1`` -- its
        signal (the straggler COUNT) is already quantized, and dropping
        exactly one straggler from the wave is the whole point."""
        if key not in CONTROLLER_TUNABLES:
            raise ValueError(f"actuating undeclared tunable {key!r}")
        _bump("decisions")
        clamped = min(max(target, lo), hi)
        if clamped != target:
            _bump("clamps")
        target = clamped
        cur = knob.value
        if target == cur:
            return []
        if band is None:
            band = max(1.0, abs(cur) * self.hysteresis)
        if abs(target - cur) < band:
            return []
        if knob.frozen(now):
            return []
        if (knob.last_change_t is not None
                and now - knob.last_change_t < self.cooldown_s):
            return []
        direction = 1 if target > cur else -1
        if knob.last_dir and direction != knob.last_dir:
            knob.reversals = [t for t in knob.reversals
                              if now - t <= self.osc_freeze_s]
            knob.reversals.append(now)
            if len(knob.reversals) >= self.osc_reversals:
                # flapping: the signals are pushing the knob back and
                # forth faster than its effects can settle -- freeze it
                knob.frozen_until = now + self.osc_freeze_s
                _bump("osc_trips")
                _flight.note("control", knob=knob.name, frozen=True,
                             reason="oscillation guard")
                return []
        knob.last_dir = direction
        knob.last_change_t = now
        knob.changes += 1
        knob.value = target
        return [{"knob": knob.name, "from": cur, "to": target,
                 "reason": reason}]

    def _decide_b(self, sig: Dict[str, object], now: float
                  ) -> List[Dict[str, object]]:
        """Cohort size from observed straggler spread: each worker whose
        push inter-arrival EWMA exceeds ``straggler_factor`` x the peer
        median (or whom the observer flags) stops being waited for --
        the wave threshold drops by one per straggler, clamped to the
        declared bounds, and recovers to the configured b when the
        spread closes."""
        ws: Dict[str, dict] = sig.get("workers") or {}
        ivs = {w: st.get("interval_ms") for w, st in ws.items()
               if st.get("interval_ms") is not None
               and st.get("accepted", 0) >= 3}
        flagged = set()
        # peer median EXCLUDING self (the observer's straggler stance):
        # a 2-worker cohort can still flag a 10x member, and one slow
        # worker cannot drag the whole cohort's median up to itself
        for w, iv in ivs.items():
            peers = [v for p, v in ivs.items() if p != w]
            med = self._median(peers)
            if med and med > 0 and iv / med >= self.straggler_factor:
                flagged.add(w)
        for w, s in (sig.get("stragglers") or {}).items():
            if s.get("flagged"):
                flagged.add(str(w))
        for w in sig.get("suspects") or ():
            flagged.add(str(w))
        p = max(1, int(self.cfg.num_workers))
        lo_f, hi_f = self._bounds["async.bucket.ratio"]
        lo = max(1.0, math.ceil(lo_f * p))
        hi = float(min(self.b_conf, max(1, math.floor(hi_f * p))))
        target = float(self.b_conf - len(flagged))
        reason = (f"{len(flagged)} straggler(s) {sorted(flagged)} "
                  f"excluded from the wave"
                  if flagged else "no straggler spread; restore conf b")
        return self._actuate("async.bucket.ratio", self._knobs["b"],
                             target, now, reason, lo, hi, band=1.0)

    def _decide_depth(self, sig: Dict[str, object], now: float
                      ) -> List[Dict[str, object]]:
        """Pipeline depth from measured RTT vs compute: the window must
        hold ~1 + rtt/compute in-flight updates to hide the round trips;
        the PR 5 prefetch stall counters nudge the formula when reality
        disagrees (stalls = window too shallow)."""
        if self.depth_conf <= 0:
            return []  # serial loops: the shape was chosen at start
        ws: Dict[str, dict] = sig.get("workers") or {}
        rtts = [st["rtt_ms"] for st in ws.values()
                if st.get("rtt_ms") is not None]
        comps = [st["compute_ms"] for st in ws.values()
                 if st.get("compute_ms") is not None]
        rtt, comp = self._median(rtts), self._median(comps)
        if rtt is None or comp is None:
            return []  # no latency decomposition yet: keep the conf
        target = 1.0 + rtt / max(comp, 0.1)
        pl = sig.get("pipeline") or {}
        hits = int(pl.get("prefetch_hits", 0))
        waits = int(pl.get("prefetch_waits", 0))
        if hits + waits >= 16 and waits / (hits + waits) > 0.25:
            target += 1.0  # the prefetch keeps stalling: go deeper
        target = float(round(target))
        lo, hi = self._bounds["async.pipeline.depth"]
        hi = min(hi, float(self.depth_conf))
        return self._actuate(
            "async.pipeline.depth", self._knobs["depth"], target, now,
            f"rtt~{rtt:.1f}ms vs compute~{comp:.1f}ms "
            f"(stalls {waits}/{hits + waits})", lo, hi)

    def _decide_merge(self, sig: Dict[str, object], now: float
                      ) -> List[Dict[str, object]]:
        """Push-merge budget from merge-queue pressure: a backlog that
        keeps pace with the budget means the apply plane is the
        bottleneck -- widen the fused drain (fewer dispatches per
        push); an empty queue shrinks it back toward the single-push
        latency path.  EWMA-smoothed so one burst does not actuate."""
        ps_sig = sig.get("ps") or {}
        q = float(ps_sig.get("queue_depth", 0) or 0)
        a = 0.3
        self._queue_ewma = (q if self._queue_ewma is None
                            else a * q + (1 - a) * self._queue_ewma)
        qe = self._queue_ewma
        cur = self._knobs["merge"].value
        if qe >= 0.75 * cur:
            target = cur * 2.0
        elif qe <= 0.125 * cur:
            target = max(qe * 2.0, cur / 2.0)
        else:
            target = cur
        target = float(round(target))
        lo, hi = self._bounds["async.push.merge"]
        hi = min(hi, float(self.merge_conf))
        return self._actuate(
            "async.push.merge", self._knobs["merge"], target, now,
            f"merge queue ewma {qe:.2f} vs budget {cur:g}", lo, hi)

    def _decide_wdamp(self, sig: Dict[str, object], now: float
                      ) -> List[Dict[str, object]]:
        """Per-worker damp table from observer straggler scores: a
        flagged worker's pushes get an EXTRA bounded damp factor
        (1/score, floored at the step tunable's floor) on top of the
        per-item staleness law -- the observer sees dimensions the PS
        drain cannot (cross-role RTT, compute skew).  Cleared when the
        flag clears."""
        table: Dict[int, float] = {}
        for w, s in (sig.get("stragglers") or {}).items():
            score = s.get("score")
            if s.get("flagged") and score:
                try:
                    wid = int(w)
                except (TypeError, ValueError):
                    continue
                table[wid] = round(
                    max(self.damp_floor, 1.0 / float(score)), 4)
        if table == self._wdamp:
            return []
        # the table change rides the SAME guard machinery as the scalar
        # knobs (module contract: every decision is guarded) -- a score
        # hovering at the flag threshold must not emit a decision, a
        # group fan-out, and a CTRL re-delivery per tick
        knob = self._knobs["wdamp"]
        now_ = now
        if knob.frozen(now_):
            return []
        if (knob.last_change_t is not None
                and now_ - knob.last_change_t < self.cooldown_s):
            return []
        if set(table) == set(self._wdamp) and all(
                abs(table[w] - self._wdamp[w])
                <= self.hysteresis * max(self._wdamp[w], 1e-6)
                for w in table):
            return []  # same flagged set, factors within the dead-band
        direction = (1 if len(table) > len(self._wdamp)
                     else -1 if len(table) < len(self._wdamp)
                     else knob.last_dir or 1)
        if knob.last_dir and direction != knob.last_dir:
            knob.reversals = [t for t in knob.reversals
                              if now_ - t <= self.osc_freeze_s]
            knob.reversals.append(now_)
            if len(knob.reversals) >= self.osc_reversals:
                # the flag set is flapping (add/remove/add...): freeze
                # the table at its current value, exactly like a
                # flapping scalar knob
                knob.frozen_until = now_ + self.osc_freeze_s
                _bump("osc_trips")
                _flight.note("control", knob="wdamp", frozen=True,
                             reason="oscillation guard")
                return []
        knob.last_dir = direction
        knob.last_change_t = now_
        knob.changes += 1
        knob.value = float(len(table))
        prev, self._wdamp = self._wdamp, table
        _bump("wdamp_set")
        # the wdamp table rides the damp tunable's actuation surface
        # (it scales the same effective step the tau law scales)
        _bump("decisions")
        return [{"knob": "wdamp", "from": prev, "to": dict(table),
                 "reason": "observer straggler flags -> per-worker damp"}]

    # -------------------------------------------------------- actuation
    def ctrl_wire(self) -> dict:
        """The CTRL payload (JSON-able) the PS serves on WELCOME/PULL
        and the group SETMAPs to every member: monotone (ep, seq) stamp
        + the four knob decisions.  ``b``/``depth``/``merge`` of 0 mean
        "no override" (receivers keep their configured value)."""
        with self._lock:
            b = int(self._knobs["b"].value)
            depth = int(self._knobs["depth"].value)
            merge = int(self._knobs["merge"].value)
            wire = {
                "seq": self._seq,
                "ep": int(getattr(self.ps, "epoch", 0) or 0),
                # the per-item damping law: [coeff, floor, free_slack]
                "damp": [1.0, self.damp_floor, self.damp_free],
                "b": b if b != self.b_conf else 0,
                "depth": depth if depth != self.depth_conf else 0,
                "merge": merge if merge != self.merge_conf else 0,
            }
            if self._wdamp:
                wire["wdamp"] = {str(w): f
                                 for w, f in self._wdamp.items()}
            return wire

    def _install(self, reason: str) -> None:
        with self._lock:
            self._seq += 1
        wire = self.ctrl_wire()
        self.ps.set_control(wire)
        if self.group is not None:
            try:
                self.group.install_ctrl(wire)
            except Exception:  # noqa: BLE001 -- a dark member heals
                pass           # via the next SETMAP re-announce
        _flight.note("control", seq=wire["seq"], reason=reason)

    # ------------------------------------------------------- observability
    def _telemetry_source(self) -> Dict[str, float]:
        """Flat ``control.*`` gauges next to the counter family: the
        knob CURRENT values and guard state the dashboards and the
        convergence SLO read."""
        with self._lock:
            now = self._now()
            out = {
                "b": self._knobs["b"].value,
                "depth": self._knobs["depth"].value,
                "merge": self._knobs["merge"].value,
                "damp_floor": self.damp_floor,
                "damp_free": self.damp_free,
                "wdamp_workers": float(len(self._wdamp)),
                "seq": float(self._seq),
                "frozen": float(sum(
                    1 for k in self._knobs.values()
                    if k.frozen_until is not None
                    and now < k.frozen_until)),
            }
        return out

    def decision_log(self) -> List[Dict[str, object]]:
        """Every committed knob change this run (bounded at 256): the
        decision trace bench.py's adaptive arm records in the BENCH
        payload."""
        with self._lock:
            return [dict(d) for d in self._decisions]

    def status(self) -> Dict[str, object]:
        """The ``control`` /api/status section (async-top/async-mon
        render it): current knob values vs configured, the last
        decision and its reason, and the oscillation-guard state."""
        with self._lock:
            now = self._now()
            configured = {"b": self.b_conf, "depth": self.depth_conf,
                          "merge": self.merge_conf, "wdamp": 0}
            knobs = {
                n: {
                    "value": k.value,
                    "configured": configured[n],
                    "changes": k.changes,
                    "frozen": bool(k.frozen_until is not None
                                   and now < k.frozen_until),
                }
                for n, k in self._knobs.items()
            }
            return {
                "enabled": True,
                "seq": self._seq,
                "knobs": knobs,
                "damp": {"floor": self.damp_floor,
                         "free": self.damp_free,
                         "wdamp": {str(w): f
                                   for w, f in self._wdamp.items()}},
                "last_decision": dict(self._last_decision)
                if self._last_decision else None,
                "totals": control_totals(),
            }
