"""Hot-standby shard replication: primary -> standby merge-batch streaming.

PR 8/9 recovery is restart-from-checkpoint behind lease expiry: a killed
PS shard costs lease-timeout + process relaunch + checkpoint replay of
availability -- the one remaining restart-shaped recovery path in a
system that otherwise degrades gracefully (ROADMAP item 5).  This module
closes it with classic primary-backup replication, shaped by ASAP's
observation (arXiv:1612.08608) that the bounded-staleness semantics the
training plane already ships are exactly what lets a slightly-behind
replica take over without violating correctness:

- each shard **primary** streams its accepted merge batches to a warm
  **standby** process over a new ``REPL_SYNC`` / ``REPL_APPEND`` wire
  plane: one full-state bootstrap (the checkpoint image -- model, merge
  clock, dedup window, trajectory), then every drained batch post-dedup,
  post-admission, with each item's ``(sid, seq)`` stamp, verdict, and
  staleness, stamped with the primary's merge clock (``pre``) and
  fencing epoch.  The standby applies batches through the SAME jitted
  apply kernel in the same order, so its state is the primary's state,
  a bounded number of merges behind (the replication lag);
- on lease expiry the :class:`~asyncframework_tpu.parallel.shardgroup.
  ShardGroup` controller **promotes** the standby (``PROMOTE``) under
  the slot's freshly-minted fencing epoch instead of relaunching a
  process: failover costs suspicion time plus one RPC, not checkpoint
  replay.  The PR 9 epoch machinery is the promotion-safety primitive --
  the deposed primary's post-promotion stream appends (and any worker
  op still routed at it) are ``REJECT_FENCED``, and because the
  standby's dedup window is REPLICATED, a worker replaying an
  applied-but-unACKed push against the promoted standby is re-answered
  from cache, never merged twice (dedup strictly precedes fencing,
  ``net/session.py`` contract);
- standbys double as **read replicas**: ``SUBSCRIBE`` (and therefore
  relaycast root fetches) are served from the standby's mirrored
  snapshot, with staleness priced by its replication lag -- surfaced as
  the ``ps.standby_lag`` time series and the default ``standby_lag``
  SLO rule.

Exactly-once across the failover, the full argument: an accepted push
exists in exactly one of three places when the primary dies -- (a)
applied+streamed: the standby holds both its effect and its dedup
record, so a replay re-ACKs from cache; (b) applied+unstreamed (still in
this sender's queue): its effect is LOST with the primary, exactly like
a push the taw filter dropped -- the worker's replay carries a stale
epoch stamp and is ``REJECT_FENCED``, so it is dropped, not re-applied
against diverged state; (c) never applied: the replay is fenced too and
the round is simply lost, the same loss as an abandoned fan-out round
today.  Nothing is ever applied twice.
"""

from __future__ import annotations

import io
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from asyncframework_tpu.net import frame as _frame
from asyncframework_tpu.net import shmring as _shmring

# ------------------------------------------------------------ repl totals
# Process-global replication counters (metrics/registry.py family
# "replication"): the primary-side stream and the standby-side appliers
# bump them in whichever process hosts them -- the same per-process
# discipline as every other family.
_totals_lock = threading.Lock()
_totals: Dict[str, int] = {}


def repl_totals() -> Dict[str, int]:
    """Replication counters: batches_streamed / items_streamed /
    syncs_sent (primary sender), appends_applied / append_items /
    sync_installs (standby applier), resyncs + resyncs_requested (gap
    recoveries, both ends), stream_reconnects, queue_overflows (slow
    standby: queue dropped, full re-sync scheduled), fenced_streams
    (a deposed primary's stream hit REJECT_FENCED and parked),
    promotions (standbys promoted to primary, standby-side)."""
    with _totals_lock:
        return dict(_totals)


def reset_repl_totals() -> None:
    """Zero the process-global replication counters (per-run isolation;
    see ``asyncframework_tpu.metrics.reset_totals``)."""
    with _totals_lock:
        _totals.clear()


def bump(key: str, n: int = 1) -> None:
    with _totals_lock:
        _totals[key] = _totals.get(key, 0) + n


class ReplicationStream:
    """The primary-side sender: a bounded queue of drained merge batches
    and one guarded thread that streams them to the shard's standby.

    Lifecycle: connect -> ``REPL_SYNC`` (full checkpoint image, captured
    under the model lock, serialized and sent OFF it) -> ``REPL_APPEND``
    per batch, each ACKed with the standby's applied clock (the lag
    signal).  Any transport fault, queue overflow, or standby-reported
    gap (``resync``) drops the queue and schedules a fresh sync -- the
    stream can always re-bootstrap, so a flapping standby costs
    bandwidth, never correctness.  A ``REJECT_FENCED`` reply means a
    successor epoch exists: THIS primary is deposed -- the stream parks
    permanently and the foreign epoch is folded back into the server
    (:meth:`ParameterServer.note_fenced_above`) so worker ops start
    bouncing too and clients re-resolve onto the promoted standby.

    :meth:`enqueue` is called under the PS model lock and is O(items)
    list work -- serialization and every byte of I/O happen on the
    sender thread.
    """

    def __init__(self, ps, host: str, port: int, queue_max: int = 256):
        self.ps = ps
        self.host, self.port = host, int(port)
        self.queue_max = max(2, int(queue_max))
        self._q: "deque" = deque()
        self._cv = threading.Condition()
        self._need_sync = True
        self._sock = None
        # shm-ring transport (net/shmring.py): a colocated standby's
        # REPL stream is the highest-rate colocated flow in the system,
        # so each fresh dial attempts the upgrade; any ring failure pins
        # this stream back to TCP (the existing reconnect machinery IS
        # the degrade path -- drop, resync, re-dial plain)
        self._shm_failed = False
        self.synced = False
        self.fenced = False
        #: the standby's last ACKed applied clock -- primary_clock minus
        #: this is the replication lag in merge units (ps.standby_lag)
        self.acked_clock = -1
        self.last_ack_mono: Optional[float] = None
        self._stop = threading.Event()
        from asyncframework_tpu.utils.threads import guarded

        self._thread = threading.Thread(
            target=guarded(self._run, "ps-repl-stream"),
            name=f"ps-repl-{self.host}:{self.port}", daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------ producer
    def enqueue(self, pre_clock: int, items: List[list],
                grads: List[np.ndarray], cal: List[float]) -> None:
        """One drained merge batch (caller holds the PS model lock).
        ``items`` = ``[wid, ts, accepted, sid, seq, ack, staleness,
        damp]`` per drained push in FIFO order (``damp`` = the
        delay-adaptive step factor the primary applied; the mirror must
        apply the identical one); ``grads`` = the accepted items'
        dense host gradients in the same order; ``cal`` = the primary's
        calibration triple.  A full queue (standby slow or dark) drops
        everything and schedules a re-sync -- bounded memory, and the
        sync carries the state the dropped batches would have built."""
        if self.fenced or self._stop.is_set():
            return
        with self._cv:
            if len(self._q) >= self.queue_max:
                self._q.clear()
                self._need_sync = True
                bump("queue_overflows")
            self._q.append((int(pre_clock), items, grads, list(cal)))
            self._cv.notify()

    def lag_versions(self) -> int:
        """Merge units the standby is behind (primary's clock minus the
        last ACKed applied clock; the whole clock while unsynced)."""
        if not self.synced:
            return int(self.ps._clock)
        return max(0, int(self.ps._clock) - int(self.acked_clock))

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._drop_sock()
        self._thread.join(timeout=5.0)

    # -------------------------------------------------------------- sender
    def _drop_sock(self) -> None:
        sock = self._sock
        self._sock = None
        if sock is not None:
            if isinstance(sock, _shmring.ShmSocket):
                # never resurrect a dropped ring blind: the next dial
                # stays on TCP so the reconnect loop converges
                self._shm_failed = True
            try:
                sock.close()
            except OSError:
                pass

    def _ensure_sock(self):
        if self._sock is None:
            sock = _frame.connect((self.host, self.port), timeout=5.0)
            sock.settimeout(15.0)
            if not self._shm_failed:
                sock, _ = _shmring.maybe_upgrade(sock)
            self._sock = sock
        return self._sock

    def _stamped(self, hdr: dict) -> dict:
        """The replication plane's ep-stamp choke point (pinned by
        async-lint next to PSClient._proc_hdr): every stream frame
        carries the primary's CURRENT fencing epoch, so a deposed
        incarnation's appends are exactly the stale-stamp shape the
        standby's admission rejects."""
        if self.ps.epoch:
            hdr["ep"] = self.ps.epoch
        return hdr

    def _pop(self, timeout_s: float):
        with self._cv:
            if not self._q and not self._stop.is_set():
                self._cv.wait(timeout=timeout_s)
            if not self._q:
                return None
            return self._q.popleft()

    def _reply(self, header: dict, payload: bytes):
        sock = self._ensure_sock()
        _frame.send_msg(sock, header, payload)
        reply, _ = _frame.recv_msg(sock)
        return reply

    def _note_reply(self, reply: dict) -> bool:
        """Fold one standby reply; False = stop processing this round."""
        op = reply.get("op")
        if op == "REJECT_FENCED":
            # a successor epoch exists for this range: we are the
            # deposed primary.  Park forever and tell the server so its
            # worker-facing admission starts bouncing stamped ops too --
            # that bounce is what drives clients to re-resolve onto the
            # promoted standby.
            self.fenced = True
            bump("fenced_streams")
            self.ps.note_fenced_above(int(reply.get("epoch", 0) or 0))
            return False
        if op == "ERR":
            if reply.get("resync"):
                self._need_sync = True
                self.synced = False
                bump("resyncs")
                return False
            raise ConnectionError(
                f"standby refused stream: {reply.get('msg')!r}")
        self.acked_clock = int(reply.get("clock", self.acked_clock))
        self.last_ack_mono = time.monotonic()
        return True

    def _send_sync(self) -> None:
        # drop whatever is queued FIRST: the image captured below
        # already contains those batches' effects, and replaying them
        # after it would read as duplicates (harmless, but wasteful)
        with self._cv:
            self._q.clear()
        with self.ps._lock:
            state = self.ps._checkpoint_state()
        buf = io.BytesIO()
        np.savez(buf, __meta__=json.dumps(state["meta"]),
                 **state["arrays"])
        reply = self._reply(self._stamped({"op": "REPL_SYNC"}),
                            buf.getvalue())
        if self._note_reply(reply):
            self._need_sync = False
            self.synced = True
            bump("syncs_sent")

    def _send_append(self, batch) -> None:
        pre_clock, items, grads, cal = batch
        hdr = self._stamped({"op": "REPL_APPEND", "pre": pre_clock,
                             "items": items, "cal": cal})
        payload = b"".join(
            np.ascontiguousarray(g, np.float32).tobytes() for g in grads
        )
        if self._note_reply(self._reply(hdr, payload)):
            bump("batches_streamed")
            bump("items_streamed", len(items))

    def _run(self) -> None:
        while not self._stop.is_set():
            if self.fenced:
                self._stop.wait(0.5)
                continue
            try:
                if self._need_sync:
                    self._send_sync()
                    continue
                batch = self._pop(0.2)
                if batch is None:
                    continue
                self._send_append(batch)
            except (ConnectionError, OSError):
                self._drop_sock()
                self.synced = False
                self._need_sync = True
                with self._cv:
                    self._q.clear()
                bump("stream_reconnects")
                self._stop.wait(0.3)
